"""Speculative decoding: draft-model proposals, target-model verify.

Greedy decode is HBM-bandwidth-bound — each token re-reads every target
weight byte. A small draft model proposes ``k`` tokens per round and
the target verifies all of them in ONE forward pass, so accepted
proposals amortize the target's weight traffic over multiple tokens.
With greedy acceptance the output is **token-identical** to running the
target alone (the property the tests pin): a proposal is accepted only
when it equals the target's own argmax at that position, and the first
mismatch is replaced by the target's choice — so every committed token
is exactly what target-only greedy would have produced.

XLA-first structure: one ``lax.while_loop`` whose carry holds both
models' caches, the committed-token buffer, and cursors; every round
runs a fixed-shape draft scan (k steps) and a fixed-shape target verify
forward (k+1 tokens). The variable acceptance count only moves cursors
(dynamic slices), never shapes. Rewind is free: caches are rewound by
moving the cursor back — stale entries beyond it are masked out by the
valid-length attention mask.

Single-sequence (batch 1): the serving engine batches across requests;
speculation accelerates within a sequence.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, forward, init_cache


class SpecResult(NamedTuple):
    tokens: jax.Array        # [max_new_tokens] committed tokens
    rounds: jax.Array        # verify rounds executed
    drafted: jax.Array       # proposals made
    accepted: jax.Array      # proposals accepted


def _set_cursor(cache, value):
    return [
        {"k": c["k"], "v": c["v"], "cursor": jnp.asarray(value, jnp.int32)}
        for c in cache
    ]


def speculative_generate(
    target_params: dict[str, Any],
    draft_params: dict[str, Any],
    prompt: jax.Array,  # [1, P]
    cfg: LlamaConfig,
    draft_cfg: LlamaConfig,
    max_new_tokens: int = 32,
    k: int = 4,
    cache_capacity: int | None = None,
) -> SpecResult:
    """Greedy speculative decode (see module docstring)."""
    b, prompt_len = prompt.shape
    if b != 1:
        raise ValueError("speculative_generate is single-sequence (batch 1)")
    # like greedy_generate: never exceed the RoPE table — out-of-range
    # positions would CLAMP in the freqs gather under jit, silently
    # breaking the token-identity guarantee instead of erroring
    cap = cache_capacity or min(
        min(cfg.max_seq_len, draft_cfg.max_seq_len),
        prompt_len + max_new_tokens + k + 1,
    )
    if prompt_len + max_new_tokens + k + 1 > cap:
        raise ValueError(
            f"prompt({prompt_len}) + new({max_new_tokens}) + k+1({k + 1}) "
            f"exceeds capacity {cap} (bounded by max_seq_len)"
        )

    # --- prefill both models; commit the target's first token ---------
    positions = jnp.arange(prompt_len)[None, :]
    t_cache = init_cache(cfg, 1, cap)
    t_logits, t_cache = forward(target_params, prompt, cfg, cache=t_cache,
                                positions=positions)
    first = jnp.argmax(t_logits[0, -1]).astype(jnp.int32)

    d_cache = init_cache(draft_cfg, 1, cap)
    _, d_cache = forward(draft_params, prompt, draft_cfg, cache=d_cache,
                         positions=positions)

    out = jnp.zeros((max_new_tokens,), jnp.int32)
    out = out.at[0].set(first)

    # carry: (t_cache, d_cache, out, n_out, n_ctx, rounds, drafted, accepted)
    # n_ctx = committed tokens IN the target cache (prompt + accepted);
    # the last committed token is NOT yet in either cache — it is fed
    # at the start of the next round (the standard lag-one invariant)
    init = (t_cache, d_cache, out, jnp.asarray(1, jnp.int32),
            jnp.asarray(prompt_len, jnp.int32),
            jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
            jnp.asarray(0, jnp.int32))

    def cond(carry):
        return carry[3] < max_new_tokens

    def body(carry):
        t_cache, d_cache, out, n_out, n_ctx, rounds, drafted, accepted = carry
        last = jax.lax.dynamic_index_in_dim(out, n_out - 1, keepdims=False)

        # --- draft: ingest `last`, then propose k greedy tokens -------
        d_cache = _set_cursor(d_cache, n_ctx)

        def d_step(c, _x):
            cache, tok = c  # the carry threads the real token chain
            lg, cache = forward(
                draft_params, tok[None, None], draft_cfg, cache=cache,
                positions=cache[0]["cursor"][None, None],
            )
            nxt = jnp.argmax(lg[0, -1]).astype(jnp.int32)
            return (cache, nxt), nxt

        (d_cache, _), proposals = jax.lax.scan(
            d_step, (d_cache, last), jnp.zeros((k,), jnp.int32),
        )
        # scan fed `last` then each proposal: proposals[i] is the draft's
        # token after last + proposals[:i]
        # (the scan xs are dummies; the carry threads the real token).
        # Ingest the final proposal too: when all k are accepted the
        # next round rewinds PAST it, and a stale cache entry there
        # would degrade the next round's proposals (never correctness —
        # the target verifies everything)
        _, d_cache = forward(
            draft_params, proposals[-1][None, None], draft_cfg,
            cache=d_cache, positions=d_cache[0]["cursor"][None, None],
        )

        # --- target: verify last + ALL k proposals in one pass ---------
        # (the logits at proposals[-1] supply the bonus token when
        # every proposal is accepted)
        t_cache = _set_cursor(t_cache, n_ctx)
        verify_tokens = jnp.concatenate([last[None], proposals])[None, :]  # [1, k+1]
        v_positions = n_ctx + jnp.arange(k + 1)[None, :]
        v_logits, t_cache = forward(target_params, verify_tokens, cfg,
                                    cache=t_cache, positions=v_positions)
        target_next = jnp.argmax(v_logits[0], axis=-1).astype(jnp.int32)  # [k+1]
        # target_next[i] = target's token after last+proposals[:i]

        # longest prefix where proposal matches the target's own choice
        matches = proposals == target_next[:k]
        m = jnp.sum(jnp.cumprod(matches.astype(jnp.int32))).astype(jnp.int32)
        # committed this round: proposals[:m] + the target's correction
        budget = max_new_tokens - n_out
        commit = jnp.minimum(m + 1, budget)
        round_tokens = jnp.concatenate([
            proposals, target_next[k][None],
        ])  # [k+1]; positions < m hold accepted proposals, m holds y_{m+1}
        round_tokens = jnp.where(
            jnp.arange(k + 1) == m, target_next[m], round_tokens
        )

        def write(i, o):
            return jax.lax.cond(
                i < commit,
                lambda oo: jax.lax.dynamic_update_index_in_dim(
                    oo, round_tokens[i], n_out + i, axis=0),
                lambda oo: oo,
                o,
            )

        out = jax.lax.fori_loop(0, k + 1, write, out)

        # caches advance by the verified run (last + proposals), but the
        # committed CONTEXT grows by the clamped commit (the extra
        # verified tokens are rewound by cursor on the next round),
        # preserving n_ctx == prompt + committed - 1
        n_ctx = n_ctx + commit
        return (t_cache, d_cache, out, n_out + commit, n_ctx,
                rounds + 1, drafted + k, accepted + jnp.minimum(m, budget))

    _, _, out, _, _, rounds, drafted, accepted = jax.lax.while_loop(
        cond, body, init
    )
    return SpecResult(out, rounds, drafted, accepted)
