"""Model families (functional JAX, sharding-rule driven)."""

from . import embedder, moe
from .llama import (
    LlamaConfig,
    forward,
    greedy_generate,
    init_cache,
    init_params,
    llama3_1b,
    llama3_8b,
    llama_tiny,
)
from .moe import MoEConfig, mixtral_8x7b, moe_tiny
from .speculative import SpecResult, speculative_generate

__all__ = [
    "SpecResult",
    "speculative_generate",
    "LlamaConfig",
    "MoEConfig",
    "embedder",
    "forward",
    "greedy_generate",
    "init_cache",
    "init_params",
    "llama3_1b",
    "llama3_8b",
    "llama_tiny",
    "mixtral_8x7b",
    "moe",
    "moe_tiny",
]
