"""Model families (functional JAX, sharding-rule driven)."""

from .llama import (
    LlamaConfig,
    forward,
    greedy_generate,
    init_cache,
    init_params,
    llama3_1b,
    llama3_8b,
    llama_tiny,
)

__all__ = [
    "LlamaConfig",
    "forward",
    "greedy_generate",
    "init_cache",
    "init_params",
    "llama3_1b",
    "llama3_8b",
    "llama_tiny",
]
