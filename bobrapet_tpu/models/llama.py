"""Llama-3 model family: functional JAX implementation.

The flagship engram model (BASELINE configs 2-5 run Llama-3-8B
inference). Pure functional style — params are a pytree dict, forward is
jit/pjit-friendly (static shapes, no Python control flow on traced
values), sharding is applied by :mod:`bobrapet_tpu.parallel.sharding`
rules, long context rides :mod:`bobrapet_tpu.parallel.ring_attention`.

Weights use bfloat16 by default (MXU-native); accumulation in fp32.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

#: one-hot embedding lookups on the sharded training path are capped by
#: vocab size — the [B, S, V] one-hot beats the gather's reshard only
#: while it stays small relative to activations (V ~ tens of dims)
ONEHOT_EMBED_MAX_VOCAB = 16384

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.rmsnorm import rmsnorm_reference
from .quant import matmul as _mm
from ..ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    #: Llama-3.1 long-context RoPE remap: (factor, low_freq_factor,
    #: high_freq_factor, original_max_position_embeddings) or None
    rope_scaling: Optional[tuple] = None
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def param_count(self) -> int:
        emb = self.vocab_size * self.dim
        attn = self.dim * self.dim + 2 * self.dim * (self.n_kv_heads * self.head_dim) + self.dim * self.dim
        mlp = 3 * self.dim * self.ffn_hidden
        norms = 2 * self.dim
        out = 0 if self.tie_embeddings else self.vocab_size * self.dim
        return emb + self.n_layers * (attn + mlp + norms) + self.dim + out


def llama3_8b() -> LlamaConfig:
    """Llama-3-8B (the BASELINE flagship)."""
    return LlamaConfig()


def llama3_1b() -> LlamaConfig:
    """A ~1B config for single-chip v5e benchmarking headroom."""
    return LlamaConfig(
        dim=2048, n_layers=16, n_heads=16, n_kv_heads=8, ffn_hidden=5632,
        max_seq_len=4096,
    )


def llama_tiny(vocab_size: int = 512, max_seq_len: int = 256) -> LlamaConfig:
    """Tiny config for tests and the graft compile check."""
    return LlamaConfig(
        vocab_size=vocab_size,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        max_seq_len=max_seq_len,
        dtype=jnp.float32,
    )


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: LlamaConfig) -> dict[str, Any]:
    """Initialize a parameter pytree.

    Layout (names chosen to map 1:1 onto sharding rules):
      embed.weight [V, D]
      layers.<i>.{attn_norm,mlp_norm}.weight [D]
      layers.<i>.attn.{wq [D, Hq*Dh], wk [D, Hkv*Dh], wv [D, Hkv*Dh], wo [Hq*Dh, D]}
      layers.<i>.mlp.{w_gate [D, F], w_up [D, F], w_down [F, D]}
      final_norm.weight [D]
      lm_head.weight [D, V] (absent when tie_embeddings)
    """
    n_weights = 2 + cfg.n_layers * 7
    keys = iter(jax.random.split(key, n_weights))
    std = 1.0 / math.sqrt(cfg.dim)

    def dense(k, shape, scale=std):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(cfg.dtype)

    params: dict[str, Any] = {
        "embed": {"weight": dense(next(keys), (cfg.vocab_size, cfg.dim), 1.0 / math.sqrt(cfg.dim))},
        "layers": [],
        "final_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
    }
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    for _ in range(cfg.n_layers):
        layer = {
            "attn_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
            "attn": {
                "wq": dense(next(keys), (cfg.dim, cfg.dim)),
                "wk": dense(next(keys), (cfg.dim, kv_dim)),
                "wv": dense(next(keys), (cfg.dim, kv_dim)),
                "wo": dense(next(keys), (cfg.dim, cfg.dim), std / math.sqrt(2 * cfg.n_layers)),
            },
            "mlp_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
            "mlp": {
                "w_gate": dense(next(keys), (cfg.dim, cfg.ffn_hidden)),
                "w_up": dense(next(keys), (cfg.dim, cfg.ffn_hidden)),
                "w_down": dense(next(keys), (cfg.ffn_hidden, cfg.dim), std / math.sqrt(2 * cfg.n_layers)),
            },
        }
        params["layers"].append(layer)
    if not cfg.tie_embeddings:
        params["lm_head"] = {"weight": dense(next(keys), (cfg.dim, cfg.vocab_size))}
    return params


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _lora_mm(h: jax.Array, w: Any, lora_layer, site: str,
             lora_scale: float) -> jax.Array:
    """Base matmul (+ optional LoRA delta: scale * (h @ A) @ B)."""
    out = _mm(h, w)
    if lora_layer is not None and site in lora_layer:
        from .lora import lora_delta

        out = out + lora_delta(h, lora_layer[site], lora_scale)
    return out


def _attention_block(
    layer: dict[str, Any],
    x: jax.Array,
    freqs: jax.Array,
    cfg: LlamaConfig,
    cache: Optional[dict[str, jax.Array]],
    positions: Optional[jax.Array],
    attn_fn,
    lora_layer=None,
    lora_scale: float = 1.0,
) -> tuple[jax.Array, Optional[dict[str, jax.Array]]]:
    b, s, _ = x.shape
    h = rmsnorm_reference(x, layer["attn_norm"]["weight"], cfg.norm_eps)
    q = _lora_mm(h, layer["attn"]["wq"], lora_layer, "wq", lora_scale).reshape(
        b, s, cfg.n_heads, cfg.head_dim)
    k = _lora_mm(h, layer["attn"]["wk"], lora_layer, "wk", lora_scale).reshape(
        b, s, cfg.n_kv_heads, cfg.head_dim)
    v = _lora_mm(h, layer["attn"]["wv"], lora_layer, "wv", lora_scale).reshape(
        b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, freqs, positions)
    k = apply_rope(k, freqs, positions)

    new_cache = None
    if cache is not None:
        # decode: write k/v at the cache cursor, attend over the prefix
        cursor = cache["cursor"]
        ck = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype), (0, cursor, 0, 0))
        cv = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype), (0, cursor, 0, 0))
        new_cache = {"k": ck, "v": cv, "cursor": cursor + s}
        k_all, v_all = ck, cv
        out = _cached_attention(q, k_all, v_all, cursor + s, cfg)
    else:
        out = attn_fn(q, k, v)
    out = out.reshape(b, s, cfg.dim)
    return x + _lora_mm(out, layer["attn"]["wo"], lora_layer, "wo",
                        lora_scale), new_cache


def _cached_attention(q, k_all, v_all, valid_len, cfg: LlamaConfig) -> jax.Array:
    """Decode attention over a cache with a traced valid length."""
    b, s, hq, d = q.shape
    cap = k_all.shape[1]
    group = hq // cfg.n_kv_heads
    scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * scale
    kf = jnp.repeat(k_all.astype(jnp.float32), group, axis=2)
    vf = jnp.repeat(v_all.astype(jnp.float32), group, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf)
    q_pos = valid_len - s + jnp.arange(s)
    k_pos = jnp.arange(cap)
    mask = (k_pos[None, :] <= q_pos[:, None]) & (k_pos[None, :] < valid_len)
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vf).astype(q.dtype)


def _mlp_block(layer: dict[str, Any], x: jax.Array, cfg: LlamaConfig,
               lora_layer=None, lora_scale: float = 1.0) -> jax.Array:
    h = rmsnorm_reference(x, layer["mlp_norm"]["weight"], cfg.norm_eps)
    gate = jax.nn.silu(
        _lora_mm(h, layer["mlp"]["w_gate"], lora_layer, "w_gate",
                 lora_scale).astype(jnp.float32))
    up = _lora_mm(h, layer["mlp"]["w_up"], lora_layer, "w_up",
                  lora_scale).astype(jnp.float32)
    return x + _lora_mm((gate * up).astype(cfg.dtype),
                        layer["mlp"]["w_down"], lora_layer, "w_down",
                        lora_scale)


def forward(
    params: dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    cache: Optional[list[dict[str, jax.Array]]] = None,
    positions: Optional[jax.Array] = None,
    attn_fn=None,
    lora: Optional[dict[str, Any]] = None,
    lora_scale: float = 1.0,
    act_sharding=None,
) -> tuple[jax.Array, Optional[list[dict[str, jax.Array]]]]:
    """Token ids [B, S] -> logits [B, S, V] (+ updated cache).

    ``attn_fn`` overrides the attention implementation (ring attention
    plugs in here for sequence-parallel long context). ``lora`` is ONE
    adapter's tree (models/lora.py); its rank-r deltas ride every site
    it carries. ``act_sharding`` (a NamedSharding for [B, S, D]
    activations) pins the residual stream at every layer boundary —
    without the pin, SPMD propagation on the BACKWARD pass is free to
    invent layouts for the residual cotangents (observed: batch sharded
    over model x seq), whose reconciliation at the attention shard_map
    boundary forces XLA involuntary full rematerialization.
    """
    if attn_fn is None:
        attn_fn = lambda q, k, v: attention(q, k, v, causal=True)  # noqa: E731
    constrain = (
        (lambda t: jax.lax.with_sharding_constraint(t, act_sharding))
        if act_sharding is not None
        else (lambda t: t)
    )
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                             cfg.rope_theta, cfg.rope_scaling)
    if act_sharding is not None and cfg.vocab_size <= ONEHOT_EMBED_MAX_VOCAB:
        # sharded training path: one-hot matmul instead of gather — a
        # gather from the (vocab=model, dim=fsdp)-sharded table
        # partitions into a layout whose transition to the pinned
        # activation sharding forces an involuntary full remat; the
        # matmul contracts over the sharded vocab dim cleanly (psum
        # over model) and rides the MXU. Capped by vocab size: the
        # [B, S, V] one-hot is only cheap for small vocabularies —
        # above the cap the gather (and its possible reshard) costs
        # less than materializing the one-hot.
        onehot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
        x = constrain(onehot @ params["embed"]["weight"].astype(cfg.dtype))
    elif act_sharding is not None:
        x = constrain(params["embed"]["weight"][tokens].astype(cfg.dtype))
    else:
        x = params["embed"]["weight"][tokens].astype(cfg.dtype)
    new_caches: Optional[list[dict[str, jax.Array]]] = [] if cache is not None else None
    for i, layer in enumerate(params["layers"]):
        layer_cache = cache[i] if cache is not None else None
        lora_layer = lora["layers"][i] if lora is not None else None
        x, updated = _attention_block(layer, x, freqs, cfg, layer_cache,
                                      positions, attn_fn, lora_layer,
                                      lora_scale)
        x = constrain(x)
        if new_caches is not None:
            new_caches.append(updated)
        x = constrain(_mlp_block(layer, x, cfg, lora_layer, lora_scale))
    x = rmsnorm_reference(x, params["final_norm"]["weight"], cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["weight"].T.astype(cfg.dtype)
    else:
        logits = _mm(x, params["lm_head"]["weight"])
    return logits.astype(jnp.float32), new_caches


# ---------------------------------------------------------------------------
# KV cache + generation
# ---------------------------------------------------------------------------


def init_cache(cfg: LlamaConfig, batch: int, capacity: Optional[int] = None) -> list[dict[str, jax.Array]]:
    cap = capacity or cfg.max_seq_len
    return [
        {
            "k": jnp.zeros((batch, cap, cfg.n_kv_heads, cfg.head_dim), cfg.dtype),
            "v": jnp.zeros((batch, cap, cfg.n_kv_heads, cfg.head_dim), cfg.dtype),
            "cursor": jnp.array(0, jnp.int32),
        }
        for _ in range(cfg.n_layers)
    ]


def greedy_generate(
    params: dict[str, Any],
    prompt: jax.Array,
    cfg: LlamaConfig,
    max_new_tokens: int = 32,
    cache_capacity: Optional[int] = None,
    forward_fn=None,
) -> jax.Array:
    """Greedy decode with a KV cache; prefill + lax.scan decode loop
    (compiler-friendly: fixed shapes, no Python loop per token).

    ``forward_fn(params, tokens, cfg, cache, positions) -> (logits,
    cache)`` swaps the model family (the MoE family reuses this exact
    loop rather than copying it)."""
    if forward_fn is None:
        forward_fn = lambda p, t, c, cache, pos: forward(  # noqa: E731
            p, t, c, cache=cache, positions=pos)
    b, prompt_len = prompt.shape
    cap = cache_capacity or min(cfg.max_seq_len, prompt_len + max_new_tokens)
    if prompt_len + max_new_tokens > cap:
        # dynamic_update_slice clamps out-of-range writes, which would
        # silently corrupt the last cache slot instead of erroring
        raise ValueError(
            f"prompt_len({prompt_len}) + max_new_tokens({max_new_tokens}) "
            f"exceeds cache capacity {cap}"
        )
    cache = init_cache(cfg, b, cap)

    positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
    logits, cache = forward_fn(params, prompt, cfg, cache, positions)
    next_tok = jnp.argmax(logits[:, -1:, :], axis=-1)

    def step(carry, _):
        cache, tok, pos = carry
        logits, cache = forward_fn(params, tok, cfg, cache, pos[:, None])
        nxt = jnp.argmax(logits[:, -1:, :], axis=-1)
        return (cache, nxt, pos + 1), tok[:, 0]

    pos0 = jnp.full((b,), prompt_len, jnp.int32)
    (_, _, _), toks = jax.lax.scan(
        step, (cache, next_tok, pos0), None, length=max_new_tokens
    )
    return jnp.swapaxes(toks, 0, 1)  # [B, max_new_tokens]
