"""HuggingFace Llama checkpoint -> bobrapet_tpu param tree.

Users arrive with real weights (HF hub format); this maps
``LlamaForCausalLM`` state dicts onto :mod:`bobrapet_tpu.models.llama`
exactly:

- both use the split-half (rotate-half) RoPE convention, so projections
  transfer with a plain TRANSPOSE (HF stores [out, in]; this tree
  stores [in, out]) — no head permutation games;
- ``tie_word_embeddings`` maps to ``tie_embeddings`` (no lm_head leaf);
- the conversion is validated against transformers' own forward pass in
  tests (logit-level agreement), so the model math — not just the
  shapes — is pinned to the canonical implementation.

The converted tree drops straight into every downstream path: sharding
rules, int8 quantization, the serving engine, speculative decoding.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig


def config_from_hf(hf_config: Any) -> LlamaConfig:
    """transformers ``LlamaConfig`` (object or dict) -> LlamaConfig."""
    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    scaling = get("rope_scaling")
    rope_scaling = None
    if scaling:
        rope_type = scaling.get("rope_type") or scaling.get("type")
        if rope_type != "llama3":
            # only the published llama3 remap is implemented; converting
            # linear/dynamic/yarn checkpoints would SILENTLY break the
            # logit-level agreement this module promises
            raise ValueError(
                f"rope_scaling type {rope_type!r} is not supported "
                "(supported: llama3)"
            )
        rope_scaling = (
            float(scaling["factor"]),
            float(scaling.get("low_freq_factor", 1.0)),
            float(scaling.get("high_freq_factor", 4.0)),
            int(scaling.get("original_max_position_embeddings", 8192)),
        )
    if get("attention_bias") or get("mlp_bias"):
        raise ValueError(
            "bias-bearing Llama variants are not supported (the bias "
            "tensors would be silently dropped)"
        )
    return LlamaConfig(
        vocab_size=int(get("vocab_size")),
        dim=int(get("hidden_size")),
        n_layers=int(get("num_hidden_layers")),
        n_heads=int(get("num_attention_heads")),
        n_kv_heads=int(get("num_key_value_heads") or get("num_attention_heads")),
        ffn_hidden=int(get("intermediate_size")),
        max_seq_len=int(get("max_position_embeddings")),
        rope_theta=float(get("rope_theta") or 10_000.0),
        rope_scaling=rope_scaling,
        norm_eps=float(get("rms_norm_eps") or 1e-5),
        tie_embeddings=bool(get("tie_word_embeddings") or False),
    )


def _to_np(t: Any) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t)


def params_from_hf_state_dict(
    state_dict: Mapping[str, Any],
    cfg: LlamaConfig,
    dtype: Optional[Any] = None,
) -> dict[str, Any]:
    """HF ``LlamaForCausalLM`` state dict -> param tree (llama.py
    layout). Raises KeyError naming the first missing weight."""
    dtype = dtype or cfg.dtype
    sd = state_dict

    def w(name: str, transpose: bool = False) -> jnp.ndarray:
        if name not in sd:
            raise KeyError(f"HF state dict missing {name!r}")
        arr = _to_np(sd[name])
        if transpose:
            arr = arr.T
        return jnp.asarray(arr, dtype)

    params: dict[str, Any] = {
        "embed": {"weight": w("model.embed_tokens.weight")},
        "layers": [],
        "final_norm": {"weight": w("model.norm.weight")},
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        params["layers"].append({
            "attn_norm": {"weight": w(p + "input_layernorm.weight")},
            "attn": {
                "wq": w(p + "self_attn.q_proj.weight", transpose=True),
                "wk": w(p + "self_attn.k_proj.weight", transpose=True),
                "wv": w(p + "self_attn.v_proj.weight", transpose=True),
                "wo": w(p + "self_attn.o_proj.weight", transpose=True),
            },
            "mlp_norm": {"weight": w(p + "post_attention_layernorm.weight")},
            "mlp": {
                "w_gate": w(p + "mlp.gate_proj.weight", transpose=True),
                "w_up": w(p + "mlp.up_proj.weight", transpose=True),
                "w_down": w(p + "mlp.down_proj.weight", transpose=True),
            },
        })
    if not cfg.tie_embeddings:
        params["lm_head"] = {"weight": w("lm_head.weight", transpose=True)}
    return params


def load_hf(model_or_path: Any, dtype: Optional[Any] = None
            ) -> tuple[dict[str, Any], LlamaConfig]:
    """Convenience: a transformers model instance OR a local/hub path
    -> (params, cfg). Requires the ``transformers`` package."""
    model = model_or_path
    if isinstance(model_or_path, str):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(model_or_path)
    cfg = config_from_hf(model.config)
    params = params_from_hf_state_dict(model.state_dict(), cfg, dtype)
    return params, cfg


# ---------------------------------------------------------------------------
# Mixtral (sparse MoE)
# ---------------------------------------------------------------------------


def moe_config_from_hf(hf_config: Any,
                       capacity_factor: Optional[float] = None):
    """transformers ``MixtralConfig`` -> MoEConfig.

    HF Mixtral routes every token to its top-k experts with NO capacity
    limit; this implementation uses static per-expert capacity (tokens
    over budget drop). For faithful conversion the default capacity
    factor is ``n_experts`` — enough for the worst case (every token
    picking the same expert), so nothing ever drops and logits agree
    with transformers exactly. Serving deployments can pass a tighter
    ``capacity_factor`` to trade exactness at the margin for memory.
    """
    from .moe import MoEConfig

    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    n_experts = int(get("num_local_experts"))
    return MoEConfig(
        vocab_size=int(get("vocab_size")),
        dim=int(get("hidden_size")),
        n_layers=int(get("num_hidden_layers")),
        n_heads=int(get("num_attention_heads")),
        n_kv_heads=int(get("num_key_value_heads") or get("num_attention_heads")),
        ffn_hidden=int(get("intermediate_size")),
        n_experts=n_experts,
        experts_per_token=int(get("num_experts_per_tok") or 2),
        capacity_factor=(float(capacity_factor)
                         if capacity_factor is not None else float(n_experts)),
        max_seq_len=int(get("max_position_embeddings")),
        rope_theta=float(get("rope_theta") or 1_000_000.0),
        norm_eps=float(get("rms_norm_eps") or 1e-5),
    )


def moe_params_from_hf_state_dict(
    state_dict: Mapping[str, Any],
    cfg: Any,
    dtype: Optional[Any] = None,
) -> dict[str, Any]:
    """HF ``MixtralForCausalLM`` state dict -> moe.py param tree
    (expert weights stacked on a leading E axis; HF w1 = gate,
    w3 = up, w2 = down)."""
    dtype = dtype or cfg.dtype
    sd = state_dict

    def w(name: str, transpose: bool = False) -> jnp.ndarray:
        if name not in sd:
            raise KeyError(f"HF state dict missing {name!r}")
        arr = _to_np(sd[name])
        if transpose:
            arr = arr.T
        return jnp.asarray(arr, dtype)

    def experts(layer: int, which: str, transpose: bool) -> jnp.ndarray:
        return jnp.stack([
            w(f"model.layers.{layer}.block_sparse_moe.experts.{j}."
              f"{which}.weight", transpose=transpose)
            for j in range(cfg.n_experts)
        ])

    params: dict[str, Any] = {
        "embed": {"weight": w("model.embed_tokens.weight")},
        "layers": [],
        "final_norm": {"weight": w("model.norm.weight")},
        "lm_head": {"weight": w("lm_head.weight", transpose=True)},
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        params["layers"].append({
            "attn_norm": {"weight": w(p + "input_layernorm.weight")},
            "attn": {
                "wq": w(p + "self_attn.q_proj.weight", transpose=True),
                "wk": w(p + "self_attn.k_proj.weight", transpose=True),
                "wv": w(p + "self_attn.v_proj.weight", transpose=True),
                "wo": w(p + "self_attn.o_proj.weight", transpose=True),
            },
            "mlp_norm": {"weight": w(p + "post_attention_layernorm.weight")},
            "moe": {
                "w_router": w(p + "block_sparse_moe.gate.weight",
                              transpose=True),
                "w_gate": experts(i, "w1", transpose=True),
                "w_up": experts(i, "w3", transpose=True),
                "w_down": experts(i, "w2", transpose=True),
            },
        })
    return params


def load_hf_mixtral(model_or_path: Any, dtype: Optional[Any] = None,
                    capacity_factor: Optional[float] = None):
    """Convenience: transformers Mixtral model or path -> (params, cfg)."""
    model = model_or_path
    if isinstance(model_or_path, str):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(model_or_path)
    cfg = moe_config_from_hf(model.config, capacity_factor)
    params = moe_params_from_hf_state_dict(model.state_dict(), cfg, dtype)
    return params, cfg
