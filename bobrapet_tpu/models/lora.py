"""LoRA adapters: low-rank deltas over the Llama weight sites.

Multi-tenant serving wants many fine-tunes over ONE resident base
model: adapters are rank-r factors (A [in, r], B [r, out]) whose delta
``scale * (x @ A) @ B`` adds to each target matmul — the base weights
(bf16 or int8) are never touched, so hundreds of adapters cost
megabytes while the base costs gigabytes.

Layout mirrors the param tree: ``{"layers": [{site: {"a", "b"}}]}``
with sites among wq/wk/wv/wo/w_gate/w_up/w_down. A STACKED tree adds a
leading adapter axis to every leaf — the serving engine gathers each
slot's adapter inside the fused decode step, so one compiled graph
serves any adapter mix. Index 0 is the reserved BASE adapter (zeros):
requests without an adapter select it and get exactly the base model.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

ATTN_SITES = ("wq", "wk", "wv", "wo")
MLP_SITES = ("w_gate", "w_up", "w_down")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    #: which matmul sites carry adapters (attention-only is the usual
    #: quality/size sweet spot)
    sites: tuple[str, ...] = ("wq", "wv")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _site_dims(cfg, site: str) -> tuple[int, int]:
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    return {
        "wq": (cfg.dim, cfg.dim),
        "wk": (cfg.dim, kv_dim),
        "wv": (cfg.dim, kv_dim),
        "wo": (cfg.dim, cfg.dim),
        "w_gate": (cfg.dim, cfg.ffn_hidden),
        "w_up": (cfg.dim, cfg.ffn_hidden),
        "w_down": (cfg.ffn_hidden, cfg.dim),
    }[site]


def init_lora(key: jax.Array, cfg, lcfg: LoRAConfig) -> dict[str, Any]:
    """One adapter: A ~ N(0, 1/r), B = 0 (standard init: the delta
    starts at zero, training moves it)."""
    layers = []
    keys = iter(jax.random.split(key, cfg.n_layers * len(lcfg.sites)))
    for _ in range(cfg.n_layers):
        layer: dict[str, Any] = {}
        for site in lcfg.sites:
            d_in, d_out = _site_dims(cfg, site)
            layer[site] = {
                "a": (jax.random.normal(next(keys), (d_in, lcfg.rank),
                                        jnp.float32)
                      / math.sqrt(lcfg.rank)).astype(cfg.dtype),
                "b": jnp.zeros((lcfg.rank, d_out), cfg.dtype),
            }
        layers.append(layer)
    return {"layers": layers}


def zero_lora(cfg, lcfg: LoRAConfig) -> dict[str, Any]:
    """The identity adapter (all-zero delta) — stack index 0."""
    layers = []
    for _ in range(cfg.n_layers):
        layer: dict[str, Any] = {}
        for site in lcfg.sites:
            d_in, d_out = _site_dims(cfg, site)
            layer[site] = {
                "a": jnp.zeros((d_in, lcfg.rank), cfg.dtype),
                "b": jnp.zeros((lcfg.rank, d_out), cfg.dtype),
            }
        layers.append(layer)
    return {"layers": layers}


def stack_adapters(adapters: Sequence[dict[str, Any]]) -> dict[str, Any]:
    """[adapter trees] -> one tree with a leading adapter axis per leaf
    (adapter 0 should be :func:`zero_lora` — the engine maps "no
    adapter" there)."""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *adapters)


def select_adapter(stacked: dict[str, Any], index) -> dict[str, Any]:
    """One adapter's tree out of a stack (gather on the leading axis —
    jit-safe with a traced index)."""
    return jax.tree_util.tree_map(lambda leaf: leaf[index], stacked)


def lora_delta(x: jax.Array, site_lora: Optional[dict[str, Any]],
               scale: float) -> jax.Array:
    """``scale * (x @ A) @ B`` — rank-r bottleneck, fused by XLA into
    two skinny matmuls; returns 0.0 when the site has no adapter."""
    if site_lora is None:
        return jnp.zeros((), x.dtype)
    a = site_lora["a"].astype(x.dtype)
    b = site_lora["b"].astype(x.dtype)
    return ((x @ a) @ b) * jnp.asarray(scale, x.dtype)


def merge_lora(params: dict[str, Any], adapter: dict[str, Any],
               scale: float) -> dict[str, Any]:
    """Materialize base + delta into plain weights (reference baseline
    for tests; production serving never does this — the whole point is
    NOT materializing per-tenant weight copies)."""
    # tree_map identity rebuilds the container dicts; leaves (immutable
    # arrays) are shared — all the site reassignment below needs
    out = jax.tree_util.tree_map(lambda x: x, params)
    for layer, lora_layer in zip(out["layers"], adapter["layers"]):
        for site, ab in lora_layer.items():
            tgt = layer["attn"] if site in ATTN_SITES else layer["mlp"]
            w = tgt[site]
            delta = (ab["a"].astype(jnp.float32)
                     @ ab["b"].astype(jnp.float32)) * scale
            tgt[site] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    return out
