"""Int8 weight-only quantization for decode.

Greedy decode is HBM-bandwidth-bound: every generated token re-reads
every weight byte (BASELINE.md roofline). Storing matmul weights as
int8 with per-output-channel scales halves the bytes vs bf16 — Llama-8B
(~16 GB bf16) fits one 16 GB v5e chip — and raises the bandwidth
roofline ~2x. Under jit the int8 tree is the carried state: XLA fuses
the dequantize (convert + scale multiply) into each matmul's operand
read, so the bf16 view is transient, never resident.

Weight-only symmetric scheme (the standard inference recipe; no
reference counterpart — the reference orchestrates containers and owns
no model code):

- every 2-D float matmul weight -> ``{"q": int8, "scale": f32[out]}``
  (per-output-channel absmax scaling, error independent per column)
- 1-D norm gains stay exact; the embedding table stays bf16 (it is a
  gather, not a matmul, and shares storage with the tied lm head)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

#: param-tree paths never quantized (gather tables + tied heads)
_SKIP_NAMES = {"embed"}


def is_quantized(leaf: Any) -> bool:
    # structural marker (jit-friendly: arrays only, no static leaves):
    # exactly {"q": int8, "scale": <original dtype>}
    return (
        isinstance(leaf, dict)
        and set(leaf) == {"q", "scale"}
        and getattr(leaf["q"], "dtype", None) == jnp.int8
    )


_is_quantized = is_quantized


def quantize_array(w: jax.Array) -> dict[str, Any]:
    """One matmul weight [in, out] -> int8 + per-out-column scale.
    The scale carries the original dtype so the dequantized view is a
    drop-in for the source weight.

    The scale is cast to the STORAGE dtype first and that rounded scale
    is what divides ``w`` — quantize and dequantize then agree exactly,
    instead of rounding with an f32 scale the stored bf16 scale can't
    represent (~3 decimal digits)."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(w.dtype)
    # guard: a tiny absmax can underflow to 0 in bf16 — quantizing with
    # it would divide by zero; scale 1 maps such columns to q=0 exactly
    scale = jnp.where(scale > 0, scale, jnp.ones_like(scale))
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale.astype(jnp.float32)), -127, 127)
    return {"q": q.astype(jnp.int8), "scale": scale}


def dequantize_array(leaf: dict[str, Any]) -> jax.Array:
    scale = leaf["scale"]
    return (leaf["q"].astype(jnp.float32) * scale.astype(jnp.float32)).astype(
        scale.dtype
    )


def matmul(x: jax.Array, w: Any) -> jax.Array:
    """``x @ w`` where ``w`` may be a plain array OR an int8 leaf.

    For the quantized case the per-output-column scales factor out of
    the contraction: ``x @ (q * s_col) == (x @ q) * s_col`` — the bf16
    weight is NEVER materialized, not even transiently, so a decode
    loop (lax.scan) carries only int8 weight bytes in HBM. This is the
    hook the model forward uses at every weight site; it makes a
    quantized tree a drop-in for the bf16 one."""
    if _is_quantized(w):
        out = x @ w["q"].astype(x.dtype)
        return out * w["scale"].astype(x.dtype)
    return x @ w


def quantize_params(params: Any) -> Any:
    """Walk a param tree; every 2-D float weight outside the skip list
    becomes an int8 leaf. Structure is otherwise preserved, so
    :func:`dequantize_params` yields a drop-in tree for ``forward``."""

    def walk(node: Any, name: str) -> Any:
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v, name) for v in node]
        if (
            isinstance(node, jax.Array)
            and node.ndim == 2
            and jnp.issubdtype(node.dtype, jnp.floating)
            and name not in _SKIP_NAMES
        ):
            return quantize_array(node)
        return node

    out = {}
    for key, value in params.items():
        out[key] = value if key in _SKIP_NAMES else walk(value, key)
    return out


def dequantize_params(qparams: Any) -> Any:
    """The bf16 view of an int8 tree — call INSIDE jit so XLA fuses the
    dequantize into each weight's consumer and the view stays
    transient."""

    def walk(node: Any) -> Any:
        if _is_quantized(node):
            return dequantize_array(node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    return walk(qparams)


def tree_bytes(params: Any) -> int:
    """Total array storage of a (possibly quantized) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


def init_quantized_params(key: jax.Array, cfg: Any) -> Any:
    """Initialize an int8 tree DIRECTLY — same structure
    ``quantize_params(llama.init_params(...))`` yields, without ever
    materializing the bf16 tree.

    Motivation: the 8b bench leg timed out in round 5 — host-initializing
    16 GB of bf16 then quantizing it took longer than the whole window.
    For throughput benchmarking the weight *values* are irrelevant (the
    decode loop reads every byte either way), so int8 leaves are drawn
    uniformly and scales set to a plausible absmax/127. Shapes and
    skip-list behavior follow llama.init_params exactly
    (models/llama.py:95).
    """
    import math

    counter = [0]

    def q(shape) -> dict[str, Any]:
        counter[0] += 1
        k = jax.random.fold_in(key, counter[0])
        return {
            "q": jax.random.randint(k, shape, -127, 128, jnp.int8),
            "scale": jnp.full(
                (shape[-1],), 1.0 / (127.0 * math.sqrt(shape[0])), cfg.dtype
            ),
        }

    def dense_bf16(shape, scale=1.0) -> jax.Array:
        counter[0] += 1
        k = jax.random.fold_in(key, counter[0])
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(
            cfg.dtype
        )

    kv_dim = cfg.n_kv_heads * cfg.head_dim
    params: dict[str, Any] = {
        # embed stays bf16 (gather table, _SKIP_NAMES)
        "embed": {"weight": dense_bf16(
            (cfg.vocab_size, cfg.dim), 1.0 / math.sqrt(cfg.dim)
        )},
        "layers": [],
        "final_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
    }
    for _ in range(cfg.n_layers):
        params["layers"].append({
            "attn_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
            "attn": {
                "wq": q((cfg.dim, cfg.dim)),
                "wk": q((cfg.dim, kv_dim)),
                "wv": q((cfg.dim, kv_dim)),
                "wo": q((cfg.dim, cfg.dim)),
            },
            "mlp_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
            "mlp": {
                "w_gate": q((cfg.dim, cfg.ffn_hidden)),
                "w_up": q((cfg.dim, cfg.ffn_hidden)),
                "w_down": q((cfg.ffn_hidden, cfg.dim)),
            },
        })
    if not cfg.tie_embeddings:
        params["lm_head"] = {"weight": q((cfg.dim, cfg.vocab_size))}
    return params
