"""Mixtral-style sparse Mixture-of-Experts family (TPU-native design).

Second model family next to Llama (the reference orchestrates arbitrary
engram containers; BASELINE's engram workloads are LLM inference — an
MoE family exercises the expert-parallel axis the dense family cannot).

TPU-first formulation: routing uses the dense one-hot dispatch/combine
einsums (GShard/Switch style) — static shapes, no gather/scatter with
data-dependent sizes, everything lands on the MXU and XLA inserts the
all-to-alls when experts are sharded on the ``expert`` mesh axis:

  router:   logits  [B,S,E]    = x @ w_router
  dispatch: mask    [B,S,E,C]  (top-k one-hot with per-expert capacity)
  expert:   inputs  [E, B*C', D] -> ffn -> outputs (batched einsum over E)
  combine:  y       [B,S,D]    = sum_e,c weight * expert_out

Expert FFN weights are stacked [E, D, F]: E shards on ``expert``
(expert parallelism), F on ``model`` (TP inside each expert), D on
``fsdp``. Attention blocks are the dense Llama ones — only the MLP is
replaced per layer.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.rmsnorm import rmsnorm_reference
from ..ops.rope import rope_frequencies
from .llama import LlamaConfig, _attention_block, _cached_attention  # noqa: F401


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32_000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14_336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25  # per-expert token budget multiplier
    max_seq_len: int = 8192
    rope_theta: float = 1_000_000.0
    #: Llama-3.1-style long-context RoPE remap (see llama.LlamaConfig)
    rope_scaling: Optional[tuple] = None
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def capacity(self, tokens: int) -> int:
        """Static per-expert capacity for a given token count."""
        cap = int(math.ceil(tokens * self.experts_per_token
                            * self.capacity_factor / self.n_experts))
        return max(cap, 1)

    def as_llama(self) -> LlamaConfig:
        """Attention-relevant view for reusing the dense attention block."""
        return LlamaConfig(
            vocab_size=self.vocab_size, dim=self.dim, n_layers=self.n_layers,
            n_heads=self.n_heads, n_kv_heads=self.n_kv_heads,
            ffn_hidden=self.ffn_hidden, max_seq_len=self.max_seq_len,
            rope_theta=self.rope_theta, rope_scaling=self.rope_scaling,
            norm_eps=self.norm_eps, dtype=self.dtype,
        )


def mixtral_8x7b() -> MoEConfig:
    return MoEConfig()


def moe_tiny(vocab_size: int = 512, max_seq_len: int = 256) -> MoEConfig:
    """Tiny config for tests and the multi-chip dryrun."""
    return MoEConfig(
        vocab_size=vocab_size, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_hidden=256, n_experts=4, experts_per_token=2,
        max_seq_len=max_seq_len, dtype=jnp.float32,
    )


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: MoEConfig) -> dict[str, Any]:
    """Parameter pytree. Expert weights are stacked on a leading E axis:

      layers.<i>.moe.w_router [D, E]
      layers.<i>.moe.{w_gate, w_up} [E, D, F]
      layers.<i>.moe.w_down [E, F, D]
    """
    keys = iter(jax.random.split(key, 2 + cfg.n_layers * 8))
    std = 1.0 / math.sqrt(cfg.dim)

    def dense(k, shape, scale=std):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(cfg.dtype)

    params: dict[str, Any] = {
        "embed": {"weight": dense(next(keys), (cfg.vocab_size, cfg.dim), 1.0)},
        "layers": [],
        "final_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
        "lm_head": {"weight": dense(next(keys), (cfg.dim, cfg.vocab_size))},
    }
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    out_scale = std / math.sqrt(2 * cfg.n_layers)
    for _ in range(cfg.n_layers):
        params["layers"].append({
            "attn_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
            "attn": {
                "wq": dense(next(keys), (cfg.dim, cfg.dim)),
                "wk": dense(next(keys), (cfg.dim, kv_dim)),
                "wv": dense(next(keys), (cfg.dim, kv_dim)),
                "wo": dense(next(keys), (cfg.dim, cfg.dim), out_scale),
            },
            "mlp_norm": {"weight": jnp.ones((cfg.dim,), cfg.dtype)},
            "moe": {
                "w_router": dense(next(keys), (cfg.dim, cfg.n_experts)),
                "w_gate": dense(next(keys), (cfg.n_experts, cfg.dim, cfg.ffn_hidden)),
                "w_up": dense(next(keys), (cfg.n_experts, cfg.dim, cfg.ffn_hidden)),
                "w_down": dense(next(keys), (cfg.n_experts, cfg.ffn_hidden, cfg.dim),
                                out_scale),
            },
        })
    return params


# ---------------------------------------------------------------------------
# routing (dense dispatch/combine — static shapes, MXU-friendly)
# ---------------------------------------------------------------------------


def route_topk(
    router_logits: jax.Array, cfg: MoEConfig
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-expert capacity.

    Returns (dispatch [T,E,C] bool, combine [T,E,C] f32, aux_loss scalar)
    for T flattened tokens. Tokens over an expert's capacity are dropped
    for that expert (standard Switch behavior; capacity_factor buys
    headroom).
    """
    t, e = router_logits.shape
    c = cfg.capacity(t)
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T,E]

    # top-k expert ids per token -> one-hot [T,K,E]
    _, topk_idx = jax.lax.top_k(probs, cfg.experts_per_token)
    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)  # [T,K,E]
    gate = jnp.einsum("tke,te->tk", onehot, probs)  # chosen probs
    # normalize the chosen gates per token
    gate = gate / jnp.clip(gate.sum(-1, keepdims=True), 1e-9)

    # position of each (token, k) in its expert's queue, in token order
    flat = onehot.reshape(t * cfg.experts_per_token, e)  # [T*K,E]
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(
        t, cfg.experts_per_token, e
    )  # [T,K,E]
    within_cap = pos_in_expert < c
    keep = onehot * within_cap  # [T,K,E]

    cap_slot = jax.nn.one_hot(
        jnp.einsum("tke->tk", pos_in_expert * onehot).astype(jnp.int32),
        c, dtype=jnp.float32,
    )  # [T,K,C]
    dispatch = jnp.einsum("tke,tkc->tec", keep, cap_slot)  # [T,E,C]
    combine = jnp.einsum("tke,tkc,tk->tec", keep, cap_slot, gate)

    # load-balancing auxiliary loss (Switch eq. 4-6)
    token_frac = jnp.mean(onehot.sum(1), axis=0)      # fraction routed per e
    prob_frac = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(token_frac * prob_frac) / cfg.experts_per_token
    return dispatch, combine, aux


def moe_mlp_block(
    layer: dict[str, Any], x: jax.Array, cfg: MoEConfig
) -> tuple[jax.Array, jax.Array]:
    """Sparse-MoE replacement for the dense MLP block. Returns
    (residual output, aux loss)."""
    b, s, d = x.shape
    h = rmsnorm_reference(x, layer["mlp_norm"]["weight"], cfg.norm_eps)
    flat = h.reshape(b * s, d)
    logits = flat @ layer["moe"]["w_router"]  # [T,E]
    dispatch, combine, aux = route_topk(logits, cfg)

    # dispatch tokens into per-expert buffers: [E,C,D]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, flat.astype(jnp.float32))
    expert_in = expert_in.astype(cfg.dtype)
    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, layer["moe"]["w_gate"])
        .astype(jnp.float32)
    )
    up = jnp.einsum("ecd,edf->ecf", expert_in, layer["moe"]["w_up"]).astype(
        jnp.float32
    )
    expert_out = jnp.einsum(
        "ecf,efd->ecd", (gate * up).astype(cfg.dtype), layer["moe"]["w_down"]
    )  # [E,C,D]
    y = jnp.einsum("tec,ecd->td", combine, expert_out.astype(jnp.float32))
    return x + y.reshape(b, s, d).astype(cfg.dtype), aux


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def forward(
    params: dict[str, Any],
    tokens: jax.Array,
    cfg: MoEConfig,
    cache: Optional[list[dict[str, jax.Array]]] = None,
    positions: Optional[jax.Array] = None,
    attn_fn=None,
) -> tuple[jax.Array, Optional[list[dict[str, jax.Array]]], jax.Array]:
    """Token ids [B,S] -> (logits [B,S,V], cache', total aux loss)."""
    if attn_fn is None:
        attn_fn = lambda q, k, v: attention(q, k, v, causal=True)  # noqa: E731
    lcfg = cfg.as_llama()
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                             cfg.rope_theta, cfg.rope_scaling)
    x = params["embed"]["weight"][tokens].astype(cfg.dtype)
    new_caches: Optional[list] = [] if cache is not None else None
    aux_total = jnp.array(0.0, jnp.float32)
    for i, layer in enumerate(params["layers"]):
        layer_cache = cache[i] if cache is not None else None
        x, updated = _attention_block(
            layer, x, freqs, lcfg, layer_cache, positions, attn_fn
        )
        if new_caches is not None:
            new_caches.append(updated)
        x, aux = moe_mlp_block(layer, x, cfg)
        aux_total = aux_total + aux
    x = rmsnorm_reference(x, params["final_norm"]["weight"], cfg.norm_eps)
    logits = x @ params["lm_head"]["weight"]
    return logits.astype(jnp.float32), new_caches, aux_total


def loss_fn(params, tokens, targets, cfg: MoEConfig,
            aux_weight: float = 0.01) -> jax.Array:
    logits, _, aux = forward(params, tokens, cfg)
    ce = -jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), targets[..., None], axis=-1
    ).mean()
    return ce + aux_weight * aux / cfg.n_layers


# ---------------------------------------------------------------------------
# KV cache + generation (reference decode for the serving engine)
# ---------------------------------------------------------------------------


def init_cache(cfg: MoEConfig, batch: int, capacity: Optional[int] = None):
    """Same attention-cache shape as the dense family (MoE replaces
    only the MLP)."""
    from .llama import init_cache as _llama_init_cache

    return _llama_init_cache(cfg.as_llama(), batch, capacity)


def greedy_generate(
    params: dict[str, Any],
    prompt: jax.Array,
    cfg: MoEConfig,
    max_new_tokens: int = 32,
    cache_capacity: Optional[int] = None,
) -> jax.Array:
    """Greedy decode for the MoE family — llama's prefill+scan loop
    with the routed forward plugged in (no copied loop)."""
    from .llama import greedy_generate as _greedy

    def fwd(p, t, c, cache, pos):
        logits, cache, _aux = forward(p, t, c, cache=cache, positions=pos)
        return logits, cache

    return _greedy(params, prompt, cfg, max_new_tokens=max_new_tokens,
                   cache_capacity=cache_capacity, forward_fn=fwd)
