"""The operator<->SDK env contract (the framework's real public API).

Capability parity with the reference's BUBU_* env contract built in
buildBaseEnvVars (reference: steprun_controller.go:1692; contract names
live in the external bubustack/core ``contracts`` package), extended with
the TPU topology fields SURVEY §7 calls for: accelerator/topology/hosts,
per-host ids, coordinator address, and logical mesh axes so the engram
can run ``jax.distributed.initialize`` + build its ``jax.sharding.Mesh``
from operator-granted facts alone.

Versioned: consumers check CONTRACT_VERSION before trusting fields.
"""

from __future__ import annotations

import json
from typing import Any, Optional

CONTRACT_VERSION = "1"

# identity
ENV_CONTRACT_VERSION = "BOBRA_CONTRACT_VERSION"
ENV_NAMESPACE = "BOBRA_NAMESPACE"
ENV_STORY = "BOBRA_STORY"
ENV_STORY_RUN = "BOBRA_STORY_RUN"
ENV_STEP = "BOBRA_STEP"
ENV_STEP_RUN = "BOBRA_STEP_RUN"
ENV_ENGRAM = "BOBRA_ENGRAM"

# execution
ENV_EXECUTION_MODE = "BOBRA_EXECUTION_MODE"  # job | deployment | statefulset
ENV_INPUTS = "BOBRA_INPUTS"  # inline JSON payload
ENV_INPUTS_REF = "BOBRA_INPUTS_REF"  # storageRef marker JSON when offloaded
ENV_CONFIG = "BOBRA_CONFIG"  # engram `with` config JSON
ENV_STEP_TIMEOUT_SECONDS = "BOBRA_STEP_TIMEOUT_SECONDS"
ENV_MAX_INLINE_SIZE = "BOBRA_MAX_INLINE_SIZE"
ENV_STORAGE_TIMEOUT_SECONDS = "BOBRA_STORAGE_TIMEOUT_SECONDS"
ENV_MAX_RECURSION_DEPTH = "BOBRA_MAX_RECURSION_DEPTH"
ENV_GRPC_PORT = "BOBRA_GRPC_PORT"
ENV_DEBUG = "BOBRA_DEBUG"

# impulse (trigger workload) contract
# (reference: appendTriggerDeliveryEnvVars impulse_controller.go:1477)
ENV_IMPULSE = "BOBRA_IMPULSE"
ENV_TRIGGER_STORY = "BOBRA_TRIGGER_STORY"
ENV_TRIGGER_STORY_NAMESPACE = "BOBRA_TRIGGER_STORY_NAMESPACE"
ENV_TRIGGER_MAPPING = "BOBRA_TRIGGER_MAPPING"  # event -> inputs template JSON
ENV_TRIGGER_DELIVERY = "BOBRA_TRIGGER_DELIVERY"  # delivery policy JSON
ENV_TRIGGER_THROTTLE = "BOBRA_TRIGGER_THROTTLE"  # throttle policy JSON

# streaming
ENV_DOWNSTREAM_TARGETS = "BOBRA_DOWNSTREAM_TARGETS"  # JSON list of next hops
ENV_BINDING_INFO = "BOBRA_BINDING_INFO"  # negotiated transport binding JSON
# shared-CA mTLS material directory (ca.crt/tls.crt/tls.key — the
# cert-manager secret layout; reference: pkg/transport/security.go:11)
ENV_TLS_DIR = "BOBRA_TLS_DIR"

# tracing: controller-persisted span context (reference: TraceInfo
# trace_types.go:20 + pkg/runs/status/trace.go) so SDK spans parent into
# the controller's trace across the process boundary
ENV_TRACE_CONTEXT = "BOBRA_TRACEPARENT"  # JSON {traceId, spanId, sampled}

# TPU topology (TPU-native additions; no reference counterpart)
ENV_TPU_ACCELERATOR = "BOBRA_TPU_ACCELERATOR"
ENV_TPU_TOPOLOGY = "BOBRA_TPU_TOPOLOGY"  # e.g. "2x4"
ENV_TPU_HOSTS = "BOBRA_TPU_HOSTS"  # host processes in the gang
ENV_TPU_HOST_ID = "BOBRA_TPU_HOST_ID"  # this host's index (0-based)
ENV_COORDINATOR_ADDRESS = "BOBRA_COORDINATOR_ADDRESS"  # jax.distributed coordinator
ENV_MESH_AXES = "BOBRA_MESH_AXES"  # JSON {axis: size}
ENV_SLICE_ID = "BOBRA_SLICE_ID"  # granted ICI-contiguous sub-mesh id
# GKE-standard names for compatibility with existing TPU tooling
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"

# multi-grant (spanning gang) contract: a `parallel` step with a
# replicas/span policy fans one logical step out as N per-pool gang
# members; each member's env carries its replica identity plus the
# span-global process layout so every host of every member initializes
# jax.distributed over ONE process set and builds the two-level
# dcn x ICI mesh (parallel/mesh.build_mesh_from_env). TPU-native
# addition; no reference counterpart.
ENV_DCN_REPLICAS = "BOBRA_DCN_REPLICAS"  # DCN axis size (span member count)
ENV_DCN_REPLICA_INDEX = "BOBRA_DCN_REPLICA_INDEX"  # this member's index
ENV_SPAN_ID = "BOBRA_SPAN_ID"  # spanning-grant group id
ENV_SPAN_PROCESSES = "BOBRA_SPAN_PROCESSES"  # global process count
ENV_SPAN_PROCESS_BASE = "BOBRA_SPAN_PROCESS_BASE"  # first global pid here

# checkpoint-resume contract (fleet preemption recovery; TPU-native
# addition). The operator always exports the step's canonical checkpoint
# prefix; after a preemption redrive it also exports the latest complete
# checkpoint step so training resumes instead of restarting at step zero
# (docs/TRAINING.md "Checkpoint-resume env contract").
ENV_CHECKPOINT_PREFIX = "BOBRA_CHECKPOINT_PREFIX"
ENV_RESUME_STEP = "BOBRA_RESUME_STEP"
ENV_PREEMPTION_ATTEMPT = "BOBRA_PREEMPTION_ATTEMPT"  # redrives so far

# exit codes with contractual meaning (reference: classifyExitCode
# steprun_controller.go:4815)
EXIT_SUCCESS = 0
EXIT_TIMEOUT = 124
EXIT_CONFIG_TERMINAL_MIN = 125  # 125-127: terminal (bad config/image)
EXIT_CONFIG_TERMINAL_MAX = 127
EXIT_SIGKILL = 137
EXIT_SIGTERM = 143
EXIT_RATE_LIMITED = 119  # in-band rate-limit signal (reference uses 429
# at the StructuredError level; one byte can't carry 429, so the contract
# reserves 119)


def build_env(
    *,
    namespace: str,
    story: str,
    story_run: str,
    step: str,
    step_run: str,
    engram: str = "",
    execution_mode: str = "job",
    inputs: Optional[Any] = None,
    inputs_ref: Optional[dict[str, Any]] = None,
    config: Optional[dict[str, Any]] = None,
    step_timeout_seconds: Optional[float] = None,
    max_inline_size: int = 16 * 1024,
    storage_timeout_seconds: int = 30,
    max_recursion_depth: int = 10,
    grpc_port: int = 50051,
    debug: bool = False,
    downstream_targets: Optional[list[dict[str, Any]]] = None,
    tpu_accelerator: Optional[str] = None,
    tpu_topology: Optional[str] = None,
    tpu_hosts: int = 1,
    coordinator_address: Optional[str] = None,
    mesh_axes: Optional[dict[str, int]] = None,
    slice_id: Optional[str] = None,
    trace_context: Optional[dict[str, Any]] = None,
    checkpoint_prefix: Optional[str] = None,
    resume_step: Optional[int] = None,
    preemption_attempt: int = 0,
    span: Optional[dict[str, Any]] = None,
) -> dict[str, str]:
    """Render the per-step env contract (host-independent portion).

    Per-host fields (HOST_ID / TPU_WORKER_ID) are layered on by
    :func:`host_env`.
    """
    env = {
        ENV_CONTRACT_VERSION: CONTRACT_VERSION,
        ENV_NAMESPACE: namespace,
        ENV_STORY: story,
        ENV_STORY_RUN: story_run,
        ENV_STEP: step,
        ENV_STEP_RUN: step_run,
        ENV_ENGRAM: engram,
        ENV_EXECUTION_MODE: execution_mode,
        ENV_MAX_INLINE_SIZE: str(max_inline_size),
        ENV_STORAGE_TIMEOUT_SECONDS: str(storage_timeout_seconds),
        ENV_MAX_RECURSION_DEPTH: str(max_recursion_depth),
        ENV_GRPC_PORT: str(grpc_port),
        ENV_DEBUG: "1" if debug else "0",
        ENV_TPU_HOSTS: str(tpu_hosts),
    }
    if inputs is not None:
        env[ENV_INPUTS] = json.dumps(inputs, separators=(",", ":"))
    if inputs_ref is not None:
        env[ENV_INPUTS_REF] = json.dumps(inputs_ref, separators=(",", ":"))
    if config is not None:
        env[ENV_CONFIG] = json.dumps(config, separators=(",", ":"))
    if step_timeout_seconds is not None:
        env[ENV_STEP_TIMEOUT_SECONDS] = str(step_timeout_seconds)
    if downstream_targets:
        env[ENV_DOWNSTREAM_TARGETS] = json.dumps(downstream_targets, separators=(",", ":"))
    if tpu_accelerator:
        env[ENV_TPU_ACCELERATOR] = tpu_accelerator
    if tpu_topology:
        env[ENV_TPU_TOPOLOGY] = tpu_topology
    if coordinator_address:
        env[ENV_COORDINATOR_ADDRESS] = coordinator_address
    if mesh_axes:
        env[ENV_MESH_AXES] = json.dumps(mesh_axes, separators=(",", ":"))
    if slice_id:
        env[ENV_SLICE_ID] = slice_id
    if trace_context:
        env[ENV_TRACE_CONTEXT] = json.dumps(trace_context, separators=(",", ":"))
    if checkpoint_prefix:
        env[ENV_CHECKPOINT_PREFIX] = checkpoint_prefix
    if resume_step is not None:
        env[ENV_RESUME_STEP] = str(int(resume_step))
    if preemption_attempt:
        env[ENV_PREEMPTION_ATTEMPT] = str(int(preemption_attempt))
    if span:
        # spanning-gang membership (SliceGrant.span): replica identity +
        # the global process layout. The span coordinator (member 0's
        # pool) overrides any per-pool coordinator already set — every
        # member of the span must dial ONE address
        env.update(span_env(span))
        if span.get("coordinator"):
            env[ENV_COORDINATOR_ADDRESS] = str(span["coordinator"])
    return env


def span_env(span: dict[str, Any]) -> dict[str, str]:
    """Render the spanning-gang membership fields (replica identity +
    global process layout) — the ONE renderer both :func:`build_env`
    and the GKE materializer use, so the two emission paths cannot
    drift. Coordinator handling stays with the caller: the runtime
    path trusts the span's recorded address verbatim, the GKE path
    normalizes ports and can derive a span-scoped coordinator Service
    when placement recorded none."""
    env = {
        ENV_DCN_REPLICAS: str(int(span.get("replicas") or 1)),
        ENV_DCN_REPLICA_INDEX: str(int(span.get("replica") or 0)),
        ENV_SPAN_PROCESS_BASE: str(int(span.get("processBase") or 0)),
    }
    if span.get("id"):
        env[ENV_SPAN_ID] = str(span["id"])
    if span.get("processes"):
        env[ENV_SPAN_PROCESSES] = str(int(span["processes"]))
    return env


def host_env(base: dict[str, str], host_id: int, hostnames: Optional[list[str]] = None) -> dict[str, str]:
    """Layer per-host identity onto the base env (completion-index ->
    TPU_WORKER_ID mapping, SURVEY §2.6 Job parallelism row)."""
    env = dict(base)
    env[ENV_TPU_HOST_ID] = str(host_id)
    env[ENV_TPU_WORKER_ID] = str(host_id)
    if hostnames:
        env[ENV_TPU_WORKER_HOSTNAMES] = ",".join(hostnames)
    return env
