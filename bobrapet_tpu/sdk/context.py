"""Engram runtime context: what user engram code sees.

The in-container counterpart of the reference's out-of-repo SDK
(SURVEY §7 'Engram runtime / SDK'): reads the env contract, exposes
inputs/config, builds the device mesh from operator-granted topology,
and patches results back into StepRun status (the SDK-direct status
write the reference's controller races against,
steprun_controller.go:2031).

Engram entrypoints are callables ``run(ctx) -> output`` registered in
:mod:`bobrapet_tpu.sdk.registry` or addressed as "module.path:attr".
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Callable, Optional

from ..api.errors import StructuredError
from . import contract

_log = logging.getLogger(__name__)


class EngramExit(Exception):
    """Terminate the engram with a specific contract exit code."""

    def __init__(self, code: int, message: str = ""):
        super().__init__(message or f"exit {code}")
        self.code = code


class EngramTimeout(EngramExit):
    def __init__(self, message: str = "step deadline exceeded"):
        super().__init__(contract.EXIT_TIMEOUT, message)


class EngramRateLimited(EngramExit):
    def __init__(self, message: str = "rate limited"):
        super().__init__(contract.EXIT_RATE_LIMITED, message)


class EngramContext:
    """Execution context handed to engram entrypoints.

    For local (in-process) execution the context holds live handles to
    the bus and storage manager; for containerized execution the same
    API is backed by env vars + the status-patch endpoint.
    """

    def __init__(
        self,
        env: dict[str, str],
        store=None,  # ResourceStore for SDK-direct status patches
        storage=None,  # StorageManager for offloaded IO
        clock=None,
        cancel_event: Optional[threading.Event] = None,
    ):
        self.env = env
        self._store = store
        self._storage = storage
        self._clock = clock
        self._cancel = cancel_event or threading.Event()
        self._deadline: Optional[float] = None
        timeout = env.get(contract.ENV_STEP_TIMEOUT_SECONDS)
        if timeout and clock is not None:
            self._deadline = clock.now() + float(timeout)
        self._inputs: Optional[Any] = None
        self._output_patched = False

    # -- identity ----------------------------------------------------------

    @property
    def namespace(self) -> str:
        return self.env.get(contract.ENV_NAMESPACE, "default")

    @property
    def step_run(self) -> str:
        return self.env.get(contract.ENV_STEP_RUN, "")

    @property
    def step(self) -> str:
        return self.env.get(contract.ENV_STEP, "")

    @property
    def story_run(self) -> str:
        return self.env.get(contract.ENV_STORY_RUN, "")

    @property
    def debug(self) -> bool:
        return self.env.get(contract.ENV_DEBUG) == "1"

    # -- gang/topology -----------------------------------------------------

    @property
    def host_id(self) -> int:
        return int(self.env.get(contract.ENV_TPU_HOST_ID, "0"))

    @property
    def num_hosts(self) -> int:
        return int(self.env.get(contract.ENV_TPU_HOSTS, "1"))

    @property
    def is_coordinator(self) -> bool:
        return self.host_id == 0

    @property
    def coordinator_address(self) -> Optional[str]:
        return self.env.get(contract.ENV_COORDINATOR_ADDRESS)

    @property
    def mesh_axes(self) -> dict[str, int]:
        raw = self.env.get(contract.ENV_MESH_AXES)
        return {k: int(v) for k, v in (json.loads(raw) if raw else {}).items()}

    @property
    def tpu_topology(self) -> Optional[str]:
        return self.env.get(contract.ENV_TPU_TOPOLOGY)

    @property
    def dcn_replicas(self) -> int:
        """DCN replica count of the spanning gang this step is one
        member of (1 = classic single-slice grant)."""
        from ..parallel.mesh import span_facts

        return span_facts(self.env)["replicas"]

    @property
    def dcn_replica_index(self) -> int:
        from ..parallel.mesh import span_facts

        return span_facts(self.env)["replica"]

    def initialize_distributed(self) -> None:
        """Run jax.distributed.initialize from granted coordinator env —
        ICI replaces NCCL (SURVEY §5.8 TPU-native equivalent). No-op for
        single-host grants. A SPANNING gang member initializes over the
        span's GLOBAL process set (every host of every per-pool member,
        one coordinator) so N per-pool gangs form ONE jax job — the
        two-level-mesh contract (parallel/mesh.distributed_init_args)."""
        from ..parallel.mesh import distributed_init_args

        args = distributed_init_args(self.env, host_id=self.host_id)
        if args is None:
            return
        import jax

        jax.distributed.initialize(**args)

    @property
    def storage(self):
        """The run's storage manager (None when storage is not wired) —
        the public accessor extension code must use."""
        return self._storage

    def mesh(self, axes: Optional[dict[str, int]] = None):
        """Build the granted jax.sharding.Mesh (local devices reshaped to
        the granted logical axes). A spanning-gang member builds the
        two-level ``dcn`` x ICI mesh — the granted ICI axes are the
        inner level, the span's replica count the outer."""
        from ..parallel.mesh import build_mesh, build_two_level_mesh

        ici = axes or self.mesh_axes or None
        replicas = self.dcn_replicas
        if replicas > 1:
            mesh = build_two_level_mesh(replicas, ici)
        else:
            mesh = build_mesh(ici)
        # the grant promised an accelerator; jax just initialized its
        # backend to build the mesh — if that landed on CPU, surface the
        # fallback in the live metrics plane instead of only in bench
        # forensics (bobrapet_backend_fallback_total{reason} + one log)
        from ..observability.analytics import check_backend_expectation

        check_backend_expectation(
            self.env.get(contract.ENV_TPU_ACCELERATOR)
        )
        return mesh

    # -- data --------------------------------------------------------------

    @property
    def inputs(self) -> Any:
        """Resolved step inputs; offloaded payloads hydrate lazily."""
        if self._inputs is None:
            raw = self.env.get(contract.ENV_INPUTS)
            ref = self.env.get(contract.ENV_INPUTS_REF)
            if raw is not None:
                value = json.loads(raw)
            elif ref is not None:
                value = json.loads(ref)
            else:
                value = {}
            if self._storage is not None:
                prefix = f"runs/{self.namespace}/{self.story_run}"
                value = self._storage.hydrate(value, allowed_prefixes=[prefix])
            self._inputs = value
        return self._inputs

    @property
    def config(self) -> dict[str, Any]:
        raw = self.env.get(contract.ENV_CONFIG)
        return json.loads(raw) if raw else {}

    # -- preemption recovery (fleet subsystem) -----------------------------

    @property
    def resume_step(self) -> Optional[int]:
        """Latest complete checkpoint step the operator observed when
        redriving this gang after a preemption; None on a fresh launch.
        Resume-aware engrams skip to this step instead of restoring
        blind (``restore_model_checkpoint`` finds it either way)."""
        raw = self.env.get(contract.ENV_RESUME_STEP)
        return int(raw) if raw is not None else None

    @property
    def preemption_attempt(self) -> int:
        """How many times this step has been preemption-redriven."""
        return int(self.env.get(contract.ENV_PREEMPTION_ATTEMPT, "0"))

    def heartbeat(self) -> None:
        """Stamp this host's liveness into StepRun.status.hostHeartbeats.
        The fleet preemption watcher treats a stale beat as a suspect
        cell (cluster-event analog of a GKE node condition)."""
        if self._store is None or not self.step_run:
            return
        import time

        # wall clock, never 0.0: a zero stamp reads as infinitely stale
        # and would earn a live host endless suspicion
        at = self._clock.now() if self._clock is not None else time.time()
        host = str(self.host_id)

        def patch(status: dict[str, Any]) -> None:
            status.setdefault("hostHeartbeats", {})[host] = at

        self._store.patch_status("StepRun", self.namespace, self.step_run, patch)

    # -- deadline / cancel -------------------------------------------------

    def check_deadline(self) -> None:
        """Cooperative timeout/cancel check for long loops."""
        if self._cancel.is_set():
            raise EngramExit(contract.EXIT_SIGTERM, "canceled")
        if (
            self._deadline is not None
            and self._clock is not None
            and self._clock.now() > self._deadline
        ):
            raise EngramTimeout()

    @property
    def canceled(self) -> bool:
        return self._cancel.is_set()

    # -- results -----------------------------------------------------------

    def output(self, value: Any) -> None:
        """SDK-direct output write into StepRun.status
        (reference: SDK patches StepRun status; controller detects via
        stepStatusPatchedBySDK)."""
        if self._store is None or not self.step_run:
            return
        if self.host_id != 0:
            return  # gang convention: coordinator host reports the output
        offloaded = value
        if self._storage is not None:
            max_inline = int(self.env.get(contract.ENV_MAX_INLINE_SIZE, "16384"))
            key = f"runs/{self.namespace}/{self.story_run}/steps/{self.step}/output"
            offloaded = self._storage.dehydrate(value, key, max_inline_size=max_inline)

        def patch(status: dict[str, Any]) -> None:
            status["output"] = offloaded
            status["outputSource"] = "sdk"

        self._store.patch_status("StepRun", self.namespace, self.step_run, patch)
        self._output_patched = True

    def signal(self, name: str, value: Any = True) -> None:
        """Emit a named signal into the StepRun signals ledger
        (reference: steprun_types.go:360 SignalEvent)."""
        if self._store is None or not self.step_run:
            return
        at = self._clock.now() if self._clock is not None else 0.0

        def patch(status: dict[str, Any]) -> None:
            status.setdefault("signals", {})[name] = value
            status.setdefault("signalEvents", []).append(
                {"name": name, "value": value, "at": at}
            )

        self._store.patch_status("StepRun", self.namespace, self.step_run, patch)

    def error(self, err: StructuredError) -> None:
        """Report a structured error before exiting nonzero."""
        if self._store is None or not self.step_run:
            return

        def patch(status: dict[str, Any]) -> None:
            status["error"] = err.to_dict()

        self._store.patch_status("StepRun", self.namespace, self.step_run, patch)

    # -- tracing -----------------------------------------------------------

    @property
    def trace_context(self) -> Optional[dict[str, Any]]:
        """Controller-persisted span context (StepRun.status.trace carried
        through the env contract) — SDK spans parent into the
        controller's trace across the process boundary."""
        raw = self.env.get(contract.ENV_TRACE_CONTEXT)
        return json.loads(raw) if raw else None

    def start_span(self, name: str, **attributes: Any):
        """Open an SDK-side span stitched into the run's trace; a no-op
        (yields None) when tracing is disabled."""
        from ..observability.tracing import TRACER

        return TRACER.start_span(
            name,
            trace_context=self.trace_context,
            step=self.step,
            step_run=self.step_run,
            # run identity on every SDK span: the flight recorder's span
            # sink and /debug/traces join spans to runs through these
            run=self.story_run,
            namespace=self.namespace,
            **attributes,
        )

    # -- model checkpointing ----------------------------------------------

    @property
    def checkpoint_prefix(self) -> str:
        """Blob-key prefix for this step's model checkpoints — stable
        across retries AND redrives (keyed on run + step id, not the
        StepRun instance), so a redriven training step finds its
        predecessor's state (SURVEY §5.4). The operator exports the same
        canonical prefix through the env contract
        (``BOBRA_CHECKPOINT_PREFIX``) — the env wins when present so the
        two sides can never disagree about where resume state lives."""
        explicit = self.env.get(contract.ENV_CHECKPOINT_PREFIX)
        if explicit:
            return explicit
        from ..storage.manager import StorageManager
        from .checkpoint import STEP_CHECKPOINT_FIELD

        return StorageManager.step_key(
            self.namespace, self.story_run, self.step, STEP_CHECKPOINT_FIELD
        )

    def save_model_checkpoint(self, state: Any, step: int, keep: int = 2) -> str:
        """Sharded save of a train-state pytree (params/opt_state/...)
        into the run's storage provider; see sdk/checkpoint.py. Each
        gang host writes its own shards + manifest (host id = process),
        so multi-host gangs cooperatively produce one checkpoint."""
        if self._storage is None:
            raise RuntimeError("no storage manager configured for checkpoints")
        from .checkpoint import save_checkpoint

        return save_checkpoint(
            self._storage.store, self.checkpoint_prefix, state, step, keep=keep,
            process=self.host_id, world=self.num_hosts,
        )

    def restore_model_checkpoint(
        self, like: Any, step: Optional[int] = None
    ) -> Optional[tuple[Any, int]]:
        """(state, step) from the latest (or given) checkpoint, restored
        onto ``like``'s structure/shardings; None when no checkpoint
        exists (fresh start)."""
        if self._storage is None:
            return None
        from ..storage.store import BlobNotFound
        from .checkpoint import restore_checkpoint

        try:
            return restore_checkpoint(
                self._storage.store, self.checkpoint_prefix, like, step=step
            )
        except BlobNotFound:
            return None

    def latest_model_checkpoint_step(self) -> Optional[int]:
        if self._storage is None:
            return None
        from .checkpoint import latest_checkpoint_step

        return latest_checkpoint_step(self._storage.store, self.checkpoint_prefix)

    # -- realtime streaming ------------------------------------------------

    @property
    def binding_info(self) -> Optional[dict[str, Any]]:
        """Negotiated transport binding (codecs/mesh/driver), injected
        by the controller (reference: EncodeBindingEnv
        transportutil.go:188)."""
        raw = self.env.get(contract.ENV_BINDING_INFO)
        return json.loads(raw) if raw else None

    @property
    def downstream_targets(self) -> list[dict[str, Any]]:
        """Controller-computed next hops for this step's output stream
        (reference: computeDownstreamTargets steprun_controller.go:1405)."""
        raw = self.env.get(contract.ENV_DOWNSTREAM_TARGETS)
        return json.loads(raw) if raw else []

    @property
    def negotiated_stream_settings(self) -> Optional[dict[str, Any]]:
        """The merged streaming settings the controller negotiated into
        the binding (transport -> story -> step layers)."""
        info = self.binding_info
        return (info or {}).get("settings")

    def open_output_streams(self, settings: Optional[dict[str, Any]] = None,
                            connect_timeout: float = 10.0):
        """One StreamProducer per downstream consumer step. Backpressure
        (credit flow control, drop policies) follows the negotiated
        settings (default: the binding's merged settings); `send` blocks
        when downstream is full. Streams are consumer-named
        ``ns/run/<consumerStep>`` — a hub target fans out to every step
        in its ``stepNames``; a P2P target names exactly one."""
        from ..dataplane.client import open_producer
        from ..dataplane.tls import TLSPaths

        if settings is None:
            settings = self.negotiated_stream_settings
        # EngramTLSSpec contract: the controller advertises the mounted
        # shared-CA material via BOBRA_TLS_DIR; every streaming edge
        # this SDK opens then speaks mTLS (plaintext otherwise)
        tls = TLSPaths.from_env(self.env)
        producers = []
        for target in self.downstream_targets:
            if target.get("terminate"):
                continue
            grpc = target.get("grpc") or {}
            host, port = grpc.get("host"), grpc.get("port")
            if not host or not port:
                continue
            dests = grpc.get("stepNames") or (
                [grpc["stepName"]] if grpc.get("stepName") else []
            )
            for dest in dests:
                stream = f"{self.namespace}/{self.story_run}/{dest}"
                # settings-aware: partitioned settings route over N
                # hub streams transparently (dataplane/partition.py)
                producers.append(open_producer(
                    f"{host}:{port}", stream, settings=settings,
                    connect_timeout=connect_timeout, tls=tls,
                    # the run trace rides onto the stream's hello frame
                    # so the hub can attribute the stream to the trace
                    trace_context=self.trace_context,
                ))
        return producers

    def open_input_stream(self, endpoint: str,
                          settings: Optional[dict[str, Any]] = None,
                          decode_json: bool = True,
                          connect_timeout: float = 10.0):
        """Subscribe to this step's input stream at the hub endpoint;
        iterate to receive (acks ride the negotiated cadence; settings
        default to the binding's merged settings)."""
        from ..dataplane.client import open_consumer
        from ..dataplane.tls import TLSPaths

        if settings is None:
            settings = self.negotiated_stream_settings
        stream = f"{self.namespace}/{self.story_run}/{self.step}"
        # step identity + gang host = the durable checkpoint identity
        # (replay.mode=fromCheckpoint): a redriven/restarted replica
        # resumes exactly after what IT acknowledged — without the
        # host suffix, gang replicas would share one checkpoint and a
        # lagging host could silently skip past its unprocessed range
        return open_consumer(endpoint, stream, settings=settings,
                             decode_json=decode_json,
                             connect_timeout=connect_timeout,
                             tls=TLSPaths.from_env(self.env),
                             consumer_id=f"{stream}@{self.host_id}")

    @property
    def log(self) -> logging.Logger:
        return logging.getLogger(f"engram.{self.step}")


def resolve_entrypoint(spec: str) -> Callable[[EngramContext], Any]:
    """Resolve "module.path:attr" or a registry name to a callable."""
    from .registry import get_engram

    registered = get_engram(spec)
    if registered is not None:
        return registered
    if ":" not in spec:
        raise ValueError(f"unknown engram entrypoint {spec!r}")
    module_name, attr = spec.split(":", 1)
    import importlib

    module = importlib.import_module(module_name)
    fn = module
    for part in attr.split("."):
        fn = getattr(fn, part)
    if not callable(fn):
        raise TypeError(f"entrypoint {spec!r} is not callable")
    return fn
