"""In-process engram registry.

Local/test deployments register engram callables by name instead of
building container images — the TPU-native analogue of pointing an
EngramTemplate at an image. Names registered here take priority over
"module:attr" import paths in :func:`resolve_entrypoint`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

_lock = threading.Lock()
_registry: dict[str, Callable[..., Any]] = {}
#: framework-managed entrypoints (e.g. the materialize delegate) that
#: survive clear_registry() — tests wipe user registrations, not these
_builtins: dict[str, Callable[..., Any]] = {}


def register_engram(
    name: str, fn: Optional[Callable[..., Any]] = None, builtin: bool = False
):
    """Register an engram entrypoint; usable as a decorator.

    @register_engram("llama-generate")
    def run(ctx): ...
    """

    def apply(f: Callable[..., Any]):
        with _lock:
            _registry[name] = f
            if builtin:
                _builtins[name] = f
        return f

    if fn is not None:
        return apply(fn)
    return apply


def get_engram(name: str) -> Optional[Callable[..., Any]]:
    with _lock:
        return _registry.get(name)


def unregister_engram(name: str) -> None:
    with _lock:
        _registry.pop(name, None)


def clear_registry() -> None:
    with _lock:
        _registry.clear()
        _registry.update(_builtins)
