"""Engram SDK: env contract, runtime context, registry."""

from . import contract
from . import materialize as _materialize  # registers the builtin delegate
from .context import (
    EngramContext,
    EngramExit,
    EngramRateLimited,
    EngramTimeout,
    resolve_entrypoint,
)
from .registry import clear_registry, get_engram, register_engram, unregister_engram

__all__ = [
    "contract",
    "EngramContext",
    "EngramExit",
    "EngramRateLimited",
    "EngramTimeout",
    "resolve_entrypoint",
    "clear_registry",
    "get_engram",
    "register_engram",
    "unregister_engram",
]
