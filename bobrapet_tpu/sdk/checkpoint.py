"""Sharded model checkpointing into the blob Store (SURVEY §5.4:
"JAX/Orbax-style model checkpointing enters on the SDK side").

The workflow half of checkpoint/resume (durable StepRun state, redrive)
lives in the controllers (reference: storyrun_controller.go:295-807);
this module is the *model* half: save/restore of a whole train-state
pytree — (params, opt_state, step) or anything tree-like — against the
same storage providers run payloads use (SSD/S3/file/memory), so a
redriven training step resumes instead of re-initializing.

Layout under ``<prefix>/ckpt-<step>/``:

- ``manifest.json`` — pytree paths, per-leaf dtype/shape, saved shard
  index ranges, step number
- ``leaf-<i>/<shard-key>`` — raw little-endian bytes, one blob per
  *unique* shard index (replicas dedup'd; multi-controller gangs write
  disjoint addressable shards into a shared store)

Restore is resharding-aware: arrays are reassembled with
``jax.make_array_from_callback`` under the *target* sharding, stitching
saved shard blobs to cover whatever index ranges the new mesh asks for —
a checkpoint saved on one mesh restores onto another (the Orbax
restore-args pattern, without the filesystem dependency).
"""

from __future__ import annotations

import json
from typing import Any, Optional

import numpy as np

from ..storage.store import BlobNotFound, Store

MANIFEST_PREFIX = "manifest-"

#: final path segment of a step's canonical checkpoint prefix
#: (``runs/<ns>/<run>/steps/<step>/model-ckpt``) — shared between the
#: SDK (EngramContext.checkpoint_prefix) and the StepRun controller's
#: preemption-redrive resume probe so the two can never diverge
STEP_CHECKPOINT_FIELD = "model-ckpt"


def _manifest_key(process: int) -> str:
    return f"{MANIFEST_PREFIX}{process:05d}.json"


def _leaf_paths(tree: Any) -> tuple[list[tuple[str, Any]], Any]:
    """Flatten to [(path_string, leaf)] + treedef."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        out.append((jax.tree_util.keystr(path), leaf))
    return out, treedef


def _shard_key(index: tuple, shape: tuple[int, ...]) -> str:
    """Canonical key for a shard's global index: 'start-stop_start-stop'."""
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        parts.append(f"{start}-{stop}")
    return "_".join(parts) if parts else "scalar"


def _parse_shard_key(key: str) -> list[tuple[int, int]]:
    if key == "scalar":
        return []
    return [tuple(int(x) for x in p.split("-")) for p in key.split("_")]


def save_checkpoint(
    store: Store,
    prefix: str,
    state: Any,
    step: int,
    keep: int = 2,
    process: Optional[int] = None,
    world: Optional[int] = None,
) -> str:
    """Write one checkpoint; returns its key prefix.

    Each process writes only its addressable shards (deduplicated by
    global index) plus its OWN ``manifest-<process>.json`` — restore
    unions all processes' manifests, so gang hosts sharing a store
    cooperatively produce one complete checkpoint without clobbering
    each other's shard listings. Completeness across hosts is the
    caller's barrier (the gang executor's all-or-nothing step semantics
    provide it: a step isn't Succeeded until every host returned).
    Old checkpoints beyond ``keep`` are pruned.
    """
    import jax

    process_explicit = process is not None
    if process is None:
        process = jax.process_index()
    ckpt = f"{prefix}/ckpt-{step:012d}"
    leaves, treedef = _leaf_paths(state)
    manifest: dict[str, Any] = {
        "step": int(step),
        "treedef": str(treedef),
        "leaves": [],
    }
    for i, (path, leaf) in enumerate(leaves):
        arr_shards: list[tuple[str, np.ndarray]] = []
        if isinstance(leaf, jax.Array):
            shape = leaf.shape
            dtype = str(leaf.dtype)
            seen: set[str] = set()
            for shard in leaf.addressable_shards:
                key = _shard_key(shard.index, shape)
                if key in seen:
                    continue
                seen.add(key)
                arr_shards.append((key, np.asarray(shard.data)))
        else:
            arr = np.asarray(leaf)
            shape = arr.shape
            dtype = str(arr.dtype)
            arr_shards.append((_shard_key((), shape) if arr.ndim == 0
                               else _shard_key(tuple(slice(0, d) for d in shape), shape),
                               arr))
        for key, data in arr_shards:
            # raw little-endian bytes; bfloat16 has no portable npy
            # representation, so dtype travels in the manifest instead
            store.put(f"{ckpt}/leaf-{i}/{key}", np.ascontiguousarray(data).tobytes())
        manifest["leaves"].append({
            "path": path,
            "index": i,
            "shape": list(shape),
            "dtype": dtype,
            "shards": [k for k, _ in arr_shards],
        })
    store.put(f"{ckpt}/{_manifest_key(process)}",
              json.dumps(manifest, separators=(",", ":")).encode())

    # a re-save at the same step after shrinking the process count must
    # not leave the departed processes' manifests behind — their stale
    # sharding layout would be unioned into restores. (With an unchanged
    # process set every manifest is overwritten above, and stale blobs
    # unreferenced by any fresh manifest are never read.) Only clean when
    # the world size is certain: an explicit `process` means a simulated
    # gang where jax.process_count() does NOT reflect the gang size, and
    # guessing low would delete live peers' manifests.
    if world is None and not process_explicit:
        try:
            world = jax.process_count()
        except Exception:
            world = None
    if world is not None:
        for key in store.list(f"{ckpt}/{MANIFEST_PREFIX}"):
            idx = int(key.rsplit(MANIFEST_PREFIX, 1)[1].removesuffix(".json"))
            if idx >= max(world, process + 1):
                store.delete(key)

    if keep > 0:
        steps = sorted(checkpoint_steps(store, prefix))
        for old in steps[:-keep]:
            delete_checkpoint(store, prefix, old)
    return ckpt


def _load_merged_manifest(store: Store, ckpt: str) -> dict[str, Any]:
    """Union all processes' manifests: same structure, shard lists merged."""
    keys = [k for k in store.list(f"{ckpt}/{MANIFEST_PREFIX}")]
    if not keys:
        raise BlobNotFound(f"{ckpt}/{MANIFEST_PREFIX}*")
    merged: Optional[dict[str, Any]] = None
    for key in keys:
        m = json.loads(store.get(key))
        if merged is None:
            merged = m
            continue
        if m["treedef"] != merged["treedef"] or len(m["leaves"]) != len(merged["leaves"]):
            raise StorageMismatch(
                f"{ckpt}: manifests disagree on checkpoint structure"
            )
        for ours, theirs in zip(merged["leaves"], m["leaves"]):
            if ours["path"] != theirs["path"] or ours["shape"] != theirs["shape"]:
                raise StorageMismatch(
                    f"{ckpt}: manifests disagree on leaf {ours['path']!r}"
                )
            for shard in theirs["shards"]:
                if shard not in ours["shards"]:
                    ours["shards"].append(shard)
    return merged


def checkpoint_steps(store: Store, prefix: str) -> list[int]:
    """Steps with a manifest-bearing checkpoint, ascending."""
    steps = set()
    for key in store.list(f"{prefix}/ckpt-"):
        tail = key[len(prefix) + 1:]
        if f"/{MANIFEST_PREFIX}" in tail:
            steps.add(int(tail.split("/")[0].removeprefix("ckpt-")))
    return sorted(steps)


def latest_checkpoint_step(store: Store, prefix: str) -> Optional[int]:
    steps = checkpoint_steps(store, prefix)
    return steps[-1] if steps else None


def _manifest_covers(manifest: dict[str, Any]) -> bool:
    """True when every leaf's shard set covers its full shape (shards
    are disjoint by construction, so coverage == volume sum)."""
    for entry in manifest["leaves"]:
        total = 1
        for d in entry["shape"]:
            total *= d
        covered = 0
        for key in entry["shards"]:
            vol = 1
            for start, stop in _parse_shard_key(key):
                vol *= stop - start
            covered += vol
        if covered < total:
            return False
    return True


def latest_restorable_checkpoint_step(
    store: Store, prefix: str
) -> Optional[int]:
    """Newest step whose merged manifests cover every leaf completely.

    A preemption can land MID-SAVE: the newest step then has some
    hosts' manifests/shards missing, and advertising it (e.g. as
    ``BOBRA_RESUME_STEP``) would point resume at state that cannot
    stitch. Manifest-only check — no shard blobs are read."""
    for step in reversed(checkpoint_steps(store, prefix)):
        ckpt = f"{prefix}/ckpt-{step:012d}"
        try:
            if _manifest_covers(_load_merged_manifest(store, ckpt)):
                return step
        except (BlobNotFound, StorageMismatch, ValueError, KeyError):
            continue
    return None


def delete_checkpoint(store: Store, prefix: str, step: int) -> None:
    ckpt = f"{prefix}/ckpt-{step:012d}"
    for key in store.list(ckpt):
        store.delete(key)


def _np_dtype(name: str):
    if name == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return np.dtype(name)


def _stitch(
    store: Store,
    ckpt: str,
    entry: dict[str, Any],
    want: list[tuple[int, int]],
) -> np.ndarray:
    """Assemble the requested global index range from saved shard blobs."""
    dtype = _np_dtype(entry["dtype"])
    shape = tuple(entry["shape"])
    out_shape = tuple(stop - start for start, stop in want)
    i = entry["index"]

    # fast path: exact shard match (bytearray copy keeps the result
    # writable — frombuffer over bytes would be read-only)
    exact = "_".join(f"{a}-{b}" for a, b in want) if want else "scalar"
    if exact in entry["shards"]:
        data = store.get(f"{ckpt}/leaf-{i}/{exact}")
        return np.frombuffer(bytearray(data), dtype=dtype).reshape(out_shape)

    out = np.empty(out_shape, dtype=dtype)
    filled = 0
    for key in entry["shards"]:
        ranges = _parse_shard_key(key)
        overlap = []
        for (ws, we), (ss, se) in zip(want, ranges):
            s, e = max(ws, ss), min(we, se)
            if s >= e:
                overlap = None
                break
            overlap.append((s, e, ss, ws))
        if overlap is None:
            continue
        data = store.get(f"{ckpt}/leaf-{i}/{key}")
        shard = np.frombuffer(data, dtype=dtype).reshape(
            tuple(se - ss for ss, se in ranges)
        )
        src = tuple(slice(s - ss, e - ss) for (s, e, ss, _ws) in overlap)
        dst = tuple(slice(s - ws, e - ws) for (s, e, _ss, ws) in overlap)
        out[dst] = shard[src]
        n = 1
        for s, e, _, _ in overlap:
            n *= e - s
        filled += n
    total = 1
    for s in out_shape:
        total *= s
    if filled < total:
        raise BlobNotFound(
            f"{ckpt}/leaf-{i}: saved shards cover {filled}/{total} elements "
            f"of requested range {want} (shape {shape})"
        )
    return out


def restore_checkpoint(
    store: Store,
    prefix: str,
    like: Any,
    step: Optional[int] = None,
) -> tuple[Any, int]:
    """Load a checkpoint into the structure/shardings of ``like``.

    ``like`` supplies the pytree structure and, for jax.Array leaves,
    the target sharding each restored array is placed with (pass your
    freshly-initialized train state — its values are discarded).
    Returns (state, step). Raises BlobNotFound when no checkpoint exists.

    Without an explicit ``step``, candidates are tried newest-first: a
    preemption can land MID-SAVE, leaving the newest step with some
    hosts' manifests/shards missing — such a partial checkpoint fails
    to stitch and restore falls back to the previous complete one
    instead of surfacing the failure (which callers would turn into a
    from-scratch restart, the exact loss checkpointing exists to
    prevent).
    """
    if step is not None:
        return _restore_one(store, prefix, like, step)
    steps = checkpoint_steps(store, prefix)
    if not steps:
        raise BlobNotFound(f"{prefix}: no checkpoint found")
    last_err: Exception = BlobNotFound(f"{prefix}: no checkpoint found")
    for candidate in reversed(steps):
        try:
            return _restore_one(store, prefix, like, candidate)
        # ValueError/KeyError cover truncated/corrupt manifests (a
        # SIGKILL mid-save can leave half-written JSON) — same clause
        # as latest_restorable_checkpoint_step, so the probes agree
        except (BlobNotFound, StorageMismatch, ValueError, KeyError) as e:
            last_err = e
    raise last_err


def _restore_one(
    store: Store, prefix: str, like: Any, step: int
) -> tuple[Any, int]:
    import jax

    ckpt = f"{prefix}/ckpt-{step:012d}"
    manifest = _load_merged_manifest(store, ckpt)

    leaves, treedef = _leaf_paths(like)
    entries = manifest["leaves"]
    if len(entries) != len(leaves):
        raise StorageMismatch(
            f"{ckpt}: checkpoint has {len(entries)} leaves, "
            f"target structure has {len(leaves)}"
        )

    restored = []
    for (path, leaf), entry in zip(leaves, entries):
        if entry["path"] != path:
            raise StorageMismatch(
                f"{ckpt}: leaf order mismatch — saved {entry['path']!r}, "
                f"target {path!r}"
            )
        shape = tuple(entry["shape"])
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding") and shape:
            sharding = leaf.sharding

            def cb(index, _entry=entry, _shape=shape):
                want = [
                    (0 if sl.start is None else int(sl.start),
                     dim if sl.stop is None else int(sl.stop))
                    for sl, dim in zip(index, _shape)
                ]
                return _stitch(store, ckpt, _entry, want)

            arr = jax.make_array_from_callback(shape, sharding, cb)
        else:
            full = [(0, d) for d in shape]
            data = _stitch(store, ckpt, entry, full)
            arr = (
                jax.device_put(data, getattr(leaf, "sharding", None))
                if isinstance(leaf, jax.Array)
                else np.asarray(data).reshape(shape)
            )
            if not shape and not isinstance(leaf, (jax.Array, np.ndarray)):
                # plain python scalar leaf (e.g. int step counters)
                arr = arr.item() if hasattr(arr, "item") else arr
        restored.append(arr)
    return jax.tree_util.tree_unflatten(treedef, restored), int(manifest["step"])


class StorageMismatch(Exception):
    """Checkpoint structure does not match the restore target."""
