"""SDK-side materialize engram: in-pod offloaded-data evaluation.

The pod half of the materialize subsystem (reference:
internal/controller/runs/materialize.go — the dedicated managed engram
that "hydrates data and returns the evaluated result"). The controller
ships ``{"expression", "scope"}`` with storage refs intact; the SDK
context hydrates them lazily when ``ctx.inputs`` is read (next to the
data, on the slice), then the expression is evaluated against the fully
hydrated scope and the boolean result is reported as the step output.
"""

from __future__ import annotations

from typing import Any

from ..templating.engine import Evaluator, TemplateConfig
from .registry import register_engram

#: must match controllers/materialize.py MATERIALIZE_ENTRYPOINT
ENTRYPOINT = "bobrapet.materialize"


@register_engram(ENTRYPOINT, builtin=True)
def materialize_entrypoint(ctx) -> dict[str, Any]:
    payload = ctx.inputs  # hydrated by the SDK context
    expression = payload["expression"]
    scope = payload.get("scope") or {}
    evaluator = Evaluator(TemplateConfig())
    return {"result": bool(evaluator.evaluate_condition(expression, scope))}
