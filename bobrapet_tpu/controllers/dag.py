"""DAG engine: the workflow scheduler.

Capability parity with the reference's DAG reconciler
(reference: internal/controller/runs/dag.go — Reconcile:306,
runDagIterations:381, findReadySteps:2631, findAndLaunchReadySteps:1697,
buildDependencyGraphs:3024, findAndAddDeps:3223 (implicit deps mined
from templates), enforceStoryConcurrency:1780,
enforceSchedulingLimits:1801, checkSyncGates:1455 / SleepSteps:1217 /
WaitSteps:1291 / ParallelSteps:1112, finalizeSuccessfulRun:2871,
phases main->compensation->finally dag.go:482-511):

- sync StepRun phases into ``status.stepStates`` (branch children roll
  up into their `parallel` parent)
- dependency graph = explicit ``needs`` + implicit ``steps.X``
  references mined from ``with``/``if`` templates
- ``if`` conditions evaluated with the offloaded-data policy
- fail-fast skips, allowed failures, story timeout
- primitive timers (sleep/wait/gate/parallel/sub-story) persisted in
  ``status.stepTimers`` — restart-safe
- story/queue/global concurrency gates; queue = TPU slice pool
- saga phases: main -> compensation (on failure) -> finally -> finalize
  with the story output template (1 MiB cap)

The engine mutates ``run.status`` in place; the StoryRun controller
persists it (patch-if-changed) and requeues at the returned delay.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Any, Optional

from ..api.enums import OffloadedDataPolicy, Phase
from ..api.errors import ErrorType, StructuredError
from ..api.runs import (
    DAG_PHASE_COMPENSATION,
    DAG_PHASE_FINALLY,
    DAG_PHASE_MAIN,
    STEP_RUN_KIND,
    STORY_RUN_KIND,
    StepState,
)
from ..api.story import Step, StorySpec
from ..core.object import Resource
from ..core.store import ResourceStore
from ..observability.metrics import metrics
from ..observability.timeline import FLIGHT
from ..storage.manager import StorageManager
from ..templating.engine import (
    EvaluationBlocked,
    Evaluator,
    OffloadedDataUsage,
    TemplateError,
)
from ..utils.duration import parse_duration
from .manager import Clock
from .materialize import (
    DEFAULT_MATERIALIZE_ENGRAM,
    MaterializeFailed,
    MaterializeSpoofed,
    resolve_materialize,
)
from .step_executor import (
    LABEL_PRIORITY,
    LABEL_QUEUE,
    STOP_KEY,
    TIMERS_KEY,
    LaunchBlocked,
    StepExecutor,
)

_log = logging.getLogger(__name__)

MAX_OUTPUT_BYTES = 1 << 20  # final output template cap (reference: 1MiB)

#: stepState reasons marking a ready step parked behind a scheduling gate
#: rather than launched (reference: markQueuedSteps dag.go:1999 — queued
#: steps stay Pending; their startedAt is the queue-entry time that feeds
#: priority aging via storyRunQueuedSince:1948)
REASON_CONCURRENCY_QUEUED = "ConcurrencyQueued"
REASON_SCHEDULING_QUEUED = "SchedulingQueued"
REASON_PRIORITY_QUEUED = "PriorityQueued"
REASON_PLACEMENT_QUEUED = "PlacementQueued"
QUEUED_REASONS = frozenset(
    {
        REASON_CONCURRENCY_QUEUED,
        REASON_SCHEDULING_QUEUED,
        REASON_PRIORITY_QUEUED,
        REASON_PLACEMENT_QUEUED,
    }
)


def _is_queued_state(raw: dict[str, Any]) -> bool:
    return (
        raw.get("phase") in (None, str(Phase.PENDING))
        and raw.get("reason") in QUEUED_REASONS
    )


#: raw-dict phase tests for the per-pass loops: constructing a StepState
#: per step per pass was a top soak profile term, and these checks only
#: need the phase string (StrEnum values ARE their strings)
_TERMINAL_RAW = frozenset(str(p) for p in Phase if p.is_terminal)
_FAILURE_RAW = frozenset(str(p) for p in Phase if p.is_failure)


def _raw_terminal(raw: Optional[dict[str, Any]]) -> bool:
    return bool(raw) and raw.get("phase") in _TERMINAL_RAW


def _raw_failure(raw: Optional[dict[str, Any]]) -> bool:
    return bool(raw) and raw.get("phase") in _FAILURE_RAW


def effective_priority(
    base: int, queued_since: Optional[float], aging_seconds: float, now: float
) -> int:
    """Priority grows one step per aging interval spent queued
    (reference: effectivePriority dag.go:1948)."""
    if queued_since is None or aging_seconds <= 0:
        return base
    elapsed = now - queued_since
    if elapsed <= 0:
        return base
    return base + int(elapsed // aging_seconds)


def storyrun_queued_since(run: Resource) -> Optional[float]:
    """Earliest queue-entry time across this run's queued steps
    (reference: storyRunQueuedSince dag.go:1962)."""
    earliest: Optional[float] = None
    for raw in (run.status.get("stepStates") or {}).values():
        if not _is_queued_state(raw):
            continue
        t = raw.get("startedAt")
        if t is not None and (earliest is None or t < earliest):
            earliest = t
    return earliest


def storyrun_has_demand(run: Resource) -> bool:
    """A run competes for queue capacity while it is live or has queued
    steps (reference: storyRunHasDemand dag.go:1981). Running runs count
    as demand deliberately, mirroring the reference: strict priority
    ordering reserves freed capacity for the highest-priority live run's
    next step, at the cost of briefly idling slots (bounded by aging).
    A run parked Pending by a guard (story missing, reference denied —
    recorded as status.reason) cannot launch anything and must not
    starve its queue peers."""
    phase = run.status.get("phase")
    states = run.status.get("stepStates") or {}
    if phase == str(Phase.RUNNING):
        return True
    if phase == str(Phase.PENDING) and not run.status.get("reason"):
        return True  # freshly admitted, about to launch
    # guard-parked (status.reason set): only live step activity counts
    return any(
        raw.get("phase") == str(Phase.RUNNING) or _is_queued_state(raw)
        for raw in states.values()
    )

#: index names (registered by the runtime)
INDEX_STEPRUN_STORYRUN = "storyRunRef"
INDEX_STEPRUN_PHASE = "phase"
#: queue-cap gate index: non-terminal StepRuns keyed by their queue
#: label, plus one all-queues bucket for the global cap. Registered by
#: the engine itself (add_index is idempotent + backfills), so the
#: O(1) gate can never silently degrade to a scan.
INDEX_STEPRUN_QUEUE_ACTIVE = "queueActive"
ACTIVE_ALL_BUCKET = "\x00all"  # cannot collide with a label value


def _queue_active_index(r: Resource) -> list[str]:
    from ..api.enums import is_nonterminal_phase

    # empty phase = not-yet-claimed StepRun: it competes for capacity
    if not is_nonterminal_phase(r.status.get("phase"), empty_is_active=True):
        return []
    out = [ACTIVE_ALL_BUCKET]
    q = r.meta.labels.get(LABEL_QUEUE)
    if q:
        out.append(q)
    return out


class DAGEngine:
    def __init__(
        self,
        store: ResourceStore,
        evaluator: Evaluator,
        executor: StepExecutor,
        config_manager,
        storage: StorageManager,
        recorder=None,
        clock: Optional[Clock] = None,
    ):
        self.store = store
        self.evaluator = evaluator
        self.executor = executor
        self.config_manager = config_manager
        self.storage = storage
        self.recorder = recorder
        self.clock = clock or Clock()
        #: sharded control plane (bobrapet_tpu/shard): when set, the
        #: GLOBAL concurrency cap counts only StepRuns whose run family
        #: this manager owns — `scheduling.global-max-concurrent-steps`
        #: is per-manager dispatch capacity, so N shards each get their
        #: own budget. Named queue caps stay bus-global (user-facing
        #: admission invariants, counted over the shared store).
        self.owned_filter = None
        #: per-pass launch counter; thread-local because the StoryRun
        #: controller's pool runs several DAG passes concurrently
        self._pass = threading.local()
        #: serializes the check-then-reserve window of the CROSS-RUN
        #: scheduling gates (queue caps, global cap, priority ordering)
        #: across concurrent StoryRun reconciles — without it two runs
        #: could both read "capacity free" and both launch past a
        #: queue's max-concurrent. Taken only when such a gate applies;
        #: uncapped stories launch lock-free. The expensive launch
        #: itself (template eval, storage offload, StepRun commit) runs
        #: OUTSIDE the lock against an in-memory reservation, so a slow
        #: materialization cannot head-of-line-block other runs' gates.
        #: The (lock, reservations) pair is BUS-WIDE (store.
        #: scheduling_gate()): named-queue caps are user-facing
        #: admission invariants counted over the shared store, so N
        #: sharded managers gating under process-local locks could each
        #: admit one step over a cap in the same instant.
        self._sched_lock, self._sched_reserved = store.scheduling_gate()
        #: the GLOBAL cap's reservation bucket is per-ENGINE: that cap
        #: is shard-local dispatch capacity (see owned_filter above),
        #: so one shard's in-flight reservations must not shrink
        #: another's budget. Named queues share their string keys.
        #: pid + id: in process mode the gate map is served centrally,
        #: and id(self) alone collides across interpreters
        self._global_bucket = ("global", os.getpid(), id(self))
        #: runs parked behind a capacity gate (queueWaiting /
        #: placementWaiting) as of their last reconcile. A terminal
        #: StepRun frees capacity, so the runtime wakes entries from
        #: here event-driven (wake_capacity_parked) instead of leaning
        #: on the scheduling.queue-probe-interval timer alone — at N
        #: shards the timer-poll churn of a parked population was the
        #: dominant control-plane CPU cost (GIL-bound), while the event
        #: wake costs one enqueue per freed slot. Entries are popped at
        #: wake time; a still-gated run re-parks itself on its own
        #: reconcile, so stale keys self-heal.
        self.capacity_parked: set[tuple[str, str]] = set()
        store.add_index(STEP_RUN_KIND, INDEX_STEPRUN_QUEUE_ACTIVE,
                        _queue_active_index)

    # ------------------------------------------------------------------
    def run(self, run: Resource, story: StorySpec) -> Optional[float]:
        """One DAG reconcile pass. Returns requeue delay or None."""
        from ..observability.tracing import TRACER

        before = run.status.get("phase")
        # prior park state from the COMMITTED status, not capacity_parked
        # membership: the event-driven wake pops keys from that set, so a
        # still-gated run would look "newly parked" on every wake and
        # flood its ring with identical queued records
        was_parked = bool(
            run.status.get("queueWaiting") or run.status.get("placementWaiting")
        )
        # feature-gated span, parented on the run's persisted trace
        # (reference: StartSpan in reconcilers, storyrun_controller.go:217)
        with TRACER.start_span(
            "dag.reconcile",
            trace_context=run.status.get("trace"),
            run=run.meta.name,
            namespace=run.meta.namespace,
        ):
            result = self._run(run, story)
        key = (run.meta.namespace, run.meta.name)
        if run.status.get("queueWaiting") or run.status.get("placementWaiting"):
            if not was_parked:
                # transition INTO the park (not every re-probe or wake):
                # the queued-reason is the forensic fact a dead run's
                # timeline needs — "it waited here, on this"
                FLIGHT.record(
                    key[0], key[1], "queued",
                    message=str(
                        run.status.get("placementWaiting")
                        or "queued behind scheduling limits"
                    ),
                    at=self.clock.now(),
                )
            self.capacity_parked.add(key)
        else:
            self.capacity_parked.discard(key)
        after = run.status.get("phase")
        if after != before and after:
            FLIGHT.record(key[0], key[1], "phase",
                          message=f"{before or 'created'} -> {after}",
                          at=self.clock.now())
            if Phase(after).is_terminal:
                metrics.storyrun_total.inc(after)
                started = run.status.get("startedAt")
                finished = run.status.get("finishedAt")
                if started is not None and finished is not None:
                    story_name = (run.spec.get("storyRef") or {}).get("name", "")
                    metrics.storyrun_duration.observe(
                        float(finished) - float(started), story_name
                    )
                if Phase(after).is_failure:
                    # a dead run explains itself: the causal tail rides
                    # the terminal status (the ring itself is reaped
                    # with the run; status survives until retention)
                    err = run.status.get("error") or {}
                    if err:
                        FLIGHT.record(
                            key[0], key[1], "error",
                            message=str(err.get("message") or "")[:512],
                            at=self.clock.now(),
                        )
                    run.status["forensics"] = FLIGHT.tail(key[0], key[1], 20)
                # critical-path analysis on EVERY terminal run: a
                # compact where-did-the-wall-clock-go rides the status;
                # the full breakdown recomputes behind
                # /debug/runs/<id>/critical-path from the same ring
                from ..observability.analytics import (
                    analyze_run,
                    compact_analysis,
                )

                analysis = analyze_run(
                    run.status, FLIGHT.timeline(key[0], key[1])
                )
                if analysis is not None:
                    run.status["analysis"] = compact_analysis(analysis)
        return result

    def _run(self, run: Resource, story: StorySpec) -> Optional[float]:
        status = run.status
        status.setdefault("phase", str(Phase.RUNNING))
        status.setdefault("dagPhase", DAG_PHASE_MAIN)
        status.setdefault("stepStates", {})
        status.setdefault("startedAt", self.clock.now())

        self._sync_state_from_stepruns(run)

        if self._enforce_story_timeout(run, story):
            return None

        # bounded iteration (reference: <= steps+1, runDagIterations:381)
        total_steps = len(story.all_steps()) + 1
        self._pass.launched = 0
        try:
            for _ in range(total_steps + 1):
                progressed = self._sync_timers(run, story)
                if status.get(STOP_KEY):
                    self._advance_to_finally_or_finalize(run, story, stop=True)
                phase_steps = self._current_phase_steps(run, story)
                progressed |= self._apply_skips(run, story, phase_steps)
                progressed |= self._launch_ready(run, story, phase_steps)
                if self._maybe_advance_phase(run, story):
                    progressed = True
                if Phase(status["phase"]).is_terminal:
                    return None
                if not progressed:
                    break
        finally:
            metrics.dag_iterations.observe(self._pass.launched)

        return self._next_wakeup(run, story)

    def wake_capacity_parked(self, limit: int = 4) -> list[tuple[str, str]]:
        """Pop up to ``limit`` capacity-parked run keys for an
        event-driven requeue (one freed slot rarely admits more than a
        few runs; the popped run re-parks itself if still gated)."""
        out: list[tuple[str, str]] = []
        while len(out) < limit:
            try:
                out.append(self.capacity_parked.pop())
            except KeyError:
                break
        return out

    # ------------------------------------------------------------------
    # state sync
    # ------------------------------------------------------------------
    def _sync_state_from_stepruns(self, run: Resource) -> None:
        """(reference: syncStateFromStepRuns:965)"""
        states = run.status["stepStates"]
        # read-only views: this sync runs on EVERY StoryRun reconcile
        # and deep-copying the whole child population was the dominant
        # per-reconcile linear cost (merged values aliasing child
        # status are isolated by the write-boundary copy on persist)
        children = self.store.list_views(
            STEP_RUN_KIND,
            namespace=run.meta.namespace,
            index=(INDEX_STEPRUN_STORYRUN, run.meta.name),
        )
        by_name: dict[str, Resource] = {}
        #: per-child max-merge ledger: entries survive child retention, so
        #: the run-level tally keeps counting after early children reap
        #: (a plain sum over live children would freeze at the old total)
        ledger = run.status.get("preemptionsByStep")
        for sr in children:
            step_id = sr.spec.get("stepId") or sr.meta.labels.get("bobrapet.io/step", "")
            by_name[sr.meta.name] = sr
            p = int(sr.status.get("preemptions") or 0)
            if p:
                if ledger is None:
                    ledger = run.status.setdefault("preemptionsByStep", {})
                ledger[sr.meta.name] = max(int(ledger.get(sr.meta.name) or 0), p)
            if sr.meta.labels.get("bobrapet.io/parent-step"):
                continue  # branch child: rolled up by the parallel timer
            if step_id:
                states[step_id] = _merge_steprun_state(
                    states.get(step_id) or {}, sr
                )
        # fleet recovery surfaces on the run: total redrives + condition
        # (child StepRuns are retention-reaped; the run keeps the record)
        if ledger:
            preemptions = sum(int(v) for v in ledger.values())
            if preemptions > int(run.status.get("preemptions") or 0):
                from ..api import conditions as api_conditions

                run.status["preemptions"] = preemptions
                api_conditions.set_condition(
                    run.status.setdefault("conditions", []),
                    api_conditions.PREEMPTION_RECOVERED, True,
                    api_conditions.Reason.PREEMPTION_REDRIVE,
                    f"{preemptions} slice preemption(s) recovered by redrive",
                    now=self.clock.now(),
                )

    # ------------------------------------------------------------------
    # timers (reference: checkSync{Sleep,Wait,Gate,Parallel}Steps)
    # ------------------------------------------------------------------
    def _sync_timers(self, run: Resource, story: StorySpec) -> bool:
        timers: dict[str, Any] = run.status.get(TIMERS_KEY) or {}
        if not timers:
            return False
        states = run.status["stepStates"]
        progressed = False
        now = self.clock.now()
        scope = self._scope(run)
        for step_name in list(timers.keys()):
            t = timers[step_name]
            raw_state = states.get(step_name) or {}
            if _raw_terminal(raw_state):
                timers.pop(step_name, None)
                continue
            state = StepState.from_dict(raw_state)
            kind = t.get("kind")
            if kind == "sleep" and now >= t.get("due", 0):
                states[step_name] = _finish(state, Phase.SUCCEEDED, now).to_dict()
                timers.pop(step_name, None)
                progressed = True
            elif kind == "wait":
                progressed |= self._sync_wait(run, step_name, t, state, scope, now)
            elif kind == "gate":
                progressed |= self._sync_gate(run, step_name, t, state, now)
            elif kind == "parallel":
                progressed |= self._sync_parallel(run, story, step_name, t, state, now)
            elif kind == "subStory":
                progressed |= self._sync_substory(run, step_name, t, state, now)
        run.status[TIMERS_KEY] = timers
        return progressed

    def _sync_wait(self, run, step_name, t, state, scope, now) -> bool:
        states = run.status["stepStates"]
        if now >= t.get("deadline", float("inf")):
            outcome = Phase.SKIPPED if t.get("onTimeout") == "skip" else Phase.TIMEOUT
            states[step_name] = _finish(state, outcome, now, reason="WaitTimeout").to_dict()
            run.status[TIMERS_KEY].pop(step_name, None)
            return True
        if now < t.get("nextPoll", 0):
            return False
        t["nextPoll"] = now + t.get("pollInterval", 5.0)
        try:
            ok = self.evaluator.evaluate_condition(t.get("until", ""), scope)
        except OffloadedDataUsage:
            try:
                ok = self._condition_with_policy(
                    run, step_name, t.get("until", ""), scope
                )
            except (OffloadedDataUsage, MaterializeFailed, MaterializeSpoofed) as e:
                # policy=fail (or broken delegate): the wait step fails
                # terminally instead of the reconcile crashing into
                # endless backoff
                states[step_name] = _finish(
                    state, Phase.FAILED, now, reason="OffloadedDataPolicy"
                ).to_dict()
                states[step_name]["message"] = str(e)
                run.status[TIMERS_KEY].pop(step_name, None)
                return True
            if ok is None:
                return False  # materialize delegate pending; poll again
            if not ok:
                # a wait polls a CHANGING condition: consume the completed
                # delegate so the next poll re-materializes fresh scope
                from .materialize import materialize_name

                try:
                    self.store.delete(
                        STEP_RUN_KIND, run.meta.namespace,
                        materialize_name(run.meta.name, step_name),
                    )
                except Exception:  # noqa: BLE001 - already gone is fine
                    pass
        except TemplateError:
            ok = False
        if ok:
            states[step_name] = _finish(state, Phase.SUCCEEDED, now).to_dict()
            run.status[TIMERS_KEY].pop(step_name, None)
            return True
        return False

    def _sync_gate(self, run, step_name, t, state, now) -> bool:
        """Decision arrives via status.gates[step] patch
        (reference: checkSyncGates:1455)."""
        states = run.status["stepStates"]
        gates = run.status.get("gates") or {}
        decision = gates.get(step_name)
        if decision is not None and decision.get("approved") is not None:
            approved = bool(decision.get("approved"))
            outcome = Phase.SUCCEEDED if approved else Phase.FAILED
            reason = "GateApproved" if approved else "GateRejected"
            states[step_name] = _finish(state, outcome, now, reason=reason).to_dict()
            run.status[TIMERS_KEY].pop(step_name, None)
            return True
        if now >= t.get("deadline", float("inf")):
            outcome = Phase.SKIPPED if t.get("onTimeout") == "skip" else Phase.TIMEOUT
            states[step_name] = _finish(state, outcome, now, reason="GateTimeout").to_dict()
            run.status[TIMERS_KEY].pop(step_name, None)
            return True
        return False

    def _sync_parallel(self, run, story, step_name, t, state, now) -> bool:
        """All children terminal -> parent terminal; non-allowFailure child
        failure fails the parent (reference: dag.go:1112-1200)."""
        states = run.status["stepStates"]
        children = t.get("children") or []
        child_states = []
        for c in children:
            sr = self.store.try_get_view(STEP_RUN_KIND, run.meta.namespace, c["stepRun"])
            phase = Phase(sr.status["phase"]) if sr is not None and sr.status.get("phase") else Phase.PENDING
            child_states.append((c, sr, phase))
        if not all(p.is_terminal for (_, _, p) in child_states):
            return False
        failed = [
            c["name"]
            for (c, _, p) in child_states
            if p.is_failure and not c.get("allowFailure")
        ]
        outputs = {
            c["name"]: (sr.status.get("output") if sr is not None else None)
            for (c, sr, _) in child_states
        }
        outcome = Phase.FAILED if failed else Phase.SUCCEEDED
        new_state = _finish(state, outcome, now,
                            reason=f"BranchesFailed:{','.join(failed)}" if failed else None)
        new_state.output = outputs
        states[step_name] = new_state.to_dict()
        run.status[TIMERS_KEY].pop(step_name, None)
        return True

    def _sync_substory(self, run, step_name, t, state, now) -> bool:
        """(reference: refreshAfterSubStoriesIfNeeded:652, sub-story output
        collection)"""
        states = run.status["stepStates"]
        child = self.store.try_get_view(STORY_RUN_KIND, run.meta.namespace, t.get("storyRun", ""))
        if child is None:
            states[step_name] = _finish(
                state, Phase.FAILED, now, reason="SubStoryVanished"
            ).to_dict()
            run.status[TIMERS_KEY].pop(step_name, None)
            return True
        phase = Phase(child.status["phase"]) if child.status.get("phase") else Phase.PENDING
        if not phase.is_terminal:
            return False
        outcome = Phase.SUCCEEDED if phase is Phase.SUCCEEDED else Phase.FAILED
        new_state = _finish(state, outcome, now,
                            reason=None if outcome is Phase.SUCCEEDED else f"SubStory{phase}")
        new_state.output = child.status.get("output")
        states[step_name] = new_state.to_dict()
        run.status[TIMERS_KEY].pop(step_name, None)
        return True

    # ------------------------------------------------------------------
    # phases
    # ------------------------------------------------------------------
    def _current_phase_steps(self, run: Resource, story: StorySpec) -> list[Step]:
        dag_phase = run.status.get("dagPhase", DAG_PHASE_MAIN)
        if dag_phase == DAG_PHASE_COMPENSATION:
            return story.compensations
        if dag_phase == DAG_PHASE_FINALLY:
            return story.finally_
        return story.steps

    def _maybe_advance_phase(self, run: Resource, story: StorySpec) -> bool:
        """main -> compensation (on failure) -> finally -> finalize
        (reference: dag.go:482-511)."""
        status = run.status
        dag_phase = status.get("dagPhase", DAG_PHASE_MAIN)
        steps = self._current_phase_steps(run, story)
        states = status["stepStates"]
        if steps and not all(_raw_terminal(states.get(s.name)) for s in steps):
            return False
        if dag_phase == DAG_PHASE_MAIN:
            failed = self._main_failed(run, story)
            if failed and story.compensations:
                status["dagPhase"] = DAG_PHASE_COMPENSATION
                return True
            if story.finally_:
                status["dagPhase"] = DAG_PHASE_FINALLY
                return True
            self._finalize(run, story)
            return True
        if dag_phase == DAG_PHASE_COMPENSATION:
            if story.finally_:
                status["dagPhase"] = DAG_PHASE_FINALLY
                return True
            self._finalize(run, story)
            return True
        self._finalize(run, story)
        return True

    def _advance_to_finally_or_finalize(self, run: Resource, story: StorySpec, stop=False) -> None:
        """Stop primitive: skip unstarted main steps, then finally/finalize
        (reference: executeStopStep terminal semantics)."""
        states = run.status["stepStates"]
        now = self.clock.now()
        for s in self._current_phase_steps(run, story):
            st = StepState.from_dict(states.get(s.name) or {})
            if not st.is_terminal and (states.get(s.name) is None or st.effective_phase is Phase.PENDING):
                states[s.name] = _finish(st, Phase.SKIPPED, now, reason="StoryStopped").to_dict()

    def _main_failed(self, run: Resource, story: StorySpec) -> bool:
        states = run.status["stepStates"]
        for s in story.steps:
            if _raw_failure(states.get(s.name)) and not s.allow_failure:
                return True
        return False

    # ------------------------------------------------------------------
    # skips + readiness
    # ------------------------------------------------------------------
    def _apply_skips(self, run: Resource, story: StorySpec, steps: list[Step]) -> bool:
        """Fail-fast: when a non-allowFailure step failed, unstarted steps
        of the phase are skipped (reference: fail-fast skips dag.go:3289).
        Honors policy.retries.continueOnStepFailure."""
        status = run.status
        states = status["stepStates"]
        continue_on_failure = bool(
            story.policy
            and story.policy.retries
            and story.policy.retries.continue_on_step_failure
        )
        if continue_on_failure:
            return False
        if status.get("dagPhase", DAG_PHASE_MAIN) != DAG_PHASE_MAIN:
            return False  # compensation/finally always run fully
        if not self._main_failed(run, story):
            return False
        progressed = False
        now = self.clock.now()
        for s in steps:
            # queued markers are parked, not launched — fail-fast reclaims
            # them exactly like never-started steps
            if s.name not in states or _is_queued_state(states[s.name]):
                states[s.name] = StepState(
                    phase=Phase.SKIPPED,
                    reason="FailFast",
                    started_at=now,
                    finished_at=now,
                ).to_dict()
                progressed = True
        return progressed

    def _launch_ready(self, run: Resource, story: StorySpec, steps: list[Step]) -> bool:
        """(reference: findAndLaunchReadySteps:1697 + findReadySteps:2631)"""
        states = run.status["stepStates"]
        progressed = False
        queue = story.policy.queue if story.policy else None
        by_name = {s.name: s for s in steps}
        # gate results computed lazily, once per pass, only when a step is
        # launchable; the concurrency verdict is invalidated after each
        # launch (a launch is the only in-pass event that changes counts)
        priority_block: Optional[bool] = None
        queued_verdict: Optional[tuple[Optional[str]]] = None
        # recomputed each pass: set again below iff some step is still
        # waiting on a materialize delegate (a per-pass aggregate, not
        # per-step state — clearing here avoids both clobbering between
        # steps and leaking the 1s requeue after a delegate failure)
        run.status.pop("materializeWaiting", None)

        # one scope per pass, patched incrementally: a step that
        # completes earlier in this same pass (condition/stop/instant
        # primitives) must be visible to later steps' `if`/`with`
        # evaluation, but rebuilding the whole scope per candidate made
        # every pass O(steps^2) in StepState parses
        scope = self._scope(run)

        def touch(name: str) -> None:
            scope["steps"][name] = _scope_entry(states[name])

        placement_parks = 0
        for step in steps:
            if step.name in states and not _is_queued_state(states[step.name]):
                continue
            deps = set(step.needs)
            deps |= {
                d
                for d in step.template_step_refs()
                if d in by_name or story.step(d) is not None
            }
            # realtime pattern: `needs` between engram steps are STREAM
            # edges — a Running upstream topology satisfies them; only
            # batch semantics require terminal deps
            # (reference: realtime topology, steprun_controller.go:2527;
            # wait/gate rejected in realtime by admission)
            realtime = story.effective_pattern.is_realtime

            def dep_satisfied(d: str) -> bool:
                raw = states.get(d)
                if raw is None:
                    return False
                if _raw_terminal(raw):
                    return True
                if realtime and raw.get("phase") == str(Phase.RUNNING):
                    dep_def = by_name.get(d) or story.step(d)
                    return bool(dep_def is not None and dep_def.ref is not None)
                return False

            if any(not dep_satisfied(d) for d in deps):
                continue

            # dependency failure/skip propagation
            blocked_reason = None
            for d in deps:
                raw = states[d]
                dep_def = by_name.get(d) or story.step(d)
                if _raw_failure(raw) and not (dep_def and dep_def.allow_failure):
                    blocked_reason = "DependencyFailed"
                elif raw.get("phase") == str(Phase.SKIPPED):
                    blocked_reason = "DependencySkipped"
            now = self.clock.now()
            if blocked_reason:
                states[step.name] = StepState(
                    phase=Phase.SKIPPED, reason=blocked_reason,
                    started_at=now, finished_at=now,
                ).to_dict()
                touch(step.name)
                progressed = True
                continue

            # `if` condition (reference: findReadySteps:2631 + offloaded
            # policy fail/inject/materialize)
            if step.if_:
                try:
                    ok = self.evaluator.evaluate_condition(step.if_, scope)
                except OffloadedDataUsage:
                    try:
                        ok = self._condition_with_policy(
                            run, step.name, step.if_, scope
                        )
                    except (
                        OffloadedDataUsage,
                        MaterializeFailed,
                        MaterializeSpoofed,
                    ) as e:
                        states[step.name] = StepState(
                            phase=Phase.FAILED, reason="OffloadedDataPolicy",
                            message=str(e), started_at=now, finished_at=now,
                        ).to_dict()
                        touch(step.name)
                        progressed = True
                        continue
                    if ok is None:
                        # materialize delegate still running: the step is
                        # not ready yet (reference: resolveMaterialize
                        # blocks readiness, materialize.go:326)
                        run.status["materializeWaiting"] = True
                        continue
                except (TemplateError, EvaluationBlocked) as e:
                    states[step.name] = StepState(
                        phase=Phase.FAILED, reason="ExpressionFailed",
                        message=str(e), started_at=now, finished_at=now,
                    ).to_dict()
                    touch(step.name)
                    progressed = True
                    continue
                if not ok:
                    states[step.name] = StepState(
                        phase=Phase.SKIPPED, reason="ConditionFalse",
                        started_at=now, finished_at=now,
                    ).to_dict()
                    touch(step.name)
                    progressed = True
                    continue

            # scheduling gates (reference: enforceStoryConcurrency:1780,
            # enforceSchedulingLimits:1801, enforcePriorityOrdering:1910).
            # A gated step is parked Pending with a queued reason; its
            # startedAt is the queue-entry time that drives priority aging.
            # Cross-run caps (queue/global) are check-then-launch: when one
            # applies, the check-then-RESERVE window is serialized under
            # _sched_lock across concurrent StoryRun workers and the
            # verdict is recomputed per candidate — the lazy per-pass
            # cache is only sound when no other worker can launch between
            # candidates. The launch itself runs OUTSIDE the lock against
            # the reservation; between the StepRun commit and _unreserve
            # the launch is briefly counted twice (index + reservation),
            # which can only park a peer BELOW the cap for that window —
            # conservative, never a breach, healed by the 1s queueWaiting
            # requeue.
            gated = bool(queue) or bool(
                self.config_manager.config.scheduling.global_max_concurrent_steps
            )
            with self._sched_lock if gated else contextlib.nullcontext():
                if gated or priority_block is None:
                    priority_block = self._priority_blocked(run, story, queue)
                if priority_block:
                    queued_reason: Optional[str] = REASON_PRIORITY_QUEUED
                else:
                    if gated or queued_verdict is None:
                        queued_verdict = (
                            self._concurrency_queued_reason(run, story, queue),
                        )
                    queued_reason = queued_verdict[0]
                if queued_reason is not None:
                    prior = states.get(step.name)
                    queued_at = (
                        prior.get("startedAt")
                        if prior and _is_queued_state(prior)
                        else None
                    )
                    if queued_at is None:
                        queued_at = self.clock.now()
                    states[step.name] = StepState(
                        phase=Phase.PENDING, reason=queued_reason,
                        message=f"queued behind scheduling limits ({queued_reason})",
                        started_at=queued_at,
                    ).to_dict()
                    touch(step.name)
                    run.status["queueWaiting"] = True
                    continue
                if gated:
                    # capacity reserved under the lock; the launch runs
                    # OUTSIDE it so slow materialization cannot stall
                    # every other run's gate
                    self._reserve_locked(queue)
            run.status.pop("queueWaiting", None)

            try:
                try:
                    state = self.executor.execute(run, story, step, scope, queue=queue)
                except LaunchBlocked as e:
                    # gang/slice capacity: park THIS step Pending and keep
                    # launching siblings — the allocator's fast-negative
                    # NoCapacity makes the re-probe O(1), and a full pool
                    # must not stall ready steps that need no TPU (or a
                    # different pool). The seed aborted the whole pass here.
                    run.status["placementWaiting"] = str(e)
                    placement_parks += 1
                    prior = states.get(step.name)
                    if not (prior and _is_queued_state(prior)):
                        # first park only — the 1s re-probe while parked
                        # must not flood the ring with identical records
                        FLIGHT.record(
                            run.meta.namespace, run.meta.name,
                            "no-capacity", message=str(e), step=step.name,
                            at=self.clock.now(),
                        )
                    parked_at = (
                        prior.get("startedAt")
                        if prior and _is_queued_state(prior)
                        else None
                    )
                    states[step.name] = StepState(
                        phase=Phase.PENDING, reason=REASON_PLACEMENT_QUEUED,
                        message=str(e),
                        started_at=parked_at or self.clock.now(),
                    ).to_dict()
                    touch(step.name)
                    continue
                except Exception as e:  # noqa: BLE001 - launch failure fails the step
                    state = StepState(
                        phase=Phase.FAILED, reason="LaunchFailed", message=str(e),
                        started_at=self.clock.now(), finished_at=self.clock.now(),
                    )
            finally:
                if gated:
                    # the committed StepRun (if any) is in the index now;
                    # drop the reservation either way
                    self._unreserve(queue)
            states[step.name] = state.to_dict()
            touch(step.name)
            self._pass.launched += 1
            queued_verdict = None  # counts changed; re-check the gate
            progressed = True
            if run.status.get(STOP_KEY):
                break  # a stop primitive halts further launches immediately
        if not placement_parks:
            # no step parked on capacity THIS pass: clear the 1s
            # placement requeue (clearing per-launch instead would let a
            # later sibling's success erase an earlier park's wakeup)
            run.status.pop("placementWaiting", None)
        return progressed

    def _condition_with_policy(
        self, run: Resource, step_name: str, expr: str, scope
    ) -> Optional[bool]:
        """Offloaded-data policy for conditions
        (reference: templating_policy.go fail/inject/controller +
        materialize.go). ``fail`` raises; ``inject`` hydrates in-process
        and re-evaluates; ``controller`` delegates to a dedicated
        materialize StepRun and returns None until it completes."""
        policy = self.config_manager.config.templating.offloaded_data_policy
        if policy is OffloadedDataPolicy.FAIL:
            raise OffloadedDataUsage("offloaded data in condition under policy=fail")
        if policy is OffloadedDataPolicy.CONTROLLER:
            engram = (
                self.config_manager.config.templating.materialize_engram
                or DEFAULT_MATERIALIZE_ENGRAM
            )
            return resolve_materialize(
                self.store, run, step_name, expr, scope, engram
            )
        prefix = f"runs/{run.meta.namespace}/{run.meta.name}"
        hydrated = {
            k: self.storage.hydrate(v, [prefix]) if k in ("inputs", "steps") else v
            for k, v in scope.items()
        }
        return self.evaluator.evaluate_condition(expr, hydrated)

    def _concurrency_queued_reason(
        self, run: Resource, story: StorySpec, queue: Optional[str]
    ) -> Optional[str]:
        """Story / queue / global concurrency gates; returns the queued
        reason when the step must wait (reference:
        enforceStoryConcurrency:1780 + enforceSchedulingLimits:1801).
        Queued markers are parked, not running — they never count against
        the limits that parked them."""
        states = run.status["stepStates"]
        running_here = sum(
            1
            for raw in states.values()
            if not _raw_terminal(raw) and not _is_queued_state(raw)
        )
        limit = story.policy.concurrency if story.policy else None
        if limit is not None:
            # per-run scope for gauges (concurrent runs of one story
            # each have their own usage; the series is deleted when the
            # run turns terminal — see _observe_terminal); the counter
            # stays story-scoped so its cardinality is bounded
            scope = f"storyrun:{run.meta.namespace}/{run.meta.name}"
            story_name = (run.spec.get("storyRef") or {}).get("name", "")
            metrics.quota_usage.set(running_here, scope)
            metrics.quota_limit.set(limit, scope)
            if running_here >= limit:
                metrics.quota_violations.inc(
                    f"story:{run.meta.namespace}/{story_name}"
                )
                return REASON_CONCURRENCY_QUEUED
        cfg = self.config_manager.config.scheduling
        if queue:
            q = cfg.queue(queue)
            if q.max_concurrent:
                active = self._active_stepruns_in_queue(queue)
                metrics.quota_usage.set(active, f"queue:{queue}")
                metrics.quota_limit.set(q.max_concurrent, f"queue:{queue}")
                if active >= q.max_concurrent:
                    metrics.quota_violations.inc(f"queue:{queue}")
                    return REASON_SCHEDULING_QUEUED
        if cfg.global_max_concurrent_steps:
            active = self._active_stepruns_in_queue(None)
            metrics.quota_usage.set(active, "global")
            metrics.quota_limit.set(cfg.global_max_concurrent_steps, "global")
            if active >= cfg.global_max_concurrent_steps:
                metrics.quota_violations.inc("global")
                return REASON_SCHEDULING_QUEUED
        return None

    def _priority_blocked(
        self, run: Resource, story: StorySpec, queue: Optional[str]
    ) -> bool:
        """Defer this run's launches while another run in the same queue
        has strictly higher effective (aged) priority and live demand
        (reference: enforcePriorityOrdering dag.go:1910)."""
        if not queue:
            return False
        qcfg = self.config_manager.config.scheduling.queue(queue)
        aging = qcfg.priority_aging_seconds
        now = self.clock.now()
        base = (
            story.policy.priority
            if story.policy and story.policy.priority is not None
            else 0
        )
        my_queued_since = storyrun_queued_since(run)
        mine = effective_priority(base, my_queued_since, aging, now)
        waiting = 0  # runs actually parked (queued steps), for the gauge
        blocked = False
        for other in self.store.list_views(STORY_RUN_KIND, labels={LABEL_QUEUE: queue}):
            if (
                other.meta.namespace == run.meta.namespace
                and other.meta.name == run.meta.name
            ):
                continue
            phase = other.status.get("phase")
            if phase and Phase(phase).is_terminal:
                continue
            other_queued_since = storyrun_queued_since(other)
            if other_queued_since is not None:
                waiting += 1
            if not storyrun_has_demand(other):
                continue
            try:
                other_base = int(other.meta.labels.get(LABEL_PRIORITY, "0"))
            except ValueError:
                other_base = 0
            other_eff = effective_priority(other_base, other_queued_since, aging, now)
            if other_eff > mine:
                blocked = True
        if blocked or my_queued_since is not None:
            waiting += 1  # this run is (or is about to be) parked
        metrics.storyrun_queue_depth.set(waiting, queue)
        if blocked and my_queued_since is not None:
            metrics.storyrun_queue_age.observe(now - my_queued_since, queue)
        return blocked

    #: non-terminal phase-index buckets (the phase index is keyed by the
    #: literal status value; "" covers not-yet-claimed StepRuns)
    _ACTIVE_PHASES = ("", str(Phase.PENDING), str(Phase.RUNNING),
                      str(Phase.SCHEDULING), str(Phase.PAUSED), str(Phase.BLOCKED))

    def _active_stepruns_in_queue(self, queue: Optional[str]) -> int:
        # copy-free count over the self-registered queue-active index:
        # this gate runs per launch attempt, and deep-copy-listing
        # whole phase buckets made every launch O(all active StepRuns)
        # once a queue or global cap was configured. Reservations cover
        # launches another worker has committed to but not yet written.
        if queue is None and self.owned_filter is not None:
            # shard-local global cap: the bucket holds every shard's
            # active steps (bounded by the sum of per-shard caps), so
            # the ownership probe over views stays cheap
            return sum(
                1
                for sr in self.store.list_views(
                    STEP_RUN_KIND,
                    index=(INDEX_STEPRUN_QUEUE_ACTIVE, ACTIVE_ALL_BUCKET),
                )
                if self.owned_filter(sr)
            ) + self._sched_reserved.get(self._global_bucket, 0)
        key = queue if queue is not None else self._global_bucket
        return self.store.count(
            STEP_RUN_KIND,
            index=(INDEX_STEPRUN_QUEUE_ACTIVE,
                   queue if queue is not None else ACTIVE_ALL_BUCKET),
        ) + self._sched_reserved.get(key, 0)

    def _reserve_locked(self, queue: Optional[str]) -> None:
        """Account one imminent launch; MUST hold _sched_lock."""
        g = self._global_bucket
        self._sched_reserved[g] = self._sched_reserved.get(g, 0) + 1
        if queue is not None:
            self._sched_reserved[queue] = self._sched_reserved.get(queue, 0) + 1

    def _unreserve(self, queue: Optional[str]) -> None:
        keys = {self._global_bucket} | ({queue} if queue is not None else set())
        with self._sched_lock:
            for k in keys:
                n = self._sched_reserved.get(k, 0) - 1
                if n > 0:
                    self._sched_reserved[k] = n
                else:
                    self._sched_reserved.pop(k, None)

    # ------------------------------------------------------------------
    # timeout + finalize
    # ------------------------------------------------------------------
    def _enforce_story_timeout(self, run: Resource, story: StorySpec) -> bool:
        """(reference: enforceStoryTimeout:544)"""
        timeout = None
        if story.policy and story.policy.timeouts and story.policy.timeouts.story:
            timeout = parse_duration(story.policy.timeouts.story)
        if not timeout:
            cfg = self.config_manager.config
            timeout = cfg.timeouts.story_seconds or None
        if not timeout:
            return False
        started = run.status.get("startedAt") or self.clock.now()
        if self.clock.now() - started < timeout:
            return False
        run.status["phase"] = str(Phase.TIMEOUT)
        run.status["error"] = StructuredError(
            type=ErrorType.TIMEOUT,
            message=f"story exceeded timeout {timeout}s",
        ).to_dict()
        run.status["finishedAt"] = self.clock.now()
        self._cancel_children(run)
        return True

    def _cancel_children(self, run: Resource) -> None:
        from .steprun import CANCEL_ANNOTATION

        for sr in self.store.list_views(
            STEP_RUN_KIND,
            namespace=run.meta.namespace,
            index=(INDEX_STEPRUN_STORYRUN, run.meta.name),
        ):
            phase = sr.status.get("phase")
            if phase and Phase(phase).is_terminal:
                continue

            def annotate(r: Resource) -> None:
                r.meta.annotations[CANCEL_ANNOTATION] = "timeout"

            try:
                self.store.mutate(STEP_RUN_KIND, sr.meta.namespace, sr.meta.name, annotate)
            except Exception:  # noqa: BLE001
                continue

    def _finalize(self, run: Resource, story: StorySpec) -> None:
        """(reference: finalizeStoryRun:693 / finalizeSuccessfulRun:2871)"""
        status = run.status
        now = self.clock.now()
        stop = status.get(STOP_KEY)
        if stop:
            status["phase"] = stop.get("phase", str(Phase.SUCCEEDED))
            if stop.get("message"):
                status["message"] = stop["message"]
            status["finishedAt"] = now
            return
        if self._main_failed(run, story):
            failed = [
                name
                for name, raw in status["stepStates"].items()
                if _raw_failure(raw)
            ]
            status["phase"] = str(Phase.FAILED)
            status["error"] = StructuredError(
                type=ErrorType.EXECUTION,
                message=f"steps failed: {sorted(failed)}",
                details={"failedSteps": sorted(failed)},
            ).to_dict()
            status["finishedAt"] = now
            return
        output = None
        if story.output is not None:
            scope = self._scope(run)
            try:
                output = self.evaluator.evaluate_value(story.output, scope)
            except OffloadedDataUsage:
                prefix = f"runs/{run.meta.namespace}/{run.meta.name}"
                hydrated = {
                    "inputs": self.storage.hydrate(scope["inputs"], [prefix]),
                    "steps": self.storage.hydrate(scope["steps"], [prefix]),
                    "run": scope["run"],
                }
                try:
                    output = self.evaluator.evaluate_value(story.output, hydrated)
                except TemplateError as e:
                    self._finalize_output_failed(run, e)
                    return
            except (TemplateError, EvaluationBlocked) as e:
                self._finalize_output_failed(run, e)
                return
            import json

            if len(json.dumps(output, default=str)) > MAX_OUTPUT_BYTES:
                # oversized final output offloads instead of failing
                output = self.storage.dehydrate(
                    output,
                    f"runs/{run.meta.namespace}/{run.meta.name}/output",
                    max_inline_size=MAX_OUTPUT_BYTES // 2,
                )
        status["phase"] = str(Phase.SUCCEEDED)
        if output is not None:
            status["output"] = output
        status["finishedAt"] = now

    def _finalize_output_failed(self, run: Resource, err: Exception) -> None:
        run.status["phase"] = str(Phase.FAILED)
        run.status["error"] = StructuredError(
            type=ErrorType.VALIDATION,
            message=f"output template evaluation failed: {err}",
        ).to_dict()
        run.status["finishedAt"] = self.clock.now()

    # ------------------------------------------------------------------
    def _scope(self, run: Resource) -> dict[str, Any]:
        """(reference: getPriorStepOutputs:2083 — outputs + signals per
        step; hydration is lazy via the offloaded-data policy)"""
        steps_scope = {
            name: _scope_entry(raw)
            for name, raw in (run.status.get("stepStates") or {}).items()
        }
        return {
            "inputs": run.spec.get("inputs") or {},
            "steps": steps_scope,
            "run": {
                "name": run.meta.name,
                "namespace": run.meta.namespace,
                "storyName": (run.spec.get("storyRef") or {}).get("name", ""),
            },
        }

    def _story_timeout_seconds(self, story: StorySpec) -> Optional[float]:
        if story.policy and story.policy.timeouts and story.policy.timeouts.story:
            return parse_duration(story.policy.timeouts.story)
        return self.config_manager.config.timeouts.story_seconds or None

    def _next_wakeup(self, run: Resource, story: StorySpec) -> Optional[float]:
        """Earliest timer tick; None when nothing is pending."""
        timers = run.status.get(TIMERS_KEY) or {}
        now = self.clock.now()
        due = []
        # the story-timeout boundary is itself a wakeup: a long sleep must
        # not outlive the deadline unobserved
        timeout = self._story_timeout_seconds(story)
        if timeout:
            started = run.status.get("startedAt") or now
            due.append(started + timeout)
        for t in timers.values():
            kind = t.get("kind")
            if kind == "sleep":
                due.append(t.get("due", now))
            elif kind == "wait":
                due.append(min(t.get("nextPoll", now), t.get("deadline", now)))
            elif kind == "gate":
                due.append(min(now + t.get("pollInterval", 10.0), t.get("deadline", now)))
        if (
            run.status.get("placementWaiting")
            or run.status.get("queueWaiting")
            or run.status.get("materializeWaiting")
        ):
            due.append(
                now
                + self.config_manager.config.scheduling.queue_probe_interval
            )
        if not due:
            return None
        return max(0.0, min(due) - now)


def _scope_entry(raw: dict[str, Any]) -> dict[str, Any]:
    """One step's template-scope projection (output/signals/phase)."""
    return {
        "output": raw.get("output"),
        "signals": raw.get("signals") or {},
        "phase": raw.get("phase") or str(Phase.PENDING),
    }


def _merge_steprun_state(existing: dict[str, Any], sr: Resource) -> dict[str, Any]:
    """Merge a StepRun's status into the run's StepState entry."""
    state = StepState.from_dict(existing)
    phase_raw = sr.status.get("phase")
    if phase_raw:
        try:
            state.phase = Phase(phase_raw)
        except ValueError:
            pass
    if sr.status.get("output") is not None:
        state.output = sr.status.get("output")
    if sr.status.get("signals"):
        state.signals = sr.status.get("signals")
    if sr.status.get("retries") is not None:
        state.retries = sr.status.get("retries")
    if sr.status.get("preemptions") is not None:
        state.preemptions = sr.status.get("preemptions")
    if sr.status.get("exitCode") is not None:
        state.exit_code = sr.status.get("exitCode")
    if sr.status.get("exitClass"):
        state.exit_class = sr.status.get("exitClass")
    err = sr.status.get("error")
    if err:
        state.message = err.get("message") if isinstance(err, dict) else str(err)
    if sr.status.get("startedAt") and not state.started_at:
        state.started_at = sr.status.get("startedAt")
    if sr.status.get("finishedAt"):
        state.finished_at = sr.status.get("finishedAt")
    return state.to_dict()


def _finish(
    state: StepState, phase: Phase, now: float, reason: Optional[str] = None
) -> StepState:
    state.phase = phase
    state.finished_at = now
    if state.started_at is None:
        state.started_at = now
    if reason:
        state.reason = reason
    return state
