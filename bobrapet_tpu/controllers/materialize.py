"""Materialize subsystem: controller-policy offloaded-data resolution.

(reference: internal/controller/runs/materialize.go:45-326 —
ensureMaterializeStepRun:142, resolveMaterialize:326;
offloaded_refs.go:23-207 — detecting storage refs in expressions;
templating_policy.go:12-43 — the fail / inject / controller policy)

When a step's ``if`` condition references *offloaded* step output under
``templating.offloaded-data-policy=controller``, the controller must not
hydrate multi-GB payloads in-process. It instead delegates to a
dedicated **materialize StepRun**: a managed engram whose input carries
the raw expression plus the unhydrated scope (storage refs intact). The
engram's SDK context hydrates the scope in-pod — on the TPU slice, next
to the data and the slice-local SSD cache — evaluates the expression,
and reports ``{"result": <value>}``. The DAG blocks the referencing
step's readiness until the materialize StepRun reaches a terminal phase.

Identity is validated on adoption: an existing StepRun at the
deterministic materialize name that is not owned by this StoryRun is a
spoof attempt and aborts resolution (reference: identity-validated,
materialize.go:142).
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from ..api import conditions
from ..api.catalog import CLUSTER_NAMESPACE, ENGRAM_TEMPLATE_KIND
from ..api.engram import KIND as ENGRAM_KIND
from ..api.enums import Phase
from ..api.runs import STEP_RUN_KIND
from ..core.object import Resource, new_resource
from ..core.store import AlreadyExists, ResourceStore
from ..observability.metrics import metrics
from ..utils.naming import compose_unique
from .step_executor import (
    LABEL_PARENT_STEP,
    LABEL_PRIORITY,
    LABEL_QUEUE,
    LABEL_STORY_RUN,
)

_log = logging.getLogger(__name__)

#: default managed engram used for controller-policy materialization;
#: overridable via operator config ``templating.materialize-engram``
#: (reference: TemplateMaterializeEngram, controller_config.go:142-144)
DEFAULT_MATERIALIZE_ENGRAM = "bobrapet-materialize"
MATERIALIZE_TEMPLATE = "bobrapet-materialize-tpl"
#: SDK entrypoint name the builtin template binds to
#: (implemented in bobrapet_tpu/sdk/materialize.py)
MATERIALIZE_ENTRYPOINT = "bobrapet.materialize"

#: marks a StepRun as a materialize delegate: the StepRun controller
#: passes its input through verbatim (no template eval, no controller
#: hydration) so hydration happens in-pod
MATERIALIZE_ANNOTATION = "runs.bobrapet.io/materialize"


class MaterializeFailed(Exception):
    """The materialize StepRun reached a failure phase."""


class MaterializeSpoofed(Exception):
    """A foreign object occupies the materialize StepRun's name."""


def materialize_name(run_name: str, step_name: str) -> str:
    """Deterministic, collision-free delegate name — identity-bearing
    (an ownership mismatch at this name is treated as spoofing), so it
    must hash the part tuple like steprun_name does."""
    return compose_unique(run_name, step_name, "mat")


def ensure_builtin_engram(store: ResourceStore, namespace: str) -> None:
    """Provision the builtin materialize EngramTemplate + Engram on
    first use (the reference expects the operator deployment to install
    its managed materialize engram; the builtin plays that role when the
    configured name is the default)."""
    try:
        store.create(new_resource(
            ENGRAM_TEMPLATE_KIND, MATERIALIZE_TEMPLATE, CLUSTER_NAMESPACE,
            spec={
                "entrypoint": MATERIALIZE_ENTRYPOINT,
                "image": "bobrapet/materialize:builtin",
                "supportedModes": ["job"],
                "description": "managed offloaded-data materializer",
            },
        ))
    except AlreadyExists:
        pass
    try:
        store.create(new_resource(
            ENGRAM_KIND, DEFAULT_MATERIALIZE_ENGRAM, namespace,
            spec={"templateRef": {"name": MATERIALIZE_TEMPLATE}},
        ))
    except AlreadyExists:
        pass


def resolve_materialize(
    store: ResourceStore,
    run: Resource,
    step_name: str,
    expression: str,
    scope: dict[str, Any],
    engram_name: str,
) -> Optional[bool]:
    """Create-or-poll the materialize StepRun for one step's condition.

    Returns None while the delegate is still running (the step is not
    ready yet), the evaluated boolean once it succeeded. Raises
    MaterializeFailed / MaterializeSpoofed on terminal failure
    (reference: resolveMaterialize materialize.go:326 — blocks readiness
    until the delegate completes)."""
    ns = run.meta.namespace
    name = materialize_name(run.meta.name, step_name)
    existing = store.try_get(STEP_RUN_KIND, ns, name)
    if existing is None:
        if store.try_get(ENGRAM_KIND, ns, engram_name) is None:
            if engram_name == DEFAULT_MATERIALIZE_ENGRAM:
                ensure_builtin_engram(store, ns)
            else:
                # a configured-but-absent materialize engram is a config
                # error: fail the step now instead of parking a Blocked
                # delegate that polls forever (reference surfaces this as
                # InvalidConfiguration)
                raise MaterializeFailed(
                    f"configured materialize engram {ns}/{engram_name!r} "
                    "not found (templating.materialize-engram points at a "
                    "nonexistent Engram)"
                )
        # delegate inherits the parent run's scheduling labels so it is
        # accounted against the same queue's max_concurrent (reference:
        # applySchedulingLabelsFromStoryRun, materialize.go)
        sched = {
            k: run.meta.labels[k]
            for k in (LABEL_QUEUE, LABEL_PRIORITY)
            if k in run.meta.labels
        }
        sr = new_resource(
            STEP_RUN_KIND, name, ns,
            spec={
                "storyRunRef": {"name": run.meta.name},
                "stepId": f"{step_name}#materialize",
                "engramRef": {"name": engram_name},
                "input": {"expression": expression, "scope": scope},
            },
            labels={
                LABEL_STORY_RUN: run.meta.name,
                # parent-step keyed off the synthetic id so neither the
                # state sync nor a parallel parent's branch roll-up
                # mistakes the delegate for a workflow step
                LABEL_PARENT_STEP: f"{step_name}#materialize",
                **sched,
            },
            annotations={MATERIALIZE_ANNOTATION: "true"},
            owners=[run.owner_ref()],
        )
        try:
            store.create(sr)
            metrics.child_stepruns_created.inc("materialize")
        except AlreadyExists:
            return None  # concurrent creator wins; poll next pass
        _log.debug("materialize StepRun %s created for step %s", name, step_name)
        return None

    if not existing.has_owner(run):
        raise MaterializeSpoofed(
            f"StepRun {name!r} exists but is not owned by StoryRun "
            f"{run.meta.name!r} — refusing to trust its result"
        )
    phase_raw = existing.status.get("phase")
    phase = Phase(phase_raw) if phase_raw else Phase.PENDING
    if phase is Phase.SUCCEEDED:
        output = existing.status.get("output") or {}
        return bool(output.get("result"))
    if phase.is_terminal:  # Failed / Canceled / Skipped
        err = (existing.status.get("error") or {}).get("message", phase_raw)
        raise MaterializeFailed(
            f"materialize delegate for step {step_name!r} ended {phase_raw}: {err}"
        )
    if phase is Phase.BLOCKED:
        # the delegate's engram or template vanished after creation: a
        # Blocked delegate never terminates on its own, so surface the
        # config error instead of polling indefinitely. But the Blocked
        # condition can be stale (engram deleted and recreated between
        # reconciles) — only fail once the reference is verified still
        # absent; otherwise keep polling and let the StepRun controller
        # self-heal.
        blocked_reasons = {
            str(conditions.Reason.REFERENCE_NOT_FOUND),
            str(conditions.Reason.TEMPLATE_NOT_FOUND),
        }
        # the delegate's OWN engram ref is the truth here, not the
        # currently-configured name — config may have moved on while the
        # existing delegate still points at the old engram
        delegate_engram = (existing.spec.get("engramRef") or {}).get(
            "name", engram_name
        )
        for cond in existing.status.get("conditions", []):
            if cond.get("reason") not in blocked_reasons:
                continue
            if _reference_still_broken(store, ns, delegate_engram):
                raise MaterializeFailed(
                    f"materialize delegate for step {step_name!r} is Blocked: "
                    f"{cond.get('message', 'engram reference not found')}"
                )
    return None


def _reference_still_broken(
    store: ResourceStore, ns: str, engram_name: str
) -> bool:
    """True when the delegate's engram (or its template) is genuinely
    missing right now, not just in a stale Blocked condition."""
    engram = store.try_get(ENGRAM_KIND, ns, engram_name)
    if engram is None:
        return True
    tpl_name = (engram.spec.get("templateRef") or {}).get("name", "")
    return bool(tpl_name) and (
        store.try_get(ENGRAM_TEMPLATE_KIND, CLUSTER_NAMESPACE, tpl_name) is None
    )
