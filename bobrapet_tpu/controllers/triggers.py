"""StoryTrigger admission + EffectClaim lease controllers.

Capability parity with the reference's durable-trigger and effect-lease
reconcilers (reference:
internal/controller/runs/storytrigger_controller.go:70-543,
internal/controller/runs/effectclaim_controller.go:57-187).

- **StoryTriggerController** — durable trigger admission: validate the
  dedupe identity, verify story access + version pinning
  (storytrigger_controller.go:101-109), dehydrate oversized inputs,
  create-or-adopt a StoryRun under a deterministic name derived from the
  identity, and resolve the decision to Created / Reused / Rejected.
  The trigger CR is the durable record: the impulse can crash after
  creating it and the run is still admitted exactly once.
- **EffectClaimController** — owns the lease lifecycle for one external
  side effect: Reserved while the holder's lease is live, Completed /
  Released on SDK report, Abandoned once the lease expires un-renewed
  (stale takeover: a new holder may then acquire a fresh claim).
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from ..api import conditions
from ..api.enums import EffectClaimPhase, Phase, TriggerDecision
from ..api.runs import (
    EFFECT_CLAIM_KIND,
    STEP_RUN_KIND,
    STORY_RUN_KIND,
    STORY_TRIGGER_KIND,
    parse_effectclaim,
    parse_storytrigger,
)
from ..api.story import KIND as STORY_KIND
from ..core.events import EventRecorder
from .impulse import INDEX_TRIGGER_IMPULSE
from ..core.object import Resource, new_resource
from ..core.store import AdmissionDenied, AlreadyExists, NotFound, ResourceStore
from ..observability.metrics import metrics
from ..utils.hashing import hash_inputs
from ..utils.naming import compose, short_hash
from .manager import Clock

_log = logging.getLogger(__name__)

# annotations stamped on the StoryRun so later triggers can be matched
# against the run that admitted them
# (reference: storyRunMatchesTrigger storytrigger_controller.go:331)
ANNO_TRIGGER_UID = "runs.bobrapet.io/trigger-uid"
ANNO_TRIGGER_INPUT_HASH = "runs.bobrapet.io/trigger-input-hash"
ANNO_TRIGGER_KEY = "runs.bobrapet.io/trigger-key"

DEFAULT_LEASE_SECONDS = 60


def derive_storyrun_name(story: str, identity) -> str:
    """Deterministic StoryRun name from the dedupe identity
    (reference: identity.DeriveStoryRunName
    pkg/runs/identity/storyrun_trigger.go:35 — key-based when available,
    hash fallback otherwise)."""
    mode = (identity.mode if identity else None) or "none"
    if mode in ("key", "keyAndInputHash") and identity.key:
        token = identity.key
        if mode == "keyAndInputHash" and identity.input_hash:
            token = f"{token}.{identity.input_hash[:12]}"
    else:
        token = identity.submission_id if identity and identity.submission_id else ""
    return compose(story, "trig", short_hash(f"{mode}:{token}"))


class StoryTriggerController:
    """(reference: storytrigger_controller.go Reconcile:70)"""

    def __init__(
        self,
        store: ResourceStore,
        storage,
        config_manager,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
    ):
        self.store = store
        self.storage = storage
        self.config_manager = config_manager
        self.recorder = recorder or EventRecorder()
        self.clock = clock or Clock()

    # ------------------------------------------------------------------
    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        trigger = self.store.try_get(STORY_TRIGGER_KIND, namespace, name)
        if trigger is None or trigger.meta.deletion_timestamp is not None:
            return None
        decision = trigger.status.get("decision")
        if decision in (
            str(TriggerDecision.CREATED),
            str(TriggerDecision.REUSED),
            str(TriggerDecision.REJECTED),
        ):
            return None

        spec = parse_storytrigger(trigger)
        story_name = spec.story_ref.name if spec.story_ref else ""
        story_ns = (spec.story_ref.namespace if spec.story_ref else None) or namespace

        # cross-namespace story access is governed by the reference policy
        # (reference: validateStoryRefAccess storytrigger_controller.go:157)
        if story_ns != namespace:
            from ..webhooks.policy import cross_namespace_allowed

            if not cross_namespace_allowed(
                self.store, self.config_manager,
                from_kind=STORY_TRIGGER_KIND, from_namespace=namespace,
                to_kind=STORY_KIND, to_namespace=story_ns, to_name=story_name,
            ):
                return self._resolve(
                    trigger, TriggerDecision.REJECTED,
                    reason="CrossNamespaceDenied",
                    message=f"access to story {story_ns}/{story_name} denied by policy",
                )

        story = self.store.try_get(STORY_KIND, story_ns, story_name)
        if story is None:
            return self._resolve(
                trigger, TriggerDecision.REJECTED,
                reason=conditions.Reason.STORY_NOT_FOUND,
                message=f"story {story_ns}/{story_name} not found",
            )

        # version pinning (reference: storytrigger_controller.go:101-109)
        pinned = spec.story_ref.version if spec.story_ref else None
        actual = story.spec.get("version")
        if pinned and actual and pinned != actual:
            return self._resolve(
                trigger, TriggerDecision.REJECTED,
                reason="StoryVersionMismatch",
                message=f"trigger pinned to story version {pinned!r}, found {actual!r}",
            )

        run_name = derive_storyrun_name(story_name, spec.identity)
        input_hash = spec.identity.input_hash if spec.identity else None
        if not input_hash:
            input_hash = hash_inputs(spec.inputs or {})

        existing = self.store.try_get(STORY_RUN_KIND, namespace, run_name)
        if existing is not None:
            return self._adopt(trigger, existing, input_hash)

        throttle_msg = self._throttle_check(spec, namespace)
        if throttle_msg is not None:
            return self._resolve(
                trigger, TriggerDecision.REJECTED,
                reason="Throttled", message=throttle_msg,
            )

        run = self._desired_storyrun(trigger, spec, run_name, story_ns, input_hash)
        try:
            self.store.create(run)
        except AlreadyExists:
            existing = self.store.try_get(STORY_RUN_KIND, namespace, run_name)
            if existing is None:
                return 0.5  # race with deletion; retry
            return self._adopt(trigger, existing, input_hash)
        except AdmissionDenied as e:
            # the durable-admission contract always resolves: an inadmissible
            # run (schema violation, size cap, cross-ns policy on the run
            # kind) is a Rejected decision, not a crash-loop
            return self._resolve(
                trigger, TriggerDecision.REJECTED,
                reason="StoryRunInadmissible", message=str(e),
            )
        
        return self._resolve(
            trigger, TriggerDecision.CREATED, storyrun=run_name,
            reason="StoryRunCreated", message=f"created StoryRun {run_name}",
        )

    # ------------------------------------------------------------------
    def _throttle_check(self, spec, namespace: str) -> Optional[str]:
        """Enforce the impulse's maxInFlight throttle at admission
        (reference: TriggerThrottlePolicy shared_types.go:341; the
        rate/burst half is paced SDK-side, in-flight is a control-plane
        invariant). Returns a rejection message when throttled."""
        if spec.impulse_ref is None or not spec.impulse_ref.name:
            return None
        from ..api.impulse import KIND as IMPULSE_KIND, parse_impulse

        impulse = self.store.try_get(IMPULSE_KIND, namespace, spec.impulse_ref.name)
        if impulse is None:
            return None
        ispec = parse_impulse(impulse)
        throttle = ispec.throttle or (
            ispec.delivery.throttle if ispec.delivery is not None else None
        )
        if throttle is None or not throttle.max_in_flight:
            return None
        runs = self.store.list(
            STORY_RUN_KIND, namespace=namespace,
            index=(INDEX_TRIGGER_IMPULSE, spec.impulse_ref.name),
        )
        in_flight = sum(
            1 for r in runs
            if not r.status.get("phase")
            or not Phase(r.status["phase"]).is_terminal
        )
        if in_flight < throttle.max_in_flight:
            return None
        return (
            f"impulse {spec.impulse_ref.name!r} has {in_flight} runs "
            f"in flight (maxInFlight={throttle.max_in_flight})"
        )

    # ------------------------------------------------------------------
    def _desired_storyrun(
        self, trigger: Resource, spec, run_name: str, story_ns: str, input_hash: str
    ) -> Resource:
        """(reference: desiredStoryRunForTrigger
        storytrigger_controller.go:292 + oversized-input dehydration
        prepareStoryRunForCreate:237)"""
        inputs = spec.inputs or {}
        # canonical offload scope "runs/<ns>/<run>/..." — the StoryRun
        # webhook rejects storage refs outside it (spoofing guard)
        inputs = self.storage.dehydrate_inputs(
            inputs, key_prefix=f"runs/{trigger.meta.namespace}/{run_name}/inputs"
        )
        run_spec: dict[str, Any] = {
            "storyRef": {"name": spec.story_ref.name, "namespace": story_ns},
            "inputs": inputs,
        }
        if spec.impulse_ref is not None:
            run_spec["impulseRef"] = spec.impulse_ref.to_dict()
        return new_resource(
            STORY_RUN_KIND,
            run_name,
            trigger.meta.namespace,
            spec=run_spec,
            labels={"bobrapet.io/story": spec.story_ref.name},
            annotations={
                ANNO_TRIGGER_UID: trigger.meta.uid,
                ANNO_TRIGGER_INPUT_HASH: input_hash,
                ANNO_TRIGGER_KEY: (spec.identity.key if spec.identity else "") or "",
            },
        )

    # ------------------------------------------------------------------
    def _adopt(self, trigger: Resource, run: Resource, input_hash: str):
        """Decide recovered-Created vs Reused vs Rejected-conflict against
        an existing run (reference: storytrigger_controller.go:120-140)."""
        run_uid = run.meta.annotations.get(ANNO_TRIGGER_UID, "")
        run_hash = run.meta.annotations.get(ANNO_TRIGGER_INPUT_HASH, "")
        if run_uid == trigger.meta.uid:
            # we created it earlier and crashed before resolving
            return self._resolve(
                trigger, TriggerDecision.CREATED, storyrun=run.meta.name,
                reason="StoryRunRecovered",
                message=f"recovered StoryRun {run.meta.name}",
            )
        if run_hash and run_hash == input_hash:
            return self._resolve(
                trigger, TriggerDecision.REUSED, storyrun=run.meta.name,
                reason="StoryRunReused",
                message=f"identical delivery matched StoryRun {run.meta.name}",
            )
        return self._resolve(
            trigger, TriggerDecision.REJECTED,
            reason="IdentityConflict",
            message=(
                f"StoryRun {run.meta.name} exists for this identity "
                "with different inputs"
            ),
        )

    # ------------------------------------------------------------------
    def _resolve(
        self,
        trigger: Resource,
        decision: TriggerDecision,
        storyrun: str = "",
        reason: str = "",
        message: str = "",
    ) -> None:
        """(reference: markResolved storytrigger_controller.go:467)"""
        now = self.clock.now()

        def patch(st: dict[str, Any]) -> None:
            st["decision"] = str(decision)
            st["reason"] = reason
            st["message"] = message
            if storyrun:
                st["storyRunName"] = storyrun
            st["resolvedAt"] = now
            conds = st.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.READY,
                decision is not TriggerDecision.REJECTED,
                reason or str(decision), message, now=now,
            )

        self.store.patch_status(
            STORY_TRIGGER_KIND, trigger.meta.namespace, trigger.meta.name, patch
        )
        metrics.trigger_decisions.inc(str(decision))
        if decision is TriggerDecision.REJECTED:
            self.recorder.warning(trigger, reason or "Rejected", message)
        else:
            self.recorder.normal(trigger, reason or str(decision), message)
        return None


class EffectClaimController:
    """(reference: effectclaim_controller.go Reconcile:57,
    effectClaimLifecycle:163)"""

    def __init__(
        self,
        store: ResourceStore,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
    ):
        self.store = store
        self.recorder = recorder or EventRecorder()
        self.clock = clock or Clock()

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        claim = self.store.try_get(EFFECT_CLAIM_KIND, namespace, name)
        if claim is None or claim.meta.deletion_timestamp is not None:
            return None
        spec = parse_effectclaim(claim)
        now = self.clock.now()

        self._ensure_owner(claim, spec)

        phase = claim.status.get("phase")
        if phase in (
            str(EffectClaimPhase.COMPLETED),
            str(EffectClaimPhase.RELEASED),
            str(EffectClaimPhase.ABANDONED),
        ):
            return None

        # SDK-reported completion/release wins
        # (reference: completion status completed/released/abandoned,
        # effectclaim_types.go:25-43)
        if claim.status.get("completed"):
            return self._set_phase(claim, EffectClaimPhase.COMPLETED,
                                   "EffectCompleted", "holder reported completion")
        if claim.status.get("released"):
            return self._set_phase(claim, EffectClaimPhase.RELEASED,
                                   "EffectReleased", "holder released the claim")

        # the controller stamps reservedAt on first sight so lease math
        # stays in one clock domain (spec acquire/renew timestamps, when
        # the holder supplies them, take precedence)
        reserved_at = claim.status.get("reservedAt")
        if reserved_at is None:
            self.store.patch_status(
                EFFECT_CLAIM_KIND, namespace, name,
                lambda st: st.__setitem__("reservedAt", now),
            )
            reserved_at = now
        lease = spec.lease_duration_seconds or DEFAULT_LEASE_SECONDS
        anchor = spec.renewed_at or spec.acquired_at or float(reserved_at)
        expires = anchor + lease
        if now >= expires:
            # stale takeover: the holder died mid-effect
            # (reference: effectclaim_types.go:45-97)
            return self._set_phase(
                claim, EffectClaimPhase.ABANDONED, "LeaseExpired",
                f"lease expired {now - expires:.0f}s ago without renewal",
            )

        if phase != str(EffectClaimPhase.RESERVED):
            self._set_phase(claim, EffectClaimPhase.RESERVED, "Reserved",
                            f"held by {spec.holder_identity}", terminal=False)
        return max(0.1, expires - now)

    # ------------------------------------------------------------------
    def _ensure_owner(self, claim: Resource, spec) -> None:
        """(reference: effectclaim_controller.go — owner ref to StepRun)"""
        ref = spec.step_run_ref or {}
        sr_name = ref.get("name")
        if not sr_name or claim.meta.owner_references:
            return
        sr = self.store.try_get(STEP_RUN_KIND, claim.meta.namespace, sr_name)
        if sr is None:
            return
        try:
            self.store.mutate(
                EFFECT_CLAIM_KIND, claim.meta.namespace, claim.meta.name,
                lambda r: r.meta.owner_references.append(sr.owner_ref(controller=False)),
            )
        except NotFound:
            pass

    def _set_phase(self, claim: Resource, phase: EffectClaimPhase,
                   reason: str, message: str, terminal: bool = True):
        now = self.clock.now()

        def patch(st: dict[str, Any]) -> None:
            st["phase"] = str(phase)
            if terminal:
                st["resolvedAt"] = now
            conds = st.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.READY,
                phase is not EffectClaimPhase.ABANDONED,
                reason, message, now=now,
            )

        self.store.patch_status(
            EFFECT_CLAIM_KIND, claim.meta.namespace, claim.meta.name, patch
        )
        metrics.effectclaim_transitions.inc(str(phase))
        return None
