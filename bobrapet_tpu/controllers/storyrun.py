"""StoryRun controller: run lifecycle around the DAG engine.

Capability parity with the reference StoryRun reconciler
(reference: internal/controller/runs/storyrun_controller.go —
Reconcile:216, handleRedriveFromStepIfRequested:295,
handleGracefulCancel:1517, handleTerminalStoryRun:1811,
ensureChildCleanup:1882, resolveRetentionSettings:1992):

guards (story ref + cross-namespace policy, input schema, oversized
inputs) -> finalizer for storage cleanup -> redrive (full +
from-step) -> graceful cancel with drain window -> DAG reconcile ->
two-phase retention (children TTL, then run record).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional

from ..api import conditions
from ..api.enums import Phase
from ..api.errors import ErrorType, StructuredError
from ..api.policy import reference_granted
from ..api.runs import STEP_RUN_KIND, STORY_RUN_KIND
from ..api.story import KIND as STORY_KIND, parse_story
from ..core.object import Resource
from ..core.store import NotFound, ResourceStore
from ..observability.metrics import metrics
from ..observability.timeline import FLIGHT
from ..storage.manager import StorageManager
from ..utils.duration import parse_duration
from .dag import INDEX_STEPRUN_STORYRUN, DAGEngine
from .manager import Clock
from .rbac import RBACOwnershipError, RunRBACManager, objects_hash
from .step_executor import LABEL_PRIORITY, LABEL_QUEUE, parse_trace_annotation
from .steprun import CANCEL_ANNOTATION

_log = logging.getLogger(__name__)

FINALIZER = "runs.bobrapet.io/storage-cleanup"
REDRIVE_ANNOTATION = "runs.bobrapet.io/redrive"


class StoryRunController:
    def __init__(
        self,
        store: ResourceStore,
        dag: DAGEngine,
        config_manager,
        storage: StorageManager,
        recorder=None,
        clock: Optional[Clock] = None,
        tracer=None,
    ):
        self.store = store
        self.dag = dag
        self.config_manager = config_manager
        self.storage = storage
        self.recorder = recorder
        self.clock = clock or Clock()
        self.rbac = RunRBACManager(store)
        if tracer is None:
            from ..observability.tracing import TRACER as tracer
        self.tracer = tracer
        # runs whose blob prefix is pinned against capacity eviction;
        # in-memory is restart-safe because the store's pin table lives
        # in the same process and resets with us
        self._pinned: set[tuple[str, str]] = set()
        #: (ns, name) -> (uid, generation) whose inputs passed the
        #: oversized-inputs probe (in-memory; a restart just re-probes).
        #: Invalidated on config reload: a lowered engram.max-inline-size
        #: must re-probe live runs, and run specs never regenerate on
        #: their own.
        self._oversize_checked: dict[tuple[str, str], tuple[str, int]] = {}
        if hasattr(config_manager, "subscribe"):
            config_manager.subscribe(
                lambda _cfg: self._oversize_checked.clear()
            )

    # ------------------------------------------------------------------
    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        run = self.store.try_get(STORY_RUN_KIND, namespace, name)
        if run is None:
            return None

        # deletion: storage cleanup behind a finalizer
        if run.meta.deletion_timestamp is not None:
            if FINALIZER in run.meta.finalizers:
                self._unpin(namespace, name)
                self.storage.delete_prefix(StorageManager.run_prefix(namespace, name))

                def strip(r: Resource) -> None:
                    if FINALIZER in r.meta.finalizers:
                        r.meta.finalizers.remove(FINALIZER)

                self.store.mutate(STORY_RUN_KIND, namespace, name, strip)
            return None

        if FINALIZER not in run.meta.finalizers:
            def add_fin(r: Resource) -> None:
                if FINALIZER not in r.meta.finalizers:
                    r.meta.finalizers.append(FINALIZER)

            run = self.store.mutate(STORY_RUN_KIND, namespace, name, add_fin)

        # redrive before the terminal check: redriving a terminal run
        # resets it (reference: handleRedriveFromStepIfRequested:295)
        if REDRIVE_ANNOTATION in run.meta.annotations:
            return self._handle_redrive(run)

        phase = Phase(run.status["phase"]) if run.status.get("phase") else None
        if phase is not None and phase.is_terminal:
            self._unpin(namespace, name)
            return self._handle_terminal(run)

        # live run: shield its offloaded blobs from LRU eviction so a
        # byte-budget squeeze can never break a pending hydrate
        if (namespace, name) not in self._pinned:
            self.storage.pin_run(namespace, name)
            self._pinned.add((namespace, name))

        # graceful cancel (reference: handleGracefulCancel:1517)
        if run.spec.get("cancelRequested"):
            return self._handle_cancel(run)

        # --- story resolution + guards ---
        story_ref = run.spec.get("storyRef") or {}
        story_name = story_ref.get("name", "")
        story_ns = story_ref.get("namespace") or namespace
        if story_ns != namespace:
            policy = self.config_manager.config.reference_cross_namespace_policy
            allowed = policy == "allow" or (
                policy == "grant"
                and reference_granted(
                    self.store, STORY_RUN_KIND, namespace, STORY_KIND, story_ns, story_name
                )
            )
            if not allowed:
                return self._fail(
                    run,
                    StructuredError(
                        type=ErrorType.VALIDATION,
                        message=f"cross-namespace story reference {story_ns}/{story_name} "
                        f"denied by policy {policy!r}",
                    ),
                    reason=conditions.Reason.STORY_REFERENCE_INVALID,
                )
        # a view: the Story is only parsed (cached) and generation-read
        story_res = self.store.try_get_view(STORY_KIND, story_ns, story_name)
        if story_res is None:
            self._set_pending(run, conditions.Reason.STORY_NOT_FOUND,
                              f"story {story_ns}/{story_name} not found")
            return None
        story = parse_story(story_res)

        # scheduling labels: queue + priority stamped on the run so the
        # DAG's priority ordering can list queue peers by label
        # (reference: storyrun_controller.go scheduling labels;
        # resolveSchedulingDecision + priorityFromLabels dag.go:1910-1946)
        sched_queue = story.policy.queue if story.policy else None
        sched_priority = (
            story.policy.priority
            if story.policy and story.policy.priority is not None
            else 0
        )
        desired_labels = (
            {LABEL_QUEUE: sched_queue, LABEL_PRIORITY: str(sched_priority)}
            if sched_queue
            else {}
        )
        current_labels = {
            k: v
            for k, v in run.meta.labels.items()
            if k in (LABEL_QUEUE, LABEL_PRIORITY)
        }
        if current_labels != desired_labels:
            def stamp(r: Resource) -> None:
                r.meta.labels.pop(LABEL_QUEUE, None)
                r.meta.labels.pop(LABEL_PRIORITY, None)
                r.meta.labels.update(desired_labels)

            run = self.store.mutate(STORY_RUN_KIND, namespace, name, stamp)

        # version pinning (reference: storytrigger_controller.go:101-109)
        pinned = story_ref.get("version")
        if pinned and story.version and pinned != story.version:
            return self._fail(
                run,
                StructuredError(
                    type=ErrorType.VALIDATION,
                    message=f"story version mismatch: run pinned {pinned!r}, "
                    f"story is {story.version!r}",
                ),
                reason=conditions.Reason.STORY_REFERENCE_INVALID,
            )

        # input schema validation (reference: reconcileAfterSetup:912)
        if story.inputs_schema and not run.status.get("inputsValidated"):
            err = _validate_inputs(run.spec.get("inputs") or {}, story.inputs_schema)
            if err:
                return self._fail(
                    run,
                    StructuredError(type=ErrorType.VALIDATION, message=err),
                    reason=conditions.Reason.INPUT_SCHEMA_FAILED,
                )

        # trace + schema-reference contracts persisted into status
        # (reference: ensureStoryRunSchemaRefs storyrun_controller.go:1047,
        # TraceInfo trace_types.go:20 + pkg/runs/status/trace.go)
        run = self._ensure_run_contracts(run, story, story_ns, story_name)

        # oversized-inputs guard (reference: oversized-input guard —
        # admission normally dehydrates; double-check here). Inputs live
        # in spec, which only changes with a generation bump — the JSON
        # size probe runs once per observed generation, not on the ~7
        # reconciles every step of the run triggers.
        if self._oversize_checked.get((namespace, name)) != (run.meta.uid, run.meta.generation):
            max_inline = self.config_manager.config.engram.max_inline_size
            inputs = run.spec.get("inputs") or {}
            import json

            if inputs and len(json.dumps(inputs, default=str)) > max_inline * 4:
                offloaded = self.storage.dehydrate_inputs(
                    inputs, f"runs/{namespace}/{name}/inputs", max_inline_size=max_inline
                )

                def swap_inputs(r: Resource) -> None:
                    r.spec["inputs"] = offloaded

                run = self.store.mutate(STORY_RUN_KIND, namespace, name, swap_inputs)
            if len(self._oversize_checked) > 65536:
                self._oversize_checked.clear()  # cheap bound; re-checks are one dump
            # uid in the key: a deleted-and-recreated run (same name,
            # generation restarts at 1) must be re-probed
            self._oversize_checked[(namespace, name)] = (run.meta.uid, run.meta.generation)

        # --- per-run RBAC identity (reference: rbac.go Reconcile:95) ---
        # Deleted/drifted SA, Role, or RoleBinding objects are repaired
        # mid-run, but the full rule collection (all_steps_deep + template
        # fetch per engram) only reruns when one of the three objects is
        # missing/unowned or the Story generation moved — parked runs
        # requeue every second and must not pay O(steps) store reads each
        # tick for an unchanged identity.
        sa_name = run.status.get("serviceAccount")
        # standing rejections disable the quick path: the fix arrives via
        # a template edit, which does not move the Story generation
        live_objs = [
            self.store.try_get_view(kind, namespace, sa_name) if sa_name else None
            for kind in ("ServiceAccount", "Role", "RoleBinding")
        ]
        rbac_fresh = (
            bool(sa_name)
            and not run.status.get("rejectedRBACRules")
            and run.status.get("rbacStoryGeneration") == story_res.meta.generation
            and all(o is not None and o.has_owner(run) for o in live_objs)
            # any out-of-band tampering — Role rules, RoleBinding
            # subjects, SA cloud-identity annotations — must trigger the
            # full ensure, which rewrites the drifted specs
            and objects_hash([o.spec for o in live_objs])
            == run.status.get("rbacObjectsHash")
        )
        if not rbac_fresh:
            try:
                rbac_summary = self.rbac.ensure(run, story)
            except RBACOwnershipError as e:
                return self._fail(
                    run,
                    StructuredError(type=ErrorType.VALIDATION, message=str(e)),
                    reason=conditions.Reason.INVALID_CONFIGURATION,
                )

            def record_sa(status: dict[str, Any]) -> None:
                status["serviceAccount"] = rbac_summary["serviceAccount"]
                status["rbacStoryGeneration"] = story_res.meta.generation
                status["rbacObjectsHash"] = rbac_summary["objectsHash"]
                if rbac_summary["rejectedRules"]:
                    status["rejectedRBACRules"] = rbac_summary["rejectedRules"]
                else:
                    status.pop("rejectedRBACRules", None)

            run = self.store.patch_status(STORY_RUN_KIND, namespace, name, record_sa)

        # --- DAG reconcile (engine mutates a working copy's status) ---
        # change detection against the COMMITTED status (a view): no
        # pre-image copy, no JSON dumps — dict == short-circuits, and a
        # mismatch with a concurrent writer just means one extra
        # patch-if-changed round through mutate's conflict retry
        committed = self.store.try_get_view(STORY_RUN_KIND, namespace, name)
        requeue = self.dag.run(run, story)
        if committed is not None and run.status != committed.status:
            new_status = dict(run.status)
            new_status["inputsValidated"] = True
            new_status["observedGeneration"] = run.meta.generation

            def persist(status: dict[str, Any]) -> None:
                # merge externally-patched channels written since our read
                # (gate decisions arrive via concurrent status patches —
                # clobbering them would turn approvals into GateTimeouts)
                fresh_gates = status.get("gates") or {}
                merged = dict(new_status)
                merged_gates = {**(merged.get("gates") or {}), **fresh_gates}
                if merged_gates:
                    merged["gates"] = merged_gates
                status.clear()
                status.update(merged)

            self.store.patch_status(STORY_RUN_KIND, namespace, name, persist)
        return requeue

    # ------------------------------------------------------------------
    def _set_pending(self, run: Resource, reason: str, message: str) -> None:
        def patch(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.PENDING)
            status["reason"] = reason
            status["message"] = message
            conds = status.setdefault("conditions", [])
            conditions.set_condition(conds, conditions.READY, False, reason, message,
                                     now=self.clock.now())

        self.store.patch_status(STORY_RUN_KIND, run.meta.namespace, run.meta.name, patch)

    def _fail(self, run: Resource, err: StructuredError, reason: str) -> None:
        ns, name = run.meta.namespace, run.meta.name
        FLIGHT.record(ns, name, "error",
                      message=f"{reason}: {err.message}"[:512],
                      at=self.clock.now())
        forensics = FLIGHT.tail(ns, name, 20)

        def patch(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.FAILED)
            status["error"] = err.to_dict()
            status["reason"] = reason
            status["finishedAt"] = self.clock.now()
            # terminal-failure forensics: the causal tail (admission
            # guards fail runs the DAG never touched — they must explain
            # themselves too)
            status["forensics"] = forensics

        self.store.patch_status(STORY_RUN_KIND, ns, name, patch)
        self._observe_terminal(run, str(Phase.FAILED))
        return None

    def _observe_terminal(self, run: Resource, phase: str) -> None:
        """Terminal transitions made outside the DAG engine (validation
        failures, cancel force-finish) still count toward the run series."""
        metrics.storyrun_total.inc(phase)
        started = run.status.get("startedAt")
        if started is not None:
            story_name = (run.spec.get("storyRef") or {}).get("name", "")
            metrics.storyrun_duration.observe(
                self.clock.now() - float(started), story_name
            )

    # ------------------------------------------------------------------
    # graceful cancel
    # ------------------------------------------------------------------
    def _handle_cancel(self, run: Resource) -> Optional[float]:
        ns, name = run.meta.namespace, run.meta.name
        now = self.clock.now()
        started = run.status.get("cancelRequestedAt")
        if started is None:
            def mark(status: dict[str, Any]) -> None:
                status["cancelRequestedAt"] = now
                status["reason"] = conditions.Reason.CANCELED

            self.store.patch_status(STORY_RUN_KIND, ns, name, mark)
            started = now

        # annotate non-terminal children (their controller tears them down)
        children = self.store.list_views(
            STEP_RUN_KIND, namespace=ns, index=(INDEX_STEPRUN_STORYRUN, name)
        )
        all_terminal = True
        for sr in children:
            phase = sr.status.get("phase")
            if phase and Phase(phase).is_terminal:
                continue
            all_terminal = False
            if CANCEL_ANNOTATION not in sr.meta.annotations:
                def annotate(r: Resource) -> None:
                    r.meta.annotations[CANCEL_ANNOTATION] = "storyrun-cancel"

                try:
                    self.store.mutate(STEP_RUN_KIND, ns, sr.meta.name, annotate)
                except NotFound:
                    pass

        drain = self._drain_timeout(run)
        if all_terminal or now - started >= drain:
            # force-finish (reference: :1517 force after drain window)
            def finish(status: dict[str, Any]) -> None:
                status["phase"] = str(Phase.FINISHED)
                status["reason"] = conditions.Reason.CANCELED
                status["finishedAt"] = self.clock.now()

            self.store.patch_status(STORY_RUN_KIND, ns, name, finish)
            metrics.storyrun_cancellations.inc()
            self._observe_terminal(run, str(Phase.FINISHED))
            return None
        return min(1.0, max(0.1, drain - (now - started)))

    def _drain_timeout(self, run: Resource) -> float:
        """(reference: transport drain timeout resolution :1700-1810)"""
        story_ref = run.spec.get("storyRef") or {}
        story = self.store.try_get_view(
            STORY_KIND, story_ref.get("namespace") or run.meta.namespace,
            story_ref.get("name", ""),
        )
        if story is not None:
            spec = parse_story(story)
            if spec.policy and spec.policy.timeouts and spec.policy.timeouts.graceful_shutdown_timeout:
                return parse_duration(spec.policy.timeouts.graceful_shutdown_timeout, 30.0) or 30.0
        return 30.0

    # ------------------------------------------------------------------
    # trace + schema references
    # ------------------------------------------------------------------
    def _ensure_run_contracts(self, run, story, story_ns, story_name):
        """Persist TraceInfo + input/output SchemaReferences into run
        status (idempotent; one patch when anything changed)."""
        from ..api.schema_refs import ensure_status_contracts, story_schema_ref

        ns, name = run.meta.namespace, run.meta.name
        # executeStory handoff edge: a child run carries its parent's
        # trace context as an annotation (step_executor.TRACE_ANNOTATION)
        # so the sub-story — possibly owned by another shard — RESUMES
        # the parent trace instead of minting a fresh traceId
        parent_ctx = parse_trace_annotation(run.meta)
        version = (run.spec.get("storyRef") or {}).get("version") or story.version
        input_ref = (
            story_schema_ref(story_ns, story_name, "inputs", version)
            if story.inputs_schema
            else None
        )
        output_ref = (
            story_schema_ref(story_ns, story_name, "output", version)
            if story.outputs_schema
            else None
        )
        return ensure_status_contracts(
            self.store, self.tracer, STORY_RUN_KIND, run, input_ref, output_ref,
            span_name="storyrun.run",
            span_attrs={"story": story_name, "run": name, "namespace": ns},
            parent_ctx=parent_ctx,
        )

    # ------------------------------------------------------------------
    # redrive (reference: :295-807)
    # ------------------------------------------------------------------
    def _handle_redrive(self, run: Resource) -> Optional[float]:
        ns, name = run.meta.namespace, run.meta.name
        target = run.meta.annotations.get(REDRIVE_ANNOTATION, "")
        from_step = target.removeprefix("from:") if target.startswith("from:") else None

        story_ref = run.spec.get("storyRef") or {}
        story_res = self.store.try_get_view(
            STORY_KIND, story_ref.get("namespace") or ns, story_ref.get("name", "")
        )
        affected: Optional[set[str]] = None
        if from_step and story_res is not None:
            affected = _transitive_dependents(parse_story(story_res), from_step)
            affected.add(from_step)

        # delete affected child StepRuns (cascade removes their Jobs)
        for sr in self.store.list_views(
            STEP_RUN_KIND, namespace=ns, index=(INDEX_STEPRUN_STORYRUN, name)
        ):
            step_id = sr.spec.get("stepId") or ""
            if affected is not None and step_id not in affected:
                continue
            try:
                self.store.delete(STEP_RUN_KIND, ns, sr.meta.name)
                metrics.dependents_deleted.inc()
            except NotFound:
                pass

        def reset(r: Resource) -> None:
            r.meta.annotations.pop(REDRIVE_ANNOTATION, None)

        self.store.mutate(STORY_RUN_KIND, ns, name, reset)

        def reset_status(status: dict[str, Any]) -> None:
            states = status.get("stepStates") or {}
            if affected is None:
                status["stepStates"] = {}
                status.pop("stepTimers", None)
                status.pop("stopRequest", None)
                # a full redrive is a fresh run-through: the fleet
                # recovery tally restarts with it (the quarantine ledger
                # itself lives in the health registry, not run status)
                status.pop("preemptions", None)
                status.pop("preemptionsByStep", None)
            else:
                for step in affected:
                    states.pop(step, None)
                    (status.get("stepTimers") or {}).pop(step, None)
            status["phase"] = str(Phase.RUNNING)
            status.pop("error", None)
            status.pop("output", None)
            status.pop("finishedAt", None)
            status.pop("childrenCleanedAt", None)
            status["dagPhase"] = "main"
            status["redrives"] = int(status.get("redrives") or 0) + 1

        self.store.patch_status(STORY_RUN_KIND, ns, name, reset_status)
        return 0.0  # reconcile again immediately

    # ------------------------------------------------------------------
    # two-phase retention (reference: :1811-2069)
    # ------------------------------------------------------------------
    def _unpin(self, namespace: str, name: str) -> None:
        if (namespace, name) in self._pinned:
            self.storage.unpin_run(namespace, name)
            self._pinned.discard((namespace, name))
        # per-run quota gauges die with the run (bounded cardinality)
        scope = f"storyrun:{namespace}/{name}"
        metrics.quota_usage.remove(scope)
        metrics.quota_limit.remove(scope)

    def _handle_terminal(self, run: Resource) -> Optional[float]:
        ns, name = run.meta.namespace, run.meta.name
        cfg = self.config_manager.config.retention
        finished = run.status.get("finishedAt") or self.clock.now()
        now = self.clock.now()

        children_ttl = cfg.children_ttl_seconds
        retention = cfg.storyrun_retention_seconds

        if now - finished >= children_ttl and not run.status.get("childrenCleanedAt"):
            sweep_started = time.monotonic()
            for _sr_ns, sr_name in self.store.list_keys(
                STEP_RUN_KIND, namespace=ns, index=(INDEX_STEPRUN_STORYRUN, name)
            ):
                try:
                    self.store.delete(STEP_RUN_KIND, ns, sr_name)
                    metrics.cleanup_ops.inc("steprun")
                except NotFound:
                    pass
            metrics.cleanup_duration.observe(
                time.monotonic() - sweep_started, "children"
            )

            def mark(status: dict[str, Any]) -> None:
                status["childrenCleanedAt"] = now

            self.store.patch_status(STORY_RUN_KIND, ns, name, mark)

        if now - finished >= retention:
            try:
                self.store.delete(STORY_RUN_KIND, ns, name)
            except NotFound:
                pass
            # the flight ring dies with the run record (its tail already
            # rode terminal status while that existed)
            FLIGHT.forget(ns, name)
            return None

        next_boundary = min(
            (finished + children_ttl) if not run.status.get("childrenCleanedAt") else float("inf"),
            finished + retention,
        )
        return max(0.5, next_boundary - now)


def _validate_inputs(inputs: dict[str, Any], schema: dict[str, Any]) -> Optional[str]:
    try:
        import jsonschema

        jsonschema.validate(inputs, schema)
        return None
    except ImportError:  # pragma: no cover
        return None
    except Exception as e:  # noqa: BLE001
        return f"inputs schema validation failed: {getattr(e, 'message', e)}"


def _transitive_dependents(story, from_step: str) -> set[str]:
    """Steps that (transitively) depend on from_step
    (explicit needs + mined template refs)."""
    deps: dict[str, set[str]] = {}
    for s in story.steps:
        d = set(s.needs)
        d |= s.template_step_refs()
        deps[s.name] = d
    out: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, d in deps.items():
            if name in out:
                continue
            if from_step in d or (d & out):
                out.add(name)
                changed = True
    return out
