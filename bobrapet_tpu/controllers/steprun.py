"""StepRun controller: the workhorse of batch execution.

Capability parity with the reference's StepRun reconciler batch path
(reference: internal/controller/runs/steprun_controller.go —
Reconcile:195, reconcileNormal:300, reconcileJobExecution:533,
prepareExecutionContext:1265, resolveRunScopedInputs:2875,
tryCacheHit:3346, createJobForStep:1080, buildBaseEnvVars:1692,
handleJobStatus:1947, scheduleRetryIfNeeded:2165,
applyFailureFallback:2345):

guards -> engram/template resolution (Blocked + watch recovery when
missing) -> input resolution (scope build, template eval with the
offloaded-data policy, schema validation, `requires` checks,
re-dehydration) -> cache probe -> Job creation with the env contract +
TPU slice grant -> Job status handling (SDK-vs-controller output race,
output schema validation, postExecution check, declaredOutputKeys
warnings, cache write) -> exit classification -> retry scheduling.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from ..api import conditions
from ..api.catalog import (
    CLUSTER_NAMESPACE,
    ENGRAM_TEMPLATE_KIND,
    parse_engram_template,
)
from ..api.engram import KIND as ENGRAM_KIND, parse_engram
from ..api.enums import ExitClass, OffloadedDataPolicy, Phase, WorkloadMode
from ..api.errors import ErrorType, StructuredError, validation_error
from ..api.runs import STEP_RUN_KIND, STORY_RUN_KIND, parse_steprun
from ..api.story import KIND as STORY_KIND, parse_story
from ..core.events import EventRecorder
from ..core.store import AlreadyExists, NotFound, ResourceStore
from ..observability.analytics import LEDGER
from ..observability.metrics import metrics
from ..observability.structured import StepLogger
from ..observability.timeline import FLIGHT
from ..sdk import contract
from ..storage.manager import StorageManager
from ..templating.engine import (
    Evaluator,
    OffloadedDataUsage,
    TemplateError,
)
from ..utils.hashing import cache_key as compute_cache_key
from .jobs import JOB_KIND, make_job
from .manager import Clock
from .retry import classify_exit_code, compute_retry_delay, retry_budget_left

_log = logging.getLogger(__name__)

CANCEL_ANNOTATION = "runs.bobrapet.io/cancel"

#: bounded stale-scope requeues before the run fails for real (at the
#: 0.5s requeue that is ~60s — a rebalance drain clears in seconds; a
#: scope still stale after this is a genuine lost output, and failing
#: loudly keeps the churn-soak assert as the detector it is)
STALE_SCOPE_RETRY_CAP = 120


class StaleRunScope(Exception):
    """Input templates referenced a sibling step whose output the
    StoryRun status view does not carry YET: the view lags the
    sibling's output patch (observed during cross-shard rebalance
    drains — PR-6 vintage). The scope is stale, not wrong — the caller
    requeues instead of failing the run."""


class StepRunController:
    def __init__(
        self,
        store: ResourceStore,
        config_manager,
        resolver,
        storage: StorageManager,
        evaluator: Evaluator,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
        tracer=None,
        fleet=None,
    ):
        self.store = store
        self.config_manager = config_manager
        self.resolver = resolver
        self.storage = storage
        self.evaluator = evaluator
        self.recorder = recorder or EventRecorder()
        self.clock = clock or Clock()
        #: fleet.FleetManager — preemption quarantine + cordon-aware
        #: grant replacement (None disables the recovery subsystem;
        #: preemption-class exits then retry like plain signal deaths)
        self.fleet = fleet
        if tracer is None:
            from ..observability.tracing import TRACER as tracer
        self.tracer = tracer

    # ------------------------------------------------------------------
    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        # a view: this controller never edits sr in place — every write
        # goes through patch_status/mutate, which re-read-and-copy
        sr = self.store.try_get_view(STEP_RUN_KIND, namespace, name)
        if sr is None:
            return None
        phase = Phase(sr.status.get("phase")) if sr.status.get("phase") else None
        if phase is not None and phase.is_terminal:
            return None
        if sr.meta.deletion_timestamp is not None:
            return None
        spec = parse_steprun(sr)

        # graceful-cancel marker from the StoryRun controller
        if CANCEL_ANNOTATION in sr.meta.annotations:
            return self._finish_canceled(sr)

        # --- resolve engram + template (Blocked on missing refs,
        # reference: steprun_controller.go:320,374) ---
        # read-only views: the engram/template/story chain is resolved on
        # every reconcile and never mutated here — spec parses go through
        # the shared cached_parse objects anyway
        engram_name = spec.engram_ref.name if spec.engram_ref else ""
        engram = self.store.try_get_view(ENGRAM_KIND, namespace, engram_name)
        if engram is None:
            self._set_blocked(sr, conditions.Reason.REFERENCE_NOT_FOUND,
                              f"engram {engram_name!r} not found")
            return None
        engram_spec = parse_engram(engram)
        template_name = engram_spec.template_ref.name if engram_spec.template_ref else ""
        template = self.store.try_get_view(
            ENGRAM_TEMPLATE_KIND, CLUSTER_NAMESPACE, template_name
        )
        if template is None:
            self._set_blocked(sr, conditions.Reason.TEMPLATE_NOT_FOUND,
                              f"engram template {template_name!r} not found")
            return None
        template_spec = parse_engram_template(template)

        mode = engram_spec.mode or (
            template_spec.supported_modes[0]
            if template_spec.supported_modes
            else WorkloadMode.JOB
        )
        if mode.is_realtime:
            # realtime path materializes a long-running service + binding
            # (transport milestone); until the service reports ready the
            # StepRun stays Pending
            return self._reconcile_realtime(sr, spec, engram_spec, template_spec)
        return self._reconcile_job(sr, spec, engram, engram_spec, template, template_spec)

    # ------------------------------------------------------------------
    # batch path
    # ------------------------------------------------------------------
    def _reconcile_job(self, sr, spec, engram, engram_spec, template, template_spec):
        namespace, name = sr.meta.namespace, sr.meta.name

        # story context for scope + policies
        run_name = spec.story_run_ref.name if spec.story_run_ref else ""
        storyrun = self.store.try_get_view(STORY_RUN_KIND, namespace, run_name)
        story_policy = None
        story_name = ""
        step_def = None
        if storyrun is not None:
            story_name = (storyrun.spec.get("storyRef") or {}).get("name", "")
            story = self.store.try_get_view(STORY_KIND, namespace, story_name)
            if story is not None:
                story_spec = parse_story(story)
                story_policy = story_spec.policy
                if spec.step_id:
                    step_def = _find_step_def(story_spec, spec.step_id)

        resolved = self.resolver.resolve(
            template_spec=template_spec,
            engram_spec=engram_spec,
            story_policy=story_policy,
            step=step_def,
            steprun_overrides=spec.execution_overrides,
        )
        if spec.timeout:
            from ..utils.duration import parse_duration

            resolved.timeout_seconds = parse_duration(spec.timeout, resolved.timeout_seconds)
        if spec.retry is not None:
            from ..config.resolver import _merge_spec

            resolved.retry = _merge_spec(resolved.retry, spec.retry)

        job_name = sr.status.get("jobName")
        if job_name:
            return self._handle_job_status(
                sr, spec, resolved, template_spec, job_name, storyrun, story_name
            )

        # --- retry gate: a scheduled retry waits for nextRetryAt ---
        next_retry_at = sr.status.get("nextRetryAt")
        if next_retry_at is not None and self.clock.now() < float(next_retry_at):
            return float(next_retry_at) - self.clock.now()

        # --- deferred preemption re-placement: the dead gang's grant was
        # released at redrive time but no cordon-free block fit; keep
        # retrying — quarantine decay reopens capacity on its own ---
        if sr.status.get("awaitingSlice") and spec.slice_grant:
            if self.fleet is None:
                self.store.patch_status(
                    STEP_RUN_KIND, namespace, name,
                    lambda st: st.pop("awaitingSlice", None),
                )
            else:
                new_grant = self.fleet.place_pending(spec.slice_grant)
                if new_grant is None:
                    return max(
                        0.5, self.config_manager.config.fleet.redrive_delay_seconds
                    )
                if not self._install_replacement_grant(namespace, name, new_grant):
                    return None
                # re-read instead of patching the parsed spec: parse
                # objects are shared via cached_parse and immutable
                sr = self.store.try_get_view(STEP_RUN_KIND, namespace, name)
                if sr is None:
                    return None
                spec = parse_steprun(sr)
                self.recorder.normal(
                    sr, conditions.Reason.SLICE_PLACED,
                    f"replacement slice {new_grant.get('sliceId')} granted "
                    "after preemption",
                )

        # --- resolve inputs ---
        try:
            resolved_inputs = self._resolve_inputs(
                sr, spec, template_spec, storyrun, engram_spec
            )
        except OffloadedDataUsage as e:
            return self._fail(
                sr,
                StructuredError(
                    type=ErrorType.VALIDATION,
                    message=f"template references offloaded data under policy=fail: {e}",
                    exit_class=ExitClass.TERMINAL,
                ),
            )
        except StaleRunScope as e:
            # cross-shard lost-work guard: the sibling's output exists
            # (its StepRun succeeded) but this reconcile's StoryRun view
            # lags the patch. Requeue — never turn a replication lag
            # into a terminal run failure — with a hard cap so a
            # genuinely lost output still fails loudly.
            retries = int(sr.status.get("staleScopeRetries") or 0)
            if retries >= STALE_SCOPE_RETRY_CAP:
                metrics.steprun_stale_scope.inc("exhausted")
                return self._fail(
                    sr,
                    StructuredError(
                        type=ErrorType.VALIDATION,
                        message=(
                            f"input scope still stale after {retries} "
                            f"requeues (sibling output never surfaced): {e}"
                        ),
                        exit_class=ExitClass.TERMINAL,
                    ),
                )
            metrics.steprun_stale_scope.inc("requeued")
            self.store.patch_status(
                STEP_RUN_KIND, namespace, name,
                lambda st: st.update({"staleScopeRetries": retries + 1}),
            )
            if retries == 0 and run_name:
                FLIGHT.record(
                    namespace, run_name, "stale-scope",
                    message=f"step {spec.step_id or name}: sibling output "
                            f"missing from run view, requeueing ({e})",
                    step=spec.step_id or name, at=self.clock.now(),
                )
            return 0.5
        except TemplateError as e:
            return self._fail(
                sr,
                StructuredError(
                    type=ErrorType.VALIDATION,
                    message=f"input template evaluation failed: {e}",
                    exit_class=ExitClass.TERMINAL,
                ),
            )
        except InputValidationError as e:
            return self._fail(sr, validation_error(str(e)))

        # --- cache probe (reference: tryCacheHit:3346) ---
        cache_cfg = resolved.cache
        cache_enabled = bool(cache_cfg and cache_cfg.enabled)
        ck = None
        if cache_enabled:
            ck = self._cache_key(cache_cfg, resolved_inputs, template, engram)
            hit = self._cache_read(ck)
            metrics.steprun_cache_lookups.inc("hit" if hit is not None else "miss")
            if hit is not None:
                def apply_hit(status: dict[str, Any]) -> None:
                    status["phase"] = str(Phase.SUCCEEDED)
                    status["output"] = hit
                    status["cacheHit"] = True
                    status["finishedAt"] = self.clock.now()
                self.store.patch_status(STEP_RUN_KIND, namespace, name, apply_hit)
                self._observe_terminal(sr, str(Phase.SUCCEEDED))
                self.recorder.normal(sr, "CacheHit", f"cache key {ck[:12]} hit")
                return None

        # --- create the Job (gang of hosts, env contract) ---
        retries = int(sr.status.get("retries") or 0)
        attempt = int(sr.status.get("attempts") or 0)
        job_name = f"{name}-a{attempt}"
        tpu = resolved.tpu
        slice_grant = spec.slice_grant or {}
        hosts = int(slice_grant.get("hosts") or (tpu.hosts if tpu and tpu.hosts else 1))
        offloaded_inputs = self.storage.dehydrate(
            resolved_inputs,
            StorageManager.step_key(namespace, run_name or name, spec.step_id or name, "input"),
            max_inline_size=resolved.max_inline_size,
        )
        sr = self._ensure_step_contracts(sr, engram, template_spec, storyrun)
        cfg = self.config_manager.config
        # checkpoint-resume contract: the canonical prefix always ships;
        # after a preemption redrive the recorded latest-checkpoint step
        # rides along so training resumes instead of restarting at zero
        ckpt_prefix = self._checkpoint_prefix(namespace, name, spec)
        resume = sr.status.get("resumeFrom") or {}
        resume_step = resume.get("step")
        preemption_attempt = int(sr.status.get("preemptions") or 0)
        env = contract.build_env(
            namespace=namespace,
            story=story_name,
            story_run=run_name,
            step=spec.step_id or name,
            step_run=name,
            engram=engram.meta.name,
            execution_mode="job",
            inputs=offloaded_inputs,
            config=engram_spec.with_config or {},
            step_timeout_seconds=resolved.timeout_seconds,
            max_inline_size=resolved.max_inline_size,
            storage_timeout_seconds=cfg.engram.storage_timeout_seconds,
            max_recursion_depth=resolved.max_recursion_depth,
            grpc_port=cfg.engram.grpc_port,
            debug=resolved.debug,
            tpu_accelerator=str(tpu.accelerator) if tpu and tpu.accelerator else None,
            tpu_topology=slice_grant.get("topology") or (tpu.topology if tpu else None),
            tpu_hosts=hosts,
            coordinator_address=slice_grant.get("coordinatorAddress"),
            mesh_axes=slice_grant.get("meshAxes") or (tpu.mesh_axes if tpu else None),
            slice_id=slice_grant.get("sliceId"),
            trace_context=sr.status.get("trace"),
            checkpoint_prefix=ckpt_prefix,
            resume_step=resume_step,
            preemption_attempt=preemption_attempt,
            # spanning-gang membership: replica identity + global
            # process layout + the ONE span coordinator (build_env
            # overrides the per-pool coordinator with it)
            span=slice_grant.get("span"),
        )
        job = make_job(
            job_name,
            namespace,
            name,
            entrypoint=resolved.entrypoint or resolved.image or "",
            env=env,
            hosts=hosts,
            timeout_seconds=resolved.timeout_seconds,
            image=resolved.image,
            slice_grant=slice_grant or None,
            owners=[sr.owner_ref()],
            labels={
                "bobrapet.io/story-run": run_name,
                "bobrapet.io/step": spec.step_id or name,
            },
        )
        # pods act under the run-scoped identity (reference: rbac.go)
        if storyrun is not None and storyrun.status.get("serviceAccount"):
            job.spec["serviceAccountName"] = storyrun.status["serviceAccount"]

        def mark_running(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.RUNNING)
            status["jobName"] = job_name
            status["attempts"] = attempt + 1
            status["retries"] = retries
            status.setdefault("startedAt", self.clock.now())
            status.pop("nextRetryAt", None)
            status.pop("staleScopeRetries", None)
            # consumed into this attempt's env; a later preemption
            # recomputes it from the then-latest checkpoint
            status.pop("resumeFrom", None)
            if ck is not None:
                status["cacheKey"] = ck

        # the gang-dispatch hop of the run trace: parented on the
        # StepRun's persisted context (a child of the StoryRun trace via
        # _ensure_step_contracts), so admission -> scheduling ->
        # placement -> dispatch -> SDK reads as one chain
        # the dispatch INSTANT, captured before the Job create: the
        # sync local executor runs the gang inside create(), so a
        # clock read after it would fold the attempt's time into the
        # pre-dispatch segment
        dispatch_at = self.clock.now()
        # chip-time ledger: the segment from grant-open (or the prior
        # attempt's end) to this dispatch was held-idle — placement
        # park/input resolution on a first attempt, redrive wait on a
        # relaunch. The attempt's own chip time is labeled when the Job
        # reports back.
        if slice_grant.get("sliceId"):
            LEDGER.account(
                slice_grant["sliceId"],
                "retry" if attempt > 0 else "park",
                dispatch_at,
                tenant=self._tenant(storyrun, namespace),
            )
        if run_name:
            FLIGHT.record(
                namespace, run_name, "dispatch",
                message=f"step {spec.step_id or name}: job {job_name} "
                        f"({hosts} host(s))",
                step=spec.step_id or name, at=dispatch_at,
            )
        with self.tracer.start_span(
            "steprun.dispatch",
            trace_context=sr.status.get("trace"),
            step_run=name, job=job_name, hosts=hosts,
            run=run_name, namespace=namespace,
        ):
            # mark first so the job-status watch can't race an
            # unclaimed state
            self.store.patch_status(STEP_RUN_KIND, namespace, name, mark_running)
            if resume_step is not None:
                metrics.fleet_resumed_steps.inc()
            if preemption_attempt and self.fleet is not None:
                # the recovered gang is relaunching now — close the
                # preemption-to-relaunch latency window
                self.fleet.observe_recovery(
                    namespace, name, slice_grant.get("pool", "")
                )
            try:
                self.store.create(job)
            except AlreadyExists:
                pass  # adopt: deterministic name makes the create idempotent
        # while this step's Job dispatches, warm the payload tiers with
        # the run scope's refs (run inputs + prior step outputs): the
        # NEXT steps' input resolution and this step's output
        # validation read the same refs and will hit the hydrate LRU —
        # and, once fetched, the slice-local disk tier holds them for
        # every later process on this slice (fire-and-forget; never
        # blocks the reconcile)
        if storyrun is not None:
            self.storage.prefetch(
                {
                    "inputs": storyrun.spec.get("inputs"),
                    "steps": storyrun.status.get("stepStates"),
                },
                [StorageManager.run_prefix(namespace, run_name)],
            )
        return None

    @staticmethod
    def _tenant(storyrun, namespace: str) -> str:
        """Goodput attribution identity: the run's tenant label, else
        its namespace (bounded cardinality either way)."""
        if storyrun is not None:
            label = storyrun.meta.labels.get("bobrapet.io/tenant")
            if label:
                return str(label)
        return namespace

    # ------------------------------------------------------------------
    def _handle_job_status(
        self, sr, spec, resolved, template_spec, job_name, storyrun, story_name
    ):
        namespace, name = sr.meta.namespace, sr.meta.name
        job = self.store.try_get_view(JOB_KIND, namespace, job_name)
        if job is None:
            # job vanished (evicted/cleaned) -> unknown exit, retry without
            # consuming budget (reference: ExitClassUnknown semantics)
            return self._handle_failure(sr, spec, resolved, exit_code=None, message="job vanished")
        jphase = job.status.get("phase")
        if jphase == str(Phase.SUCCEEDED):
            return self._handle_success(sr, spec, resolved, template_spec, job)
        if jphase == str(Phase.FAILED):
            return self._handle_failure(
                sr,
                spec,
                resolved,
                exit_code=job.status.get("exitCode"),
                message=job.status.get("message", ""),
                preempted=bool(job.status.get("preempted")),
                preempted_host=job.status.get("preemptedHost"),
                job_name=job_name,
            )
        return None  # still running; job watch will re-trigger us

    def _handle_success(self, sr, spec, resolved, template_spec, job):
        namespace, name = sr.meta.namespace, sr.meta.name
        fresh = self.store.get_view(STEP_RUN_KIND, namespace, name)
        # SDK-vs-controller race (reference: stepStatusPatchedBySDK:2031):
        # the SDK writes status.output directly; the controller only reads
        # it here — a job that succeeded without reporting yields {}
        output = fresh.status.get("output")
        if output is None:
            output = {}

        # output schema validation (reference: handleJobSucceeded:2050)
        if template_spec.output_schema:
            err = _validate_schema(
                self._hydrated_for_validation(output, namespace, spec), template_spec.output_schema, "output"
            )
            if err is not None:
                return self._fail(sr, validation_error(err))

        # postExecution condition (reference: :2088)
        post = spec_post_execution(sr)
        if post is not None:
            scope = {"inputs": {}, "steps": {}, "run": {}, "output": output}
            try:
                ok = self.evaluator.evaluate_condition(post.get("condition", ""), {**scope, "steps": {}})
            except TemplateError as e:
                return self._fail(sr, validation_error(f"postExecution evaluation failed: {e}"))
            if not ok:
                msg = post.get("failureMessage") or "postExecution condition failed"
                return self._fail(sr, StructuredError(
                    type=ErrorType.VALIDATION, message=msg, exit_class=ExitClass.TERMINAL))

        # declaredOutputKeys warnings (reference: declared keys advisory)
        if template_spec.declared_output_keys and isinstance(output, dict):
            missing = [k for k in template_spec.declared_output_keys if k not in output]
            if missing:
                self.recorder.warning(
                    sr, "DeclaredOutputKeysMissing",
                    f"output missing declared keys: {missing}",
                )

        # cache write (reference: maybeWriteCache:3403)
        ck = fresh.status.get("cacheKey")
        if ck and resolved.cache and resolved.cache.enabled:
            self._cache_write(ck, output, resolved.cache)

        exit_code = job.status.get("exitCode", 0)

        def finish(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.SUCCEEDED)
            status["output"] = output
            status["exitCode"] = exit_code
            status["exitClass"] = str(ExitClass.SUCCESS)
            status["finishedAt"] = self.clock.now()
            status.pop("error", None)

        # the attempt's chip time was goodput — the one bucket the
        # per-tenant counters scale on. Accounted BEFORE the terminal
        # patch: the release watch fires synchronously on it and closes
        # the ledger entry (the tail after this mark is drain).
        LEDGER.account(
            (spec.slice_grant or {}).get("sliceId"), "productive",
            self.clock.now(),
        )
        self.store.patch_status(STEP_RUN_KIND, namespace, name, finish)
        # logging.step-output toggle (reference: pkg/logging/features.go)
        StepLogger("steprun", namespace=namespace, object=name).step_output(output)
        self._observe_terminal(fresh, str(Phase.SUCCEEDED))
        return None

    def _handle_failure(
        self, sr, spec, resolved, exit_code, message,
        preempted=False, preempted_host=None, job_name=None,
    ):
        namespace, name = sr.meta.namespace, sr.meta.name
        # without a FleetManager the preemption marker is ignored and the
        # death classifies like any signal (retry on the user budget) —
        # the recovery subsystem must be all-on or all-off
        exit_class = classify_exit_code(
            exit_code, preempted=preempted and self.fleet is not None
        )
        if exit_class is ExitClass.PREEMPTED:
            return self._handle_preemption(
                sr, spec, exit_code, message, preempted_host, job_name
            )
        retries = int(sr.status.get("retries") or 0)
        retry_policy = resolved.retry

        if exit_class.is_retryable and (
            not exit_class.consumes_retry_budget
            or retry_budget_left(retry_policy, retries)
        ):
            consumed = retries + (1 if exit_class.consumes_retry_budget else 0)
            delay = compute_retry_delay(
                retry_policy,
                attempt=max(1, consumed),
                rate_limited=exit_class is ExitClass.RATE_LIMITED,
            )
            due = self.clock.now() + delay

            def schedule(status: dict[str, Any]) -> None:
                status["phase"] = str(Phase.PENDING)
                status["retries"] = consumed
                status["nextRetryAt"] = due
                status["exitCode"] = exit_code
                status["exitClass"] = str(exit_class)
                status.pop("jobName", None)
                # dead attempt's liveness stamps must not outlive it
                status.pop("hostHeartbeats", None)

            self.store.patch_status(STEP_RUN_KIND, namespace, name, schedule)
            # the failed attempt's chip time is retry waste (the grant
            # stays held across the backoff; the relaunch dispatch
            # labels the wait itself)
            LEDGER.account(
                (spec.slice_grant or {}).get("sliceId"), "retry",
                self.clock.now(),
            )
            metrics.steprun_retries.inc(str(exit_class))
            self.recorder.warning(
                sr, conditions.Reason.RETRY_SCHEDULED,
                f"exit {exit_code} ({exit_class}); retry {consumed} in {delay:.1f}s",
            )
            return delay

        # terminal failure; keep SDK-reported structured error if present
        fresh = self.store.get_view(STEP_RUN_KIND, namespace, name)
        err_payload = fresh.status.get("error")
        if not err_payload:
            # applyFailureFallback (reference: :2345) — SDK died before
            # reporting; synthesize from the exit facts
            err_payload = StructuredError(
                type=ErrorType.TIMEOUT if exit_code == contract.EXIT_TIMEOUT else ErrorType.EXECUTION,
                message=message or f"step failed with exit code {exit_code}",
                exit_class=exit_class,
                retryable=False,
                details={"exitCode": exit_code},
            ).to_dict()

        phase = Phase.TIMEOUT if exit_code == contract.EXIT_TIMEOUT else Phase.FAILED

        def fail(status: dict[str, Any]) -> None:
            status["phase"] = str(phase)
            status["exitCode"] = exit_code
            status["exitClass"] = str(exit_class)
            status["error"] = err_payload
            status["finishedAt"] = self.clock.now()

        # before the terminal patch: its release watch closes the entry
        LEDGER.account(
            (spec.slice_grant or {}).get("sliceId"), "failed",
            self.clock.now(),
        )
        self.store.patch_status(STEP_RUN_KIND, namespace, name, fail)
        self._observe_terminal(fresh, str(phase))
        return None

    # ------------------------------------------------------------------
    # fleet preemption recovery (TPU-native; no reference counterpart —
    # the reference retries 137/143 from scratch on the user budget)
    # ------------------------------------------------------------------
    @staticmethod
    def _checkpoint_prefix(namespace: str, name: str, spec) -> str:
        """The one canonical checkpoint prefix — exported to the worker
        as BOBRA_CHECKPOINT_PREFIX at launch AND probed for the resume
        step at redrive; a single derivation so the two can't diverge."""
        from ..sdk.checkpoint import STEP_CHECKPOINT_FIELD

        run_name = spec.story_run_ref.name if spec.story_run_ref else ""
        return StorageManager.step_key(
            namespace, run_name or name, spec.step_id or name,
            STEP_CHECKPOINT_FIELD,
        )

    def _install_replacement_grant(
        self, namespace: str, name: str, new_grant: dict[str, Any]
    ) -> bool:
        """Commit a freshly-allocated replacement grant into the StepRun
        spec (one atomic mutate: grant in, awaitingSlice flag out).
        False = the StepRun vanished mid-recovery; the grant is released
        and recovery tracking abandoned — nothing references the block,
        so the terminal-phase release watch could never reclaim it."""

        def swap(r):
            r.spec["sliceGrant"] = new_grant
            r.status.pop("awaitingSlice", None)

        try:
            self.store.mutate(STEP_RUN_KIND, namespace, name, swap)
            # the replacement block's clock starts now; the relaunch
            # dispatch labels the redrive wait
            LEDGER.open_grant(new_grant, self.clock.now())
            return True
        except NotFound:
            self.fleet.placer.release(new_grant)
            self.fleet.abandon_recovery(namespace, name)
            return False

    def _handle_preemption(
        self, sr, spec, exit_code, message, preempted_host, job_name
    ):
        """Checkpoint-resuming gang redrive: quarantine the reclaimed
        host's cells, re-place the gang onto a healthy sub-mesh, and
        inject resume env — all against ``fleet.preemption-retry-cap``,
        leaving the user policy's ``retries`` untouched."""
        namespace, name = sr.meta.namespace, sr.meta.name
        fleet_cfg = self.config_manager.config.fleet
        preemptions = int(sr.status.get("preemptions") or 0)
        grant = spec.slice_grant
        try:
            # external writers may stamp a node NAME here; an unknown
            # host quarantines the whole grant block instead of wedging
            # the reconcile
            host = int(preempted_host) if preempted_host is not None else None
        except (TypeError, ValueError):
            host = None

        if self.fleet is not None and grant:
            # one event key shared with the fleet watcher (both observe
            # the same dead Job; the registry books it once)
            self.fleet.on_preemption(
                grant, host=host,
                key=f"{namespace}/{job_name}" if job_name else None,
            )

        if preemptions >= fleet_cfg.preemption_retry_cap:
            err = StructuredError(
                type=ErrorType.EXECUTION,
                message=(
                    f"preempted {preemptions + 1}x; "
                    f"fleet.preemption-retry-cap={fleet_cfg.preemption_retry_cap} "
                    "exhausted"
                ),
                exit_class=ExitClass.PREEMPTED,
                retryable=False,
                details={"exitCode": exit_code, "preemptions": preemptions + 1},
            ).to_dict()

            def exhaust(status: dict[str, Any]) -> None:
                status["phase"] = str(Phase.FAILED)
                status["exitCode"] = exit_code
                status["exitClass"] = str(ExitClass.PREEMPTED)
                status["preemptions"] = preemptions + 1
                status["error"] = err
                status["finishedAt"] = self.clock.now()
                conds = status.setdefault("conditions", [])
                conditions.set_condition(
                    conds, conditions.PREEMPTION_RECOVERED, False,
                    conditions.Reason.PREEMPTION_BUDGET_EXHAUSTED, message or "",
                    now=self.clock.now(),
                )

            # before the terminal patch (its release watch closes the
            # entry): the dead attempt's time is preempted waste
            LEDGER.account(
                (grant or {}).get("sliceId"), "preempted", self.clock.now()
            )
            self.store.patch_status(STEP_RUN_KIND, namespace, name, exhaust)
            self._observe_terminal(sr, str(Phase.FAILED))
            if self.fleet is not None:
                self.fleet.abandon_recovery(namespace, name)
            self.recorder.warning(
                sr, conditions.Reason.PREEMPTION_BUDGET_EXHAUSTED,
                f"preemption retry cap {fleet_cfg.preemption_retry_cap} exhausted",
            )
            return None

        # re-place onto a healthy sub-mesh; the dead grant is released
        # either way (fail fast — never hold a reclaimed slice)
        new_grant = None
        awaiting = False
        awaiting_hint = ""
        if grant:
            # the dead attempt's chip time since the last mark was lost
            # to the reclaim, and replace_grant releases the block below
            # — close its ledger entry under the preempted bucket
            LEDGER.close_grant(
                grant.get("sliceId"), "preempted", self.clock.now()
            )
            if self.fleet is not None:
                self.fleet.begin_recovery(namespace, name)
                new_grant = self.fleet.replace_grant(grant)
                awaiting = new_grant is None
                if awaiting:
                    # what the pool could still place — the figure the
                    # operator needs to judge whether the park will clear
                    # on quarantine decay or needs a capacity fix
                    awaiting_hint = self.fleet.capacity_hint(grant)
            if new_grant is not None and not self._install_replacement_grant(
                namespace, name, new_grant
            ):
                return None

        # resume facts for the relaunch env: the latest checkpoint this
        # step completed before the reclaim (None -> fresh start)
        prefix = self._checkpoint_prefix(namespace, name, spec)
        resume_step = None
        try:
            # restorable, not merely newest: a reclaim mid-save leaves a
            # partial checkpoint whose manifests can't cover the shapes
            from ..sdk.checkpoint import latest_restorable_checkpoint_step

            resume_step = latest_restorable_checkpoint_step(
                self.storage.store, prefix
            )
        except Exception:  # noqa: BLE001 - storage probe is best-effort
            pass

        delay = max(0.0, fleet_cfg.redrive_delay_seconds)
        due = self.clock.now() + delay

        def redrive(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.PENDING)
            status["preemptions"] = preemptions + 1
            status["nextRetryAt"] = due
            status["exitCode"] = exit_code
            status["exitClass"] = str(ExitClass.PREEMPTED)
            status.pop("jobName", None)
            # beats belong to the dead attempt; judging them stale later
            # would book false suspicion against the REPLACEMENT grant
            status.pop("hostHeartbeats", None)
            if resume_step is not None:
                status["resumeFrom"] = {"prefix": prefix, "step": resume_step}
            if awaiting:
                status["awaitingSlice"] = True
            conds = status.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.PREEMPTION_RECOVERED, True,
                conditions.Reason.AWAITING_HEALTHY_SLICE if awaiting
                else conditions.Reason.PREEMPTION_REDRIVE,
                f"preemption {preemptions + 1}: "
                + (f"resuming from checkpoint step {resume_step}"
                   if resume_step is not None else "restarting from step zero")
                + (f"; no healthy block fits ({awaiting_hint})"
                   if awaiting_hint else ""),
                now=self.clock.now(),
            )

        self.store.patch_status(STEP_RUN_KIND, namespace, name, redrive)
        metrics.steprun_retries.inc(str(ExitClass.PREEMPTED))
        run_name = spec.story_run_ref.name if spec.story_run_ref else name
        FLIGHT.record(
            namespace, run_name, "preemption",
            message=f"step {spec.step_id or name}: host {preempted_host} "
                    f"preempted (exit {exit_code}); redrive "
                    f"{preemptions + 1}/{fleet_cfg.preemption_retry_cap}"
                    + (", awaiting healthy slice" if awaiting else ""),
            step=spec.step_id or name, at=self.clock.now(),
        )
        self.recorder.warning(
            sr, conditions.Reason.PREEMPTION_REDRIVE,
            f"host {preempted_host} preempted (exit {exit_code}); "
            f"redrive {preemptions + 1}/{fleet_cfg.preemption_retry_cap}"
            + (f", resume from step {resume_step}" if resume_step is not None
               else ""),
        )
        return delay

    def _observe_terminal(self, sr, phase: str) -> None:
        metrics.steprun_total.inc(phase)
        started = sr.status.get("startedAt")
        if started is not None:
            engram = (sr.spec.get("engramRef") or {}).get("name") or ""
            metrics.steprun_duration.observe(self.clock.now() - float(started), engram)
        if phase in (str(Phase.FAILED), str(Phase.TIMEOUT)):
            run_name = (sr.spec.get("storyRunRef") or {}).get("name")
            if run_name:
                err = sr.status.get("error") or {}
                FLIGHT.record(
                    sr.meta.namespace, run_name, "error",
                    message=f"step {sr.spec.get('stepId') or sr.meta.name} "
                            f"{phase}: "
                            f"{str(err.get('message') or '')[:256]}",
                    step=sr.spec.get("stepId") or sr.meta.name,
                    at=self.clock.now(),
                )

    def _fail(self, sr, err: StructuredError):
        def fail(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.FAILED)
            status["error"] = err.to_dict()
            status["finishedAt"] = self.clock.now()

        # validation/postExecution/template failures are attempt waste
        # like any other terminal failure — account BEFORE the terminal
        # patch whose release watch closes the grant (else the whole
        # attempt misattributes to drain)
        LEDGER.account(
            (sr.spec.get("sliceGrant") or {}).get("sliceId"), "failed",
            self.clock.now(),
        )
        self.store.patch_status(STEP_RUN_KIND, sr.meta.namespace, sr.meta.name, fail)
        self._observe_terminal(sr, str(Phase.FAILED))
        return None

    def _finish_canceled(self, sr):
        job_name = sr.status.get("jobName")
        if job_name:
            try:
                self.store.delete(JOB_KIND, sr.meta.namespace, job_name)
            except NotFound:
                pass

        # a realtime step also tears its stream topology down
        # (reference: ReasonTopologyTerminated consumed at dag.go:441)
        if sr.status.get("bindingName"):
            from ..api.transport import TRANSPORT_BINDING_KIND

            try:
                self.store.patch_status(
                    TRANSPORT_BINDING_KIND, sr.meta.namespace,
                    sr.status["bindingName"],
                    lambda st: st.update(
                        {"phase": "Terminated", "terminatedAt": self.clock.now()}
                    ),
                )
            except NotFound:
                pass

        def cancel(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.FINISHED)
            status["finishedAt"] = self.clock.now()
            status["reason"] = conditions.Reason.CANCELED

        self.store.patch_status(STEP_RUN_KIND, sr.meta.namespace, sr.meta.name, cancel)
        self._observe_terminal(sr, str(Phase.FINISHED))
        return None

    def _set_blocked(self, sr, reason: str, message: str):
        def block(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.BLOCKED)
            status["reason"] = reason
            status["message"] = message
            conds = status.setdefault("conditions", [])
            conditions.set_condition(conds, conditions.READY, False, reason, message,
                                     now=self.clock.now())

        self.store.patch_status(STEP_RUN_KIND, sr.meta.namespace, sr.meta.name, block)
        self.recorder.warning(sr, reason, message)

    # ------------------------------------------------------------------
    # input resolution
    # ------------------------------------------------------------------
    def _resolve_inputs(self, sr, spec, template_spec, storyrun, engram_spec):
        """(reference: resolveRunScopedInputs:2875)"""
        from .materialize import MATERIALIZE_ANNOTATION

        if sr.meta.annotations.get(MATERIALIZE_ANNOTATION):
            # materialize delegate: input ships verbatim — storage refs
            # intact — so hydration happens in-pod, which is the whole
            # point of the controller policy (reference: materialize.go)
            return spec.input or {}
        namespace = sr.meta.namespace
        run_inputs: dict[str, Any] = {}
        prior_outputs: dict[str, Any] = {}
        run_meta: dict[str, Any] = {}
        if storyrun is not None:
            run_inputs = storyrun.spec.get("inputs") or {}
            for step_name, state in (storyrun.status.get("stepStates") or {}).items():
                prior_outputs[step_name] = {
                    "output": state.get("output"),
                    "signals": state.get("signals") or {},
                    "phase": state.get("phase"),
                }
            run_meta = {
                "name": storyrun.meta.name,
                "namespace": namespace,
                "storyName": (storyrun.spec.get("storyRef") or {}).get("name", ""),
            }
        scope = {"inputs": run_inputs, "steps": prior_outputs, "run": run_meta}

        raw = spec.input or {}
        policy = self.config_manager.config.templating.offloaded_data_policy
        # when the scope had to be hydrated for evaluation, the SAME
        # hydrated values feed schema validation below — the ref fetches
        # happen once per reconcile, not once per consumer
        evaluated_hydrated = False
        try:
            try:
                resolved = self.evaluator.evaluate_value(raw, scope)
            except OffloadedDataUsage:
                raise  # policy hydration below, not a stale-scope case
            except TemplateError:
                # the StoryRun status view can LAG a sibling's output
                # patch (cross-shard rebalance drain): resolve the
                # missing outputs from the authoritative StepRun state
                # and retry once before judging the template
                if not self._authoritative_steps_overlay(
                    namespace, storyrun, scope
                ):
                    stale = self._stale_output_refs(raw, scope)
                    if stale:
                        # the reference IS a succeeded sibling whose
                        # output no view carries yet (patch in flight):
                        # stale, not wrong — requeue
                        raise StaleRunScope(
                            f"succeeded sibling(s) {stale} have no "
                            f"output in the run view yet"
                        ) from None
                    raise
                metrics.steprun_stale_scope.inc("healed")
                try:
                    resolved = self.evaluator.evaluate_value(raw, scope)
                except OffloadedDataUsage:
                    raise
                except TemplateError:
                    # overlay healed some refs but not all — if what
                    # remains is still a stale sibling, requeue
                    stale = self._stale_output_refs(raw, scope)
                    if stale:
                        raise StaleRunScope(
                            f"succeeded sibling(s) {stale} have no "
                            f"output in the run view yet"
                        ) from None
                    raise
        except OffloadedDataUsage:
            if policy is OffloadedDataPolicy.FAIL:
                raise
            # inject / controller policies hydrate the offloaded values
            # into the scope and re-evaluate (reference: in-process resolve
            # resolve_inprocess.go; controller-materialize delegates to a
            # dedicated engram — here hydration happens in-controller)
            prefix = f"runs/{namespace}/{storyrun.meta.name}" if storyrun is not None else None
            hydrated_scope = {
                "inputs": self.storage.hydrate(run_inputs, [prefix] if prefix else None),
                "steps": self.storage.hydrate(prior_outputs, [prefix] if prefix else None),
                "run": run_meta,
            }
            resolved = self.evaluator.evaluate_value(raw, hydrated_scope)
            evaluated_hydrated = True

        # `requires` checks (reference: :5523)
        story = None
        step_def = None
        if storyrun is not None:
            story_name = (storyrun.spec.get("storyRef") or {}).get("name", "")
            story = self.store.try_get_view(STORY_KIND, namespace, story_name)
        if story is not None and spec.step_id:
            step_def = parse_story(story).step(spec.step_id)
        if step_def is not None and step_def.requires:
            missing = [
                k for k in step_def.requires
                if not isinstance(resolved, dict) or k not in resolved or resolved.get(k) is None
            ]
            if missing:
                raise InputValidationError(f"required inputs missing: {missing}")

        # input schema validation (hydrate markers first so the schema sees
        # real values). A scope hydrated for evaluation is SHARED with
        # validation: scope-derived values are already real, so unless
        # the raw input carried a verbatim marker there is nothing left
        # to fetch — and what is left hits the hydrate LRU warmed by the
        # scope pass, not the store.
        if template_spec.input_schema:
            if evaluated_hydrated and not _contains_marker(resolved):
                to_validate = resolved
            else:
                to_validate = self._hydrated_for_validation(
                    resolved, namespace, spec
                )
            err = _validate_schema(to_validate, template_spec.input_schema, "input")
            if err is not None:
                raise InputValidationError(err)
        return resolved

    def _hydrated_for_validation(self, value, namespace, spec):
        run_name = spec.story_run_ref.name if spec.story_run_ref else ""
        prefix = f"runs/{namespace}/{run_name}" if run_name else None
        try:
            return self.storage.hydrate(value, [prefix] if prefix else None)
        except Exception:  # noqa: BLE001 - validation best-effort on refs
            return value

    # ------------------------------------------------------------------
    # stale-scope recovery (cross-shard lost-work guard)
    # ------------------------------------------------------------------
    def _authoritative_steps_overlay(
        self, namespace: str, storyrun, scope: dict[str, Any]
    ) -> bool:
        """Fill scope["steps"] entries whose output is missing from the
        (possibly lagging) StoryRun status view with the AUTHORITATIVE
        StepRun status — the output patch lands on the sibling StepRun
        strictly before the DAG merges it into stepStates, so the
        StepRun is the source of truth whenever the two disagree.
        Returns True when anything was filled. Deterministic top-level
        StepRun names only; `parallel` branch outputs roll up through
        the parent timer and never resolve here."""
        if storyrun is None:
            return False
        from ..utils.naming import steprun_name

        changed = False
        for step_name, entry in list((scope.get("steps") or {}).items()):
            if not isinstance(entry, dict) or entry.get("output") is not None:
                continue
            sib = self.store.try_get_view(
                STEP_RUN_KIND, namespace,
                steprun_name(storyrun.meta.name, step_name),
            )
            if sib is None:
                continue
            out = sib.status.get("output")
            if out is None:
                continue
            healed = dict(entry)
            healed["output"] = out
            healed["phase"] = sib.status.get("phase") or entry.get("phase")
            scope["steps"][step_name] = healed
            changed = True
        return changed

    def _stale_output_refs(
        self, raw: Any, scope: dict[str, Any]
    ) -> list[str]:
        """Referenced sibling steps whose run-view state says SUCCEEDED
        yet carries no output — the exact signature of an output patch
        the view has not absorbed yet (anything else failing template
        evaluation is a genuine authoring error and must stay one)."""
        try:
            refs = Evaluator.find_step_references({"with": raw})
        except Exception:  # noqa: BLE001 - detector must never mask the error
            return []
        stale = []
        for name in refs:
            entry = (scope.get("steps") or {}).get(name)
            if (
                isinstance(entry, dict)
                and entry.get("phase") == str(Phase.SUCCEEDED)
                and entry.get("output") is None
            ):
                stale.append(name)
        return sorted(stale)

    # ------------------------------------------------------------------
    # cache
    # ------------------------------------------------------------------
    def _ensure_step_contracts(self, sr, engram, template_spec, storyrun):
        """Persist TraceInfo (child of the StoryRun's trace) + engram
        schema references into StepRun status
        (reference: ensureStepRunSchemaRefs steprun_controller.go:2138,
        pkg/runs/status/trace.go)."""
        from ..api.schema_refs import engram_schema_ref, ensure_status_contracts

        ns, name = sr.meta.namespace, sr.meta.name
        version = getattr(template_spec, "version", None)
        input_ref = (
            engram_schema_ref(ns, engram.meta.name, "input", version)
            if template_spec.input_schema
            else None
        )
        output_ref = (
            engram_schema_ref(ns, engram.meta.name, "output", version)
            if template_spec.output_schema
            else None
        )
        return ensure_status_contracts(
            self.store, self.tracer, STEP_RUN_KIND, sr, input_ref, output_ref,
            span_name="steprun.launch",
            span_attrs={"step_run": name, "namespace": ns},
            parent_ctx=storyrun.status.get("trace") if storyrun is not None else None,
        )

    def _cache_key(self, cache_cfg, resolved_inputs, template, engram) -> str:
        salt = cache_cfg.salt or ""
        mode = cache_cfg.mode or "inputs"
        basis = {
            "inputs": resolved_inputs,
            "template": template.meta.name,
            "templateGeneration": template.meta.generation,
            "engram": engram.meta.name,
        }
        if mode == "key" and cache_cfg.key:
            basis = {"key": cache_cfg.key}
        return compute_cache_key(basis, salt=salt, mode=mode)

    def _cache_blob_key(self, ck: str) -> str:
        return f"cache/steps/{ck}"

    def _cache_read(self, ck: str):
        import json

        from ..storage.store import BlobNotFound

        try:
            data = self.storage.store.get(self._cache_blob_key(ck))
        except BlobNotFound:
            return None
        try:
            payload = json.loads(data.decode())
        except ValueError:
            return None
        ttl = payload.get("ttlSeconds")
        if ttl and self.clock.now() - payload.get("storedAt", 0) > ttl:
            return None
        return payload.get("output")

    def _cache_write(self, ck: str, output, cache_cfg) -> None:
        import json

        payload = {
            "output": output,
            "storedAt": self.clock.now(),
            "ttlSeconds": cache_cfg.ttl_seconds,
        }
        self.storage.store.put(
            self._cache_blob_key(ck), json.dumps(payload, default=str).encode()
        )

    # ------------------------------------------------------------------
    # realtime placeholder (full implementation in the transport layer)
    # ------------------------------------------------------------------
    def _reconcile_realtime(self, sr, spec, engram_spec, template_spec):
        from .streaming import reconcile_realtime_step

        sr = self._ensure_realtime_trace(sr, spec)
        return reconcile_realtime_step(self, sr, spec, engram_spec, template_spec)

    def _ensure_realtime_trace(self, sr, spec):
        """Persist a TraceInfo child of the StoryRun trace into a
        realtime StepRun's status (the batch path does this in
        _ensure_step_contracts; realtime must too, or the serving
        engram's env contract carries no context and the request
        lifecycle falls out of the run trace)."""
        if sr.status.get("trace") is not None or not self.tracer.config.enabled:
            return sr
        ns, name = sr.meta.namespace, sr.meta.name
        run_name = spec.story_run_ref.name if spec.story_run_ref else ""
        storyrun = (
            self.store.try_get_view(STORY_RUN_KIND, ns, run_name)
            if run_name else None
        )
        from ..api.schema_refs import ensure_status_contracts

        return ensure_status_contracts(
            self.store, self.tracer, STEP_RUN_KIND, sr, None, None,
            span_name="steprun.realtime",
            span_attrs={"step_run": name, "namespace": ns, "run": run_name},
            parent_ctx=(storyrun.status.get("trace")
                        if storyrun is not None else None),
        )


class InputValidationError(Exception):
    pass


def _contains_marker(value) -> bool:
    """True when any storageRef marker survives in a value tree."""
    from ..templating.engine import is_storage_ref

    if is_storage_ref(value):
        return True
    if isinstance(value, dict):
        return any(_contains_marker(v) for v in value.values())
    if isinstance(value, list):
        return any(_contains_marker(v) for v in value)
    return False


def _find_step_def(story_spec, step_id: str):
    """Locate a step definition by name, including `parallel` branches
    (both spellings: explicit `steps` and the replicas/step fan-out)."""
    from ..api.story import expand_parallel_branches

    direct = story_spec.step(step_id)
    if direct is not None:
        return direct
    for s in story_spec.all_steps():
        if s.type is not None and s.with_:
            for branch in expand_parallel_branches(s):
                if branch.name == step_id:
                    return branch
    return None


def spec_post_execution(sr) -> Optional[dict[str, Any]]:
    return (sr.spec.get("postExecution") or None) if isinstance(sr.spec, dict) else None


def _validate_schema(value, schema: dict[str, Any], what: str) -> Optional[str]:
    try:
        import jsonschema

        jsonschema.validate(value, schema)
        return None
    except ImportError:  # pragma: no cover
        return None
    except Exception as e:  # noqa: BLE001 - collapse validator errors
        return f"{what} schema validation failed: {getattr(e, 'message', e)}"
