"""Controllers: reconcile loops over the coordination bus."""

from .dag import DAGEngine
from .jobs import JOB_KIND, LocalGangExecutor, make_job
from .manager import Clock, ControllerManager, ManualClock, jittered_backoff
from .retry import classify_exit_code, compute_retry_delay, retry_budget_left
from .step_executor import StepExecutor
from .steprun import StepRunController
from .storyrun import StoryRunController

__all__ = [
    "DAGEngine",
    "JOB_KIND",
    "LocalGangExecutor",
    "make_job",
    "Clock",
    "ControllerManager",
    "ManualClock",
    "jittered_backoff",
    "classify_exit_code",
    "compute_retry_delay",
    "retry_budget_left",
    "StepExecutor",
    "StepRunController",
    "StoryRunController",
]
