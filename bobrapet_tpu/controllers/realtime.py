"""Realtime (streaming) StepRun path — control-plane side.

The reference materializes realtime steps as per-run Deployment +
Service + TransportBinding with codec negotiation and handoff
(reference: steprun_controller.go reconcileRunScopedRealtimeStep:2527).
The full streaming data plane lands with the transport layer; this
module keeps the StepRun phase machine honest meanwhile: a realtime step
materializes a Service resource on the bus and derives its phase from
binding + service readiness.
"""

from __future__ import annotations

from typing import Any

from ..api import conditions
from ..api.enums import Phase
from ..api.runs import STEP_RUN_KIND


def reconcile_realtime_step(ctrl, sr, spec, engram_spec, template_spec):
    """Minimal realtime reconcile: materialize the service record and
    report Running once it exists; the transport layer upgrades this to
    full binding negotiation + downstream target wiring."""
    from .streaming import ensure_realtime_topology

    return ensure_realtime_topology(ctrl, sr, spec, engram_spec, template_spec)


def set_realtime_pending(ctrl, sr, message: str):
    def patch(status: dict[str, Any]) -> None:
        status["phase"] = str(Phase.PENDING)
        status["message"] = message
        conds = status.setdefault("conditions", [])
        conditions.set_condition(
            conds,
            conditions.TRANSPORT_READY,
            False,
            conditions.Reason.AWAITING_TRANSPORT,
            message,
            now=ctrl.clock.now(),
        )

    ctrl.store.patch_status(STEP_RUN_KIND, sr.meta.namespace, sr.meta.name, patch)
    return None
