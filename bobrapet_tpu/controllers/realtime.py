"""Realtime StepRun path — delegation shim.

The full realtime control plane lives in :mod:`.streaming`
(reference: steprun_controller.go reconcileRunScopedRealtimeStep:2527);
this module keeps the StepRunController-facing entry point stable.
"""

from __future__ import annotations

from .streaming import reconcile_realtime_step

__all__ = ["reconcile_realtime_step"]
