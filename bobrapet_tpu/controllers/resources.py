"""Definition-resource controllers: Story, Engram, catalog templates.

Capability parity with the reference's definition-side reconcilers
(reference: internal/controller/story_controller.go:247,
internal/controller/engram_controller.go:122,
internal/controller/catalog/{engramtemplate,impulsetemplate}_controller.go):

- **StoryController** — cross-resource validation (step engram refs exist
  + mode compatibility, executeStory targets exist, step transports are
  declared on the story, declared transports resolve), status rollup
  (stepsTotal, transportMode hot/fallback, validationStatus +
  errors/warnings), and token-based idempotent run/trigger counting
  (reference: countStoryTriggersBounded story_controller.go:1212,
  markUsageDirty:119).
- **EngramController** — templateRef validation + mode support, usage
  counters (Stories referencing) and trigger counters (StepRuns), phase.
- **EngramTemplateController / ImpulseTemplateController** — spec
  validation + usage counts
  (reference: internal/controller/catalog/template_helpers.go).

Counting is *token-based and idempotent*: each StoryRun/StepRun counts at
most once per counter family, recorded by an annotation on the counted
child (reference: trigger_annotations.go:48-179); a bounded batch is
consumed per reconcile so a large backlog cannot stall the reconciler.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from ..api import conditions
from ..api.catalog import (
    CLUSTER_NAMESPACE,
    ENGRAM_TEMPLATE_KIND,
    IMPULSE_TEMPLATE_KIND,
    parse_engram_template,
)
from ..api.engram import KIND as ENGRAM_KIND, parse_engram
from ..api.enums import Phase, StepType, ValidationStatus, WorkloadMode
from ..api.impulse import KIND as IMPULSE_KIND
from ..api.runs import STEP_RUN_KIND, STORY_RUN_KIND
from ..api.story import KIND as STORY_KIND, Step, parse_story
from ..api.transport import TRANSPORT_KIND
from ..core.events import EventRecorder
from ..core.store import NotFound, ResourceStore
from ..observability.metrics import metrics

_log = logging.getLogger(__name__)

# annotation families marking a child as already counted
# (reference: trigger_annotations.go:48 — `story`, `impulse`,
# `impulse-success`, `impulse-failed` token families)
ANNO_COUNTED_STORY = "runs.bobrapet.io/counted-story"
ANNO_COUNTED_ENGRAM = "runs.bobrapet.io/counted-engram"
ANNO_COUNTED_IMPULSE = "runs.bobrapet.io/counted-impulse"
ANNO_COUNTED_IMPULSE_OUTCOME = "runs.bobrapet.io/counted-impulse-outcome"

# bounded backfill batch per reconcile
# (reference: countStoryTriggersBounded story_controller.go:1212)
COUNT_BATCH = 50

INDEX_STORY_ENGRAM_REFS = "stepEngramRefs"

#: status/annotation-derived indexes (recomputed on every commit) that
#: keep the usage-counter controllers O(interesting children) instead
#: of O(all children): the r5 scale soak measured the old full-list
#: path at 37 steps/s on a 10k-StepRun population — the N^2 term was
#: deep-copying every child per usage reconcile.
INDEX_STORYRUN_STORY_ACTIVE = "storyRefActive"
INDEX_STORYRUN_UNCOUNTED = "storyRefUncounted"
INDEX_STEPRUN_ENGRAM_ACTIVE = "engramRefActive"
INDEX_STEPRUN_UNCOUNTED = "engramRefUncounted"
INDEX_STORY_EXECUTE_REFS = "executeStoryRefs"
INDEX_STORY_TRANSPORT_REFS = "transportRefs"
INDEX_STORYRUN_STORY = "storyRef"
INDEX_STEPRUN_ENGRAM = "engramRef"
INDEX_ENGRAM_TEMPLATE = "templateRef"


def _bounded_fetch(store: ResourceStore, kind: str, namespace: str,
                   index: tuple[str, str], limit: int) -> list:
    """At most ``limit`` deep-copied objects from an index bucket —
    _consume_tokens consumes COUNT_BATCH per pass, so under a burst of
    10k uncounted children a full list() would deep-copy the whole
    bucket every pass (O(U^2/batch) total)."""
    out = []
    for ns, nm in store.list_keys(kind, namespace=namespace, index=index):
        r = store.try_get(kind, ns, nm)
        if r is not None:
            out.append(r)
            if len(out) >= limit:
                break
    return out


def _consume_tokens(
    store: ResourceStore,
    children,
    annotation: str,
    clock_now: float,
    value_fn=None,
) -> dict[str, int]:
    """Idempotently count un-counted children, annotating each consumed
    one. Returns {bucket: increment}; bucket "" is the total family.
    ``value_fn(child) -> Optional[str]`` selects an outcome bucket (and
    may return None to defer counting, e.g. until a run is terminal)."""
    increments: dict[str, int] = {}
    consumed = 0
    for child in children:
        if consumed >= COUNT_BATCH:
            break
        if annotation in child.meta.annotations:
            continue
        bucket = ""
        if value_fn is not None:
            maybe = value_fn(child)
            if maybe is None:
                continue  # not countable yet (e.g. still running)
            bucket = maybe
        try:
            store.mutate(
                child.kind,
                child.meta.namespace,
                child.meta.name,
                lambda r: r.meta.annotations.__setitem__(annotation, str(clock_now)),
            )
        except NotFound:
            continue
        increments[bucket] = increments.get(bucket, 0) + 1
        consumed += 1
    return increments


class StoryController:
    """(reference: story_controller.go Reconcile:247)"""

    def __init__(self, store: ResourceStore, recorder: Optional[EventRecorder] = None,
                 clock=None):
        self.store = store
        self.recorder = recorder or EventRecorder()
        self.clock = clock

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        story = self.store.try_get(STORY_KIND, namespace, name)
        if story is None or story.meta.deletion_timestamp is not None:
            return None
        spec = parse_story(story)
        errors: list[str] = []
        warnings: list[str] = []

        all_steps = spec.all_steps()
        realtime = spec.effective_pattern.is_realtime
        declared_transports = {t.name or t.transport_ref for t in (spec.transports or [])}

        for step in all_steps:
            self._validate_step(namespace, spec, step, realtime, declared_transports,
                                errors, warnings)

        for t in spec.transports or []:
            tname = t.transport_ref or t.name
            if tname and self.store.try_get(TRANSPORT_KIND, CLUSTER_NAMESPACE, tname) is None:
                errors.append(f"transport {tname!r} not found")

        transport_mode = self._determine_transport_mode(spec, realtime, errors)

        # O(interesting) index reads, not an O(all-runs) deep-copying
        # list: `active` from the status-derived index, token
        # consumption over only the still-uncounted runs
        active = self.store.count(
            STORY_RUN_KIND, namespace=namespace,
            index=(INDEX_STORYRUN_STORY_ACTIVE, name),
        )
        uncounted_runs = _bounded_fetch(
            self.store, STORY_RUN_KIND, namespace,
            (INDEX_STORYRUN_UNCOUNTED, name), COUNT_BATCH,
        )
        now = self.clock.now() if self.clock else 0.0
        inc = _consume_tokens(self.store, uncounted_runs, ANNO_COUNTED_STORY, now)

        status = ValidationStatus.INVALID if errors else ValidationStatus.VALID

        def patch(st: dict[str, Any]) -> None:
            st["stepsTotal"] = len(all_steps)
            st["validationStatus"] = str(status)
            st["validationErrors"] = errors
            st["validationWarnings"] = warnings
            st["transportMode"] = transport_mode
            st["activeRuns"] = active
            st["runsTriggered"] = int(st.get("runsTriggered", 0)) + inc.get("", 0)
            st["observedGeneration"] = story.meta.generation
            conds = st.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.READY, not errors,
                conditions.Reason.VALIDATION_PASSED if not errors
                else conditions.Reason.VALIDATION_FAILED,
                "; ".join(errors) or "story validated", now=now,
            )

        self.store.patch_status(STORY_KIND, namespace, name, patch)
        if errors:
            self.recorder.warning(
                story, conditions.Reason.VALIDATION_FAILED, "; ".join(errors)
            )
        # more un-counted runs than one batch -> come back soon
        uncounted = self.store.count(
            STORY_RUN_KIND, namespace=namespace,
            index=(INDEX_STORYRUN_UNCOUNTED, name),
        )
        return 1.0 if uncounted > COUNT_BATCH else None

    # ------------------------------------------------------------------
    def _validate_step(self, namespace, spec, step: Step, realtime,
                       declared_transports, errors, warnings) -> None:
        if step.ref is not None and step.ref.name:
            engram = self.store.try_get(ENGRAM_KIND, namespace, step.ref.name)
            if engram is None:
                errors.append(f"step {step.name!r}: engram {step.ref.name!r} not found")
            else:
                self._check_mode_compat(step, parse_engram(engram), realtime,
                                        errors, warnings)
        if step.type == StepType.EXECUTE_STORY:
            ref = (step.with_ or {}).get("storyRef") or {}
            target = ref.get("name")
            target_ns = ref.get("namespace") or namespace
            if target and self.store.try_get(STORY_KIND, target_ns, target) is None:
                errors.append(f"step {step.name!r}: executeStory target {target!r} not found")
        if step.transport and step.transport not in declared_transports:
            errors.append(
                f"step {step.name!r}: transport {step.transport!r} not declared on story"
            )

    def _check_mode_compat(self, step: Step, engram_spec, realtime: bool,
                           errors, warnings) -> None:
        """(reference: validateStoryStep story_controller.go:734 — engram
        mode must suit the story pattern)"""
        template = self.store.try_get(
            ENGRAM_TEMPLATE_KIND, CLUSTER_NAMESPACE,
            engram_spec.template_ref.name if engram_spec.template_ref else "",
        )
        mode = engram_spec.mode
        if mode is None and template is not None:
            modes = parse_engram_template(template).supported_modes or []
            mode = modes[0] if modes else None
        if mode is None:
            return
        if realtime and mode == WorkloadMode.JOB:
            warnings.append(
                f"step {step.name!r}: job-mode engram in a realtime story runs batch"
            )
        if not realtime and mode != WorkloadMode.JOB:
            warnings.append(
                f"step {step.name!r}: {mode}-mode engram in a batch story"
            )

    def _determine_transport_mode(self, spec, realtime: bool, errors) -> str:
        """hot when a realtime story has all its declared transports
        resolvable; fallback otherwise
        (reference: determineTransportMode story_controller.go:603)."""
        if not realtime:
            return ""
        if spec.transports and not errors:
            return "hot"
        return "fallback"


class EngramController:
    """(reference: engram_controller.go Reconcile:122)"""

    def __init__(self, store: ResourceStore, recorder: Optional[EventRecorder] = None,
                 clock=None):
        self.store = store
        self.recorder = recorder or EventRecorder()
        self.clock = clock

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        engram = self.store.try_get(ENGRAM_KIND, namespace, name)
        if engram is None or engram.meta.deletion_timestamp is not None:
            return None
        spec = parse_engram(engram)
        errors: list[str] = []
        template_name = spec.template_ref.name if spec.template_ref else ""
        template = self.store.try_get(ENGRAM_TEMPLATE_KIND, CLUSTER_NAMESPACE, template_name)
        if template is None:
            errors.append(f"engram template {template_name!r} not found")
        elif spec.mode is not None:
            tspec = parse_engram_template(template)
            if tspec.supported_modes and not tspec.supports_mode(spec.mode):
                errors.append(
                    f"mode {spec.mode} not supported by template {template_name!r} "
                    f"(supports {[str(m) for m in tspec.supported_modes]})"
                )

        # usage: stories whose steps reference this engram
        # (reference: countEngramUsage engram_controller.go:323) —
        # names/counts from index keys, token consumption over only
        # the uncounted StepRuns (O(interesting), not O(all children))
        story_names = sorted(
            n for _ns, n in self.store.list_keys(
                STORY_KIND, namespace=namespace,
                index=(INDEX_STORY_ENGRAM_REFS, name),
            )
        )
        active = self.store.count(
            STEP_RUN_KIND, namespace=namespace,
            index=(INDEX_STEPRUN_ENGRAM_ACTIVE, name),
        )
        uncounted_srs = _bounded_fetch(
            self.store, STEP_RUN_KIND, namespace,
            (INDEX_STEPRUN_UNCOUNTED, name), COUNT_BATCH,
        )
        now = self.clock.now() if self.clock else 0.0
        inc = _consume_tokens(self.store, uncounted_srs, ANNO_COUNTED_ENGRAM, now)
        if engram.status.get("usageCount") != len(story_names):
            metrics.story_dirty_marks.inc()

        def patch(st: dict[str, Any]) -> None:
            st["phase"] = str(Phase.FAILED if errors else Phase.RUNNING)
            st["usedByStories"] = story_names
            st["usageCount"] = len(story_names)
            st["activeStepRuns"] = active
            st["triggerCount"] = int(st.get("triggerCount", 0)) + inc.get("", 0)
            st["observedGeneration"] = engram.meta.generation
            conds = st.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.TEMPLATE_RESOLVED, template is not None,
                conditions.Reason.TEMPLATE_RESOLVED if template is not None
                else conditions.Reason.TEMPLATE_NOT_FOUND,
                errors[0] if errors else f"template {template_name!r} resolved",
                now=now,
            )
            conditions.set_condition(
                conds, conditions.READY, not errors,
                conditions.Reason.VALIDATION_PASSED if not errors
                else conditions.Reason.VALIDATION_FAILED,
                "; ".join(errors) or "engram ready", now=now,
            )

        self.store.patch_status(ENGRAM_KIND, namespace, name, patch)
        return None


class TemplateController:
    """Shared EngramTemplate/ImpulseTemplate reconcile
    (reference: internal/controller/catalog/template_helpers.go)."""

    def __init__(self, store: ResourceStore, kind: str, user_kind: str,
                 recorder: Optional[EventRecorder] = None, clock=None):
        self.store = store
        self.kind = kind
        self.user_kind = user_kind  # Engram or Impulse
        self.recorder = recorder or EventRecorder()
        self.clock = clock

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        template = self.store.try_get(self.kind, CLUSTER_NAMESPACE, name)
        if template is None or template.meta.deletion_timestamp is not None:
            return None
        errors: list[str] = []
        spec = template.spec
        if not spec.get("image") and not spec.get("entrypoint"):
            errors.append("one of spec.image or spec.entrypoint is required")
        modes = spec.get("supportedModes") or []
        for m in modes:
            try:
                WorkloadMode(m)
            except ValueError:
                errors.append(f"unsupported mode {m!r}")

        users = self.store.list(self.user_kind, index=(INDEX_ENGRAM_TEMPLATE, name))
        now = self.clock.now() if self.clock else 0.0

        def patch(st: dict[str, Any]) -> None:
            st["validationStatus"] = str(
                ValidationStatus.INVALID if errors else ValidationStatus.VALID
            )
            st["validationErrors"] = errors
            st["usageCount"] = len(users)
            st["usedBy"] = sorted(
                f"{u.meta.namespace}/{u.meta.name}" for u in users
            )
            st["observedGeneration"] = template.meta.generation
            conds = st.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.READY, not errors,
                conditions.Reason.VALIDATION_PASSED if not errors
                else conditions.Reason.VALIDATION_FAILED,
                "; ".join(errors) or "template validated", now=now,
            )

        self.store.patch_status(self.kind, CLUSTER_NAMESPACE, name, patch)
        return None


def make_catalog_controllers(store: ResourceStore, recorder=None, clock=None):
    return (
        TemplateController(store, ENGRAM_TEMPLATE_KIND, ENGRAM_KIND, recorder, clock),
        TemplateController(store, IMPULSE_TEMPLATE_KIND, IMPULSE_KIND, recorder, clock),
    )
