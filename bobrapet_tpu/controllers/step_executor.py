"""Step executor: materializes ready steps.

Capability parity with the reference StepExecutor
(reference: internal/controller/runs/step_executor.go — Execute:132
dispatch, executeEngramStep:205, createEngramStepRun:360,
maybeOffloadStepRunInput:662, executeParallelStep:740,
executeStoryStep:1132, executeStopStep:1081, resolveIdempotencyKey:896;
primitive `with` shapes documented in SURVEY §2.2):

- engram steps -> StepRun CRs with deterministic names (create-or-adopt)
  + input offload + idempotency key template + **TPU slice grant** from
  the placement stage (TPU-native addition, SURVEY §7)
- `condition` -> Succeeded immediately (branching happens via `if`)
- `sleep`/`wait`/`gate` -> in-status timer state machines
- `stop` -> story terminal request
- `parallel` -> child StepRuns per branch (gang fan-out; branches place
  onto disjoint ICI sub-meshes of one pool)
- `executeStory` -> child StoryRun (sub-story)
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from ..api.enums import Phase, StepType, StopMode
from ..api.runs import STEP_RUN_KIND, STORY_RUN_KIND, StepState
from ..api.story import Step, StorySpec
from ..core.object import Resource, new_resource
from ..core.store import AlreadyExists, ResourceStore
from ..observability import tracing
from ..observability.analytics import LEDGER, UTILIZATION
from ..observability.metrics import metrics
from ..observability.timeline import FLIGHT
from ..parallel.placement import NoCapacity, SlicePlacer
from ..storage.manager import StorageManager
from ..templating.engine import Evaluator, TemplateError
from ..utils.duration import parse_duration
from ..utils.naming import branch_steprun_name, compose_unique, steprun_name
from .manager import Clock

_log = logging.getLogger(__name__)

#: durable per-step timers parked in StoryRun.status
#: (reference keeps them in the runs.bubustack.io/step-timers annotation,
#: dag.go:64-76; status is this framework's durable home)
TIMERS_KEY = "stepTimers"
#: stop-primitive request recorded for the finalizer
STOP_KEY = "stopRequest"

LABEL_STORY_RUN = "bobrapet.io/story-run"
LABEL_STEP = "bobrapet.io/step"
LABEL_QUEUE = "bobrapet.io/queue"
LABEL_PRIORITY = "bobrapet.io/priority"
LABEL_PARENT_STEP = "bobrapet.io/parent-step"
DEPTH_LABEL = "bobrapet.io/substory-depth"
#: parent trace context carried on the executeStory handoff edge: the
#: child StoryRun (possibly owned by ANOTHER shard) resumes the parent's
#: trace from this annotation, so one story + its sub-stories yield ONE
#: queryable trace across the cross-shard handoff
TRACE_ANNOTATION = "runs.bobrapet.io/traceparent"


def parse_trace_annotation(meta) -> Optional[dict[str, Any]]:
    """The one decoder for :data:`TRACE_ANNOTATION` (the StoryRun
    controller and the shard coordinator both consume it — a format
    change must not be able to diverge the two stitches)."""
    raw = meta.annotations.get(TRACE_ANNOTATION)
    if not raw:
        return None
    import json

    try:
        parsed = json.loads(raw)
    except ValueError:
        return None
    return parsed if isinstance(parsed, dict) else None


class LaunchBlocked(Exception):
    """Step cannot launch yet (e.g. no slice capacity) — stay Pending."""


class StepExecutor:
    def __init__(
        self,
        store: ResourceStore,
        evaluator: Evaluator,
        storage: StorageManager,
        config_manager,
        placer: Optional[SlicePlacer] = None,
        clock: Optional[Clock] = None,
    ):
        self.store = store
        self.evaluator = evaluator
        self.storage = storage
        self.config_manager = config_manager
        self.placer = placer or SlicePlacer()
        self.clock = clock or Clock()

    # ------------------------------------------------------------------
    def execute(
        self,
        run: Resource,
        story: StorySpec,
        step: Step,
        scope: dict[str, Any],
        queue: Optional[str] = None,
    ) -> StepState:
        """Launch one ready step; returns its initial StepState.

        ``run.status`` is mutated in place (timers/stop requests); the DAG
        engine persists it after the iteration loop.
        """
        with tracing.TRACER.start_span(
            "step.execute",
            trace_context=run.status.get("trace"),
            step=step.name,
            type=str(step.type) if step.type else "engram",
            run=run.meta.name,
            namespace=run.meta.namespace,
        ):
            state = self._dispatch(run, story, step, scope, queue)
        FLIGHT.record(
            run.meta.namespace, run.meta.name, "launch",
            message=f"step {step.name} "
                    f"({str(step.type) if step.type else 'engram'}) -> "
                    f"{state.phase}",
            step=step.name, at=self.clock.now(),
        )
        return state

    def _dispatch(
        self,
        run: Resource,
        story: StorySpec,
        step: Step,
        scope: dict[str, Any],
        queue: Optional[str],
    ) -> StepState:
        if step.type is None:
            return self._execute_engram(run, story, step, scope, queue)
        if step.type is StepType.CONDITION:
            # branching is expressed through dependents' `if`; the node
            # itself completes instantly (reference: step_executor.go:168)
            return StepState(phase=Phase.SUCCEEDED, started_at=self.clock.now(),
                             finished_at=self.clock.now())
        if step.type is StepType.SLEEP:
            return self._execute_sleep(run, step)
        if step.type is StepType.WAIT:
            return self._execute_wait(run, step)
        if step.type is StepType.GATE:
            return self._execute_gate(run, step)
        if step.type is StepType.STOP:
            return self._execute_stop(run, step, scope)
        if step.type is StepType.PARALLEL:
            return self._execute_parallel(run, story, step, scope, queue)
        if step.type is StepType.EXECUTE_STORY:
            return self._execute_story(run, step, scope)
        raise ValueError(f"unknown step type {step.type}")

    # ------------------------------------------------------------------
    # engram steps
    # ------------------------------------------------------------------
    def _execute_engram(
        self,
        run: Resource,
        story: StorySpec,
        step: Step,
        scope: dict[str, Any],
        queue: Optional[str],
        name_override: Optional[str] = None,
        parent_step: Optional[str] = None,
        preplaced_grant: Optional[dict[str, Any]] = None,
        preplaced: bool = False,
    ) -> StepState:
        ns = run.meta.namespace
        name = name_override or steprun_name(run.meta.name, step.name)

        # TPU slice placement stage (gang semantics: all-or-nothing).
        # ``preplaced`` means the parent fan-out already ran the batched
        # gang pass and this branch's grant (possibly None) is final.
        slice_grant = preplaced_grant
        if not preplaced and step.tpu is not None:
            # placement decision span: nests under step.execute on this
            # thread, so the trace reads admission -> scheduling ->
            # placement without explicit context plumbing
            with tracing.TRACER.start_span(
                "slice.place", step=step.name, run=run.meta.name,
                namespace=ns,
            ) as sp:
                try:
                    grant = self.placer.place(step.tpu, queue=queue)
                except NoCapacity as e:
                    raise LaunchBlocked(str(e)) from None
                if sp is not None and grant is not None:
                    sp.set_attribute("sliceId", grant.to_dict().get("sliceId"))
            slice_grant = grant.to_dict() if grant is not None else None

        idempotency_key = self._resolve_idempotency_key(run, step, scope)

        spec: dict[str, Any] = {
            "storyRunRef": {"name": run.meta.name},
            "stepId": step.name,
            "engramRef": step.ref.to_dict() if step.ref else {},
            "input": step.with_ or {},
        }
        if idempotency_key:
            spec["idempotencyKey"] = idempotency_key
        if step.execution is not None:
            spec["executionOverrides"] = step.execution.to_dict()
            if step.execution.timeout:
                spec["timeout"] = step.execution.timeout
            if step.execution.retry is not None:
                spec["retry"] = step.execution.retry.to_dict()
        if step.post_execution is not None:
            spec["postExecution"] = step.post_execution.to_dict()
        if slice_grant is not None:
            spec["sliceGrant"] = slice_grant

        labels = {LABEL_STORY_RUN: run.meta.name, LABEL_STEP: step.name}
        if queue:
            labels[LABEL_QUEUE] = queue
        if parent_step:
            labels[LABEL_PARENT_STEP] = parent_step

        sr = new_resource(
            STEP_RUN_KIND, name, ns, spec, labels=labels, owners=[run.owner_ref()]
        )
        # the StepRun controller will hydrate this scope's refs while
        # resolving inputs — start pulling them through the payload
        # tiers now, overlapped with the create + watch dispatch (fire
        # and forget; resolution hits the hydrate LRU, and the fetch
        # leaves the slice-local disk tier warm for later processes)
        self.storage.prefetch(
            scope, [StorageManager.run_prefix(ns, run.meta.name)]
        )
        try:
            self.store.create(sr)
            metrics.child_stepruns_created.inc(
                "parallel-branch" if parent_step else "engram"
            )
        except AlreadyExists:
            # deterministic name -> adopt (drift detection: if the adopted
            # spec diverges, patch it; reference: drift detection/patch).
            # The grant allocated above belongs to nobody (the surviving
            # StepRun carries its own) — return it or the pool leaks.
            if slice_grant is not None:
                self.placer.release(slice_grant)
            existing = self.store.get(STEP_RUN_KIND, ns, name)
            # the surviving StepRun's grant is the live one — anything
            # reported below must name it, not the released allocation
            slice_grant = existing.spec.get("sliceGrant")
            if existing.spec.get("input") != spec["input"] and not (
                existing.status.get("phase")
                and Phase(existing.status["phase"]).is_terminal
            ):
                # keep the adopted StepRun's own (still-live) slice grant
                drift = {k: v for k, v in spec.items() if k != "sliceGrant"}

                def sync_spec(r: Resource) -> None:
                    r.spec.update(drift)

                self.store.mutate(STEP_RUN_KIND, ns, name, sync_spec)
        if slice_grant is not None:
            # surfaced into stepStates so `kubectl get storyrun -o yaml`
            # answers "which sub-mesh is this step on" without chasing
            # the StepRun; the fleet redrive path replaces the grant and
            # the merge keeps this reason until the step turns terminal
            from ..api.conditions import Reason

            # chip-time ledger: the clock starts the moment the grant is
            # committed to a StepRun (idempotent for the adopt path —
            # the surviving grant keeps its original open time); tenant
            # = the run's tenant label or its namespace
            now = self.clock.now()
            LEDGER.open_grant(
                slice_grant, now,
                tenant=run.meta.labels.get("bobrapet.io/tenant") or ns,
            )
            UTILIZATION.sample(self.placer, now)
            FLIGHT.record(
                ns, run.meta.name, "placement",
                message=f"step {step.name}: slice "
                        f"{slice_grant.get('sliceId')} on pool "
                        f"{slice_grant.get('pool')}",
                step=step.name, sliceId=slice_grant.get("sliceId"),
                pool=slice_grant.get("pool"), at=now,
            )
            return StepState(
                phase=Phase.PENDING,
                started_at=self.clock.now(),
                reason=Reason.SLICE_PLACED,
                message=(
                    f"slice {slice_grant.get('sliceId')} "
                    f"({slice_grant.get('topology')}) on pool "
                    f"{slice_grant.get('pool')}"
                ),
            )
        return StepState(phase=Phase.PENDING, started_at=self.clock.now())

    def _resolve_idempotency_key(self, run, step, scope) -> Optional[str]:
        if not step.idempotency_key_template:
            # default identity ns/run/step (reference:
            # identity/steprun_idempotency.go:14)
            return f"{run.meta.namespace}/{run.meta.name}/step/{step.name}"
        try:
            v = self.evaluator.evaluate_string(step.idempotency_key_template, scope)
            return str(v)
        except TemplateError as e:
            _log.warning("idempotency key template for %s failed: %s", step.name, e)
            return None

    # ------------------------------------------------------------------
    # primitives (exact `with` shapes per SURVEY §2.2)
    # ------------------------------------------------------------------
    def _execute_sleep(self, run: Resource, step: Step) -> StepState:
        """sleep: {duration} (reference: dag.go:1549)"""
        w = step.with_ or {}
        duration = parse_duration(w.get("duration"), default=0.0) or 0.0
        due = self.clock.now() + duration
        run.status.setdefault(TIMERS_KEY, {})[step.name] = {
            "kind": "sleep",
            "due": due,
        }
        return StepState(phase=Phase.RUNNING, started_at=self.clock.now())

    def _execute_wait(self, run: Resource, step: Step) -> StepState:
        """wait: {until (required), timeout, pollInterval, onTimeout: fail|skip}
        (reference: dag.go:1569, normalizeOnTimeout:1643)"""
        w = step.with_ or {}
        cfg = self.config_manager.config
        timeout = parse_duration(w.get("timeout"), default=cfg.timeouts.external_data_seconds)
        poll = parse_duration(w.get("pollInterval"), default=5.0) or 5.0
        run.status.setdefault(TIMERS_KEY, {})[step.name] = {
            "kind": "wait",
            "until": w.get("until", ""),
            "deadline": self.clock.now() + (timeout or 0.0),
            "pollInterval": poll,
            "nextPoll": self.clock.now(),
            "onTimeout": _normalize_on_timeout(w.get("onTimeout")),
        }
        return StepState(phase=Phase.RUNNING, started_at=self.clock.now())

    def _execute_gate(self, run: Resource, step: Step) -> StepState:
        """gate: {timeout, pollInterval, onTimeout} — decision arrives via a
        status.gates[step] patch (reference: dag.go:1608,
        storyrun_types.go:274)"""
        w = step.with_ or {}
        cfg = self.config_manager.config
        timeout = parse_duration(w.get("timeout"), default=cfg.timeouts.approval_seconds)
        poll = parse_duration(w.get("pollInterval"), default=10.0) or 10.0
        run.status.setdefault(TIMERS_KEY, {})[step.name] = {
            "kind": "gate",
            "deadline": self.clock.now() + (timeout or 0.0),
            "pollInterval": poll,
            "onTimeout": _normalize_on_timeout(w.get("onTimeout")),
        }
        return StepState(phase=Phase.PAUSED, started_at=self.clock.now(),
                         reason="AwaitingApproval")

    def _execute_stop(self, run: Resource, step: Step, scope) -> StepState:
        """stop: {phase (default Succeeded), message}
        (reference: step_executor.go:1084-1101)"""
        w = step.with_ or {}
        raw_phase = w.get("phase", "Succeeded")
        message = w.get("message", "")
        if isinstance(message, str) and "{{" in message:
            try:
                message = str(self.evaluator.evaluate_string(message, scope))
            except TemplateError:
                pass
        try:
            phase = StopMode(str(raw_phase).lower()).terminal_phase
        except ValueError:
            try:
                phase = Phase(raw_phase)
            except ValueError:
                phase = Phase.SUCCEEDED
        if not phase.is_terminal:
            phase = Phase.SUCCEEDED
        run.status[STOP_KEY] = {"phase": str(phase), "message": message, "step": step.name}
        return StepState(
            phase=Phase.SUCCEEDED,
            started_at=self.clock.now(),
            finished_at=self.clock.now(),
            message=message or None,
        )

    def _execute_parallel(
        self, run: Resource, story: StorySpec, step: Step, scope, queue
    ) -> StepState:
        """parallel: {steps: []Step} — full inline Steps per branch; parent
        completes when ALL children are terminal, fails if any
        non-allowFailure branch failed (no completionPolicy — SURVEY §2.2
        documents the reference implements none despite enum comments)
        (reference: step_executor.go:741-747, dag.go:1112-1200).

        The ``replicas``/``step`` spelling ({replicas: N, step: {...},
        pools: [...]}) fans ONE logical step out as N gang members and
        places them as one SPANNING grant across the named pools (or
        ``scheduling.span-pools``): per-pool ICI-contiguous
        super-blocks, all-or-nothing across pools, every member's env
        carrying replica index + span process layout so the engrams
        initialize jax.distributed as one job over a dcn x ICI mesh —
        the multi-slice DCN-data-parallel shape."""
        from ..api.story import expand_parallel_branches

        w = step.with_ or {}
        branches = expand_parallel_branches(step)
        replicated = bool(w.get("replicas")) and not w.get("steps")
        for branch in branches:
            if branch.type is not None:
                # primitive branches run as instant/timer states inside the
                # parent's timer store, keyed parent.branch
                raise ValueError(
                    f"parallel branch {branch.name!r}: primitive branches are "
                    "not supported; use engram steps"
                )
        span_pools: Optional[list[str]] = None
        spill = True
        if replicated:
            sched = self.config_manager.config.scheduling
            pools = w.get("pools") or sched.span_pools
            if not pools:
                # no pools named anywhere: span over the queue's own
                # pool. The replicas spelling ALWAYS means one
                # data-parallel job — silently launching N independent
                # full-workload copies (no span env, N flat meshes)
                # would burn N slices for zero extra throughput
                pools = [
                    queue if queue and self.placer.pool(queue) else "local"
                ]
            span_pools = [str(p) for p in pools]
            spill = bool(w.get("spill", sched.span_spill))
        # batched gang placement: every TPU branch gets its slice in ONE
        # pass per pool (siblings packed ICI-adjacent when a super-block
        # fits), and capacity shortfall surfaces BEFORE any branch
        # StepRun exists — the per-branch path could strand a partial
        # gang when a later sibling hit NoCapacity
        with tracing.TRACER.start_span(
            "slice.place_group", step=step.name, run=run.meta.name,
            namespace=run.meta.namespace, branches=len(branches),
            span_pools=",".join(span_pools) if span_pools else None,
        ):
            try:
                grants = self.placer.place_group(
                    [(b.name, b.tpu) for b in branches], queue=queue,
                    pools=span_pools, spill=spill,
                )
            except NoCapacity as e:
                raise LaunchBlocked(str(e)) from None
        if any(g is not None for g in grants.values()):
            placed = [g for g in grants.values() if g is not None]
            span = placed[0].span if placed else None
            FLIGHT.record(
                run.meta.namespace, run.meta.name, "placement",
                message=f"gang {step.name}: {len(placed)} "
                        f"branch slice(s) granted in one pass"
                        + (f" spanning pools "
                           f"{sorted({g.pool for g in placed})} "
                           f"({span['id']})" if span else ""),
                step=step.name, at=self.clock.now(),
            )
        children = []
        try:
            for branch in branches:
                child_name = branch_steprun_name(
                    run.meta.name, step.name, branch.name
                )
                grant = grants.pop(branch.name, None)
                try:
                    self._execute_engram(
                        run, story, branch, scope, queue,
                        name_override=child_name, parent_step=step.name,
                        preplaced_grant=(
                            grant.to_dict() if grant is not None else None
                        ),
                        preplaced=True,
                    )
                except BaseException:
                    if grant is not None:
                        self.placer.release(grant.to_dict())
                    raise
                children.append({"name": branch.name, "stepRun": child_name,
                                 "allowFailure": bool(branch.allow_failure)})
        except BaseException:
            # a failed branch launch must hand the still-unconsumed
            # sibling grants back or the gang leaks its blocks
            for grant in grants.values():
                if grant is not None:
                    self.placer.release(grant.to_dict())
            raise
        run.status.setdefault(TIMERS_KEY, {})[step.name] = {
            "kind": "parallel",
            "children": children,
        }
        return StepState(phase=Phase.RUNNING, started_at=self.clock.now())

    def _execute_story(self, run: Resource, step: Step, scope) -> StepState:
        """executeStory: {storyRef (required), waitForCompletion (default
        true), with} (reference: step_executor.go:1188-1215,
        ensureSubStoryRun:1407)"""
        w = step.with_ or {}
        story_ref = w.get("storyRef") or {}
        story_name = story_ref.get("name", "") if isinstance(story_ref, dict) else str(story_ref)
        sub_inputs = w.get("with") or {}
        try:
            sub_inputs = self.evaluator.evaluate_value(sub_inputs, scope)
        except TemplateError as e:
            return StepState(
                phase=Phase.FAILED,
                started_at=self.clock.now(),
                finished_at=self.clock.now(),
                message=f"executeStory input evaluation failed: {e}",
            )
        # recursion guard: sub-story depth is inherited through a label and
        # capped at the resolved max recursion depth (reference:
        # executeStory reference-cycle validation + MaxRecursionDepth)
        depth = int(run.meta.labels.get(DEPTH_LABEL, "0")) + 1
        max_depth = self.config_manager.config.engram.max_recursion_depth
        if depth > max_depth:
            return StepState(
                phase=Phase.FAILED,
                started_at=self.clock.now(),
                finished_at=self.clock.now(),
                reason="RecursionDepthExceeded",
                message=f"executeStory nesting depth {depth} exceeds limit {max_depth}",
            )
        wait = w.get("waitForCompletion", True)
        child_name = compose_unique(run.meta.name, step.name, "sub")
        # the handoff edge carries the parent's trace context: the child
        # run (which may hash to ANOTHER shard) resumes the same traceId
        # instead of minting a fresh one, so the executeStory hop — and
        # the cross-shard handoff it may become — stays one trace
        annotations = {}
        parent_trace = run.status.get("trace")
        if parent_trace:
            import json as _json

            annotations[TRACE_ANNOTATION] = _json.dumps(parent_trace)
        child = new_resource(
            STORY_RUN_KIND,
            child_name,
            run.meta.namespace,
            spec={"storyRef": {"name": story_name}, "inputs": sub_inputs},
            labels={
                LABEL_STORY_RUN: run.meta.name,
                LABEL_PARENT_STEP: step.name,
                DEPTH_LABEL: str(depth),
            },
            annotations=annotations,
            owners=[run.owner_ref()],
        )
        try:
            self.store.create(child)
            metrics.child_stepruns_created.inc("sub-story")
            FLIGHT.record(
                run.meta.namespace, child_name, "handoff",
                message=f"sub-story of {run.meta.name} (step {step.name})",
                trace_id=(parent_trace or {}).get("traceId"),
                span_id=(parent_trace or {}).get("spanId"),
                parent=run.meta.name, step=step.name,
                at=self.clock.now(),
            )
        except AlreadyExists:
            pass
        if not wait:
            return StepState(
                phase=Phase.SUCCEEDED,
                started_at=self.clock.now(),
                finished_at=self.clock.now(),
                output={"storyRun": child_name},
            )
        run.status.setdefault(TIMERS_KEY, {})[step.name] = {
            "kind": "subStory",
            "storyRun": child_name,
        }
        return StepState(phase=Phase.RUNNING, started_at=self.clock.now())


def _normalize_on_timeout(value) -> str:
    """(reference: normalizeOnTimeout dag.go:1643)"""
    v = str(value or "fail").lower()
    return v if v in ("fail", "skip") else "fail"
