"""Transport controller: validation + capability aggregation.

Capability parity with the reference's Transport reconciler
(reference: internal/controller/transport_controller.go — Reconcile:68,
collectAvailableCapabilities:182, heartbeatTimeout:345): validate the
Transport spec (driver, codec lists, MIME types, ICI mesh descriptor),
aggregate the negotiated capabilities of its live TransportBindings
(heartbeat staleness excludes dead connectors), and maintain usage
(stories declaring it) and binding state counters.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from ..api import conditions
from ..api.catalog import CLUSTER_NAMESPACE
from ..api.enums import ValidationStatus
from ..api.story import KIND as STORY_KIND
from ..api.transport import (
    TRANSPORT_BINDING_KIND,
    TRANSPORT_KIND,
    parse_transport,
)
from ..core.events import EventRecorder
from ..core.store import ResourceStore
from ..observability.metrics import metrics
from ..transport import aggregate_bindings, validate_transport_spec
from ..transport.capabilities import DEFAULT_HEARTBEAT_TIMEOUT
from .manager import Clock

_log = logging.getLogger(__name__)

INDEX_BINDING_TRANSPORT = "transportRef"
INDEX_STORY_TRANSPORT_REFS = "transportRefs"


class TransportController:
    """(reference: transport_controller.go Reconcile:68)"""

    def __init__(
        self,
        store: ResourceStore,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
        heartbeat_timeout: float = DEFAULT_HEARTBEAT_TIMEOUT,
    ):
        self.store = store
        self.recorder = recorder or EventRecorder()
        self.clock = clock or Clock()
        self.heartbeat_timeout = heartbeat_timeout

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        transport = self.store.try_get(TRANSPORT_KIND, CLUSTER_NAMESPACE, name)
        if transport is None or transport.meta.deletion_timestamp is not None:
            return None
        spec = parse_transport(transport)
        errors = validate_transport_spec(spec)
        now = self.clock.now()

        bindings = self.store.list(
            TRANSPORT_BINDING_KIND, index=(INDEX_BINDING_TRANSPORT, name)
        )
        caps = aggregate_bindings(bindings, now, self.heartbeat_timeout)
        stories = self.store.list(
            STORY_KIND, index=(INDEX_STORY_TRANSPORT_REFS, name)
        )

        metrics.bindings_by_state.set(caps["liveBindings"], "ready")
        metrics.bindings_by_state.set(caps["pendingBindings"], "pending")
        metrics.bindings_by_state.set(caps["failedBindings"], "failed")

        def patch(st: dict[str, Any]) -> None:
            st["validationStatus"] = str(
                ValidationStatus.INVALID if errors else ValidationStatus.VALID
            )
            st["validationErrors"] = errors
            st["capabilities"] = {
                k: caps[k] for k in ("audio", "video", "binary", "meshes")
            }
            st["liveBindings"] = caps["liveBindings"]
            st["staleBindings"] = caps["staleBindings"]
            st["pendingBindings"] = caps["pendingBindings"]
            st["failedBindings"] = caps["failedBindings"]
            st["usedByStories"] = sorted(
                f"{s.meta.namespace}/{s.meta.name}" for s in stories
            )
            st["usageCount"] = len(stories)
            st["observedGeneration"] = transport.meta.generation
            conds = st.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.READY, not errors,
                conditions.Reason.VALIDATION_PASSED if not errors
                else conditions.Reason.VALIDATION_FAILED,
                "; ".join(errors) or "transport validated", now=now,
            )

        self.store.patch_status(TRANSPORT_KIND, CLUSTER_NAMESPACE, name, patch)
        if errors:
            self.recorder.warning(
                transport, conditions.Reason.VALIDATION_FAILED, "; ".join(errors)
            )
        # live bindings can go stale without any event: requeue while
        # anything is live (reference: heartbeat staleness sweep);
        # an infinite timeout (no connectors, local runtime) never sweeps
        import math

        if caps["liveBindings"] and math.isfinite(self.heartbeat_timeout):
            return self.heartbeat_timeout
        return None
