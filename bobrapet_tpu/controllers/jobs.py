"""Job kind + the local gang executor (this framework's "kubelet").

The reference materializes batch steps as Kubernetes Jobs executed by
kubelet (reference: steprun_controller.go buildJobSpec:1784; Job→pod→
container). Here a **Job resource on the bus** carries the same facts
(entrypoint/image, env contract, gang size, timeout) and the
:class:`LocalGangExecutor` plays kubelet: it watches Jobs, runs one
"host process" per gang member with per-host env
(completion-index -> TPU_WORKER_ID, SURVEY §2.6), and patches Job status
with the classified exit outcome. On GKE the same Job spec maps onto a
JobSet-style multi-host TPU Job; the control plane above is identical.
"""

from __future__ import annotations

import logging
import threading
import traceback
import uuid
from typing import Any, Optional

from ..api.enums import Phase
from ..core.object import Resource, new_resource
from ..core.store import ADDED, DELETED, MODIFIED, ResourceStore, WatchEvent
from ..observability.metrics import metrics
from ..sdk import contract
from ..sdk.context import EngramContext, EngramExit, resolve_entrypoint
from .manager import Clock

_log = logging.getLogger(__name__)

JOB_KIND = "Job"


def make_job(
    name: str,
    namespace: str,
    step_run_name: str,
    entrypoint: str,
    env: dict[str, str],
    hosts: int = 1,
    timeout_seconds: Optional[float] = None,
    image: Optional[str] = None,
    slice_grant: Optional[dict[str, Any]] = None,
    owners=None,
    labels=None,
) -> Resource:
    spec: dict[str, Any] = {
        "stepRunRef": {"name": step_run_name},
        "entrypoint": entrypoint,
        "env": env,
        "hosts": hosts,
    }
    if timeout_seconds is not None:
        spec["timeoutSeconds"] = timeout_seconds
    if image:
        spec["image"] = image
    if slice_grant:
        spec["sliceGrant"] = slice_grant
    return new_resource(JOB_KIND, name, namespace, spec, labels=labels, owners=owners)


class LocalGangExecutor:
    """Runs Job resources in-process.

    Modes:
    - ``sync`` (default; deterministic tests): hosts run sequentially on
      the watcher thread the moment the Job is committed. Timeouts are
      cooperative (ctx.check_deadline()).
    - ``threaded`` (live): one thread per host, join with timeout; a
      host that outlives the deadline is canceled and recorded as
      EXIT_TIMEOUT (kubelet's activeDeadlineSeconds role).
    """

    def __init__(
        self,
        store: ResourceStore,
        storage=None,
        clock: Optional[Clock] = None,
        mode: str = "sync",
        injector=None,
        config_manager=None,
    ):
        self.store = store
        self.storage = storage
        self.clock = clock or Clock()
        self.mode = mode
        #: fault injection (controllers/workload_sim.PreemptionInjector):
        #: plays the GKE spot reclaimer for chaos testing — picks gang
        #: hosts to kill mid-step and stamps the preemption notice
        self.injector = injector
        self.config_manager = config_manager
        # collision-free executor identity for claim arbitration (a
        # truncated id(self) can collide across instances/processes)
        self.executor_id = uuid.uuid4().hex
        self._cancels: dict[tuple[str, str], threading.Event] = {}
        self._lock = threading.Lock()
        store.watch(self._on_event, kinds=[JOB_KIND])

    # -- cancellation (graceful cancel path reaches running jobs) ---------

    def cancel(self, namespace: str, name: str) -> None:
        with self._lock:
            ev = self._cancels.get((namespace, name))
        if ev is not None:
            ev.set()

    # -- watch -------------------------------------------------------------

    def _on_event(self, ev: WatchEvent) -> None:
        job = ev.resource
        if ev.type == DELETED or job.meta.deletion_timestamp is not None:
            # kubelet role: a deleted Job kills its still-running gang
            # (graceful-cancel tears the Job down; threaded hosts must
            # observe the cancel event, not just leak as daemon threads)
            self.cancel(job.meta.namespace, job.meta.name)
            return
        if ev.type not in (ADDED, MODIFIED):
            return
        if job.status.get("phase") in (None, "", str(Phase.PENDING)):
            self._start(job)

    def _start(self, job: Resource) -> None:
        # claim the job (Pending -> Running); losing the claim means
        # another executor instance took it
        try:
            claimed = self.store.mutate(
                JOB_KIND,
                job.meta.namespace,
                job.meta.name,
                self._claim,
                status_only=True,
            )
        except Exception:  # noqa: BLE001
            return
        if claimed.status.get("executor") != self.executor_id:
            return
        # register the cancel event BEFORE any thread runs: a DELETED
        # watch event landing between spawn and the gang thread's first
        # instruction must still find something to set
        ns, name = job.meta.namespace, job.meta.name
        cancel = threading.Event()
        with self._lock:
            self._cancels[(ns, name)] = cancel
        if self.store.try_get_view(JOB_KIND, ns, name) is None:
            cancel.set()  # deleted before we registered — don't run blind
        if self.mode == "threaded":
            t = threading.Thread(
                target=self._run_gang, args=(claimed, cancel), daemon=True,
                name=f"gang-{job.meta.name}",
            )
            t.start()
        else:
            self._run_gang(claimed, cancel)

    def _claim(self, r: Resource) -> None:
        if r.status.get("phase") in (None, "", str(Phase.PENDING)):
            r.status["phase"] = str(Phase.RUNNING)
            r.status["startedAt"] = self.clock.now()
            r.status["executor"] = self.executor_id

    # -- gang execution ----------------------------------------------------

    def _run_gang(self, job: Resource, cancel: threading.Event) -> None:
        ns, name = job.meta.namespace, job.meta.name
        spec = job.spec
        hosts = int(spec.get("hosts") or 1)
        entrypoint = spec.get("entrypoint") or ""
        timeout = spec.get("timeoutSeconds")

        host_results: list[dict[str, Any]] = [{} for _ in range(hosts)]
        # chaos: the injector may pick one host of this gang to preempt
        # (cooperative SIGTERM after N deadline polls — the local analog
        # of a GKE spot reclaim landing mid-step)
        plan = self.injector.plan(job) if self.injector is not None else None
        fuse = _PreemptionFuse(cancel, plan["afterPolls"]) if plan else None
        fail_fast = (
            self.config_manager.config.fleet.fail_fast
            if self.config_manager is not None
            else True
        )

        def run_host(host_id: int) -> None:
            env = contract.host_env(dict(spec.get("env") or {}), host_id)
            if timeout is not None:
                env[contract.ENV_STEP_TIMEOUT_SECONDS] = str(timeout)
            ctx = EngramContext(
                env,
                store=self.store,
                storage=self.storage,
                clock=self.clock,
                cancel_event=fuse if plan and host_id == plan["host"] else cancel,
            )
            try:
                fn = resolve_entrypoint(entrypoint)
            except Exception as e:  # noqa: BLE001 - bad entrypoint = bad image
                host_results[host_id] = {
                    "hostId": host_id,
                    "exitCode": contract.EXIT_CONFIG_TERMINAL_MAX,
                    "message": f"entrypoint resolution failed: {e}",
                }
                return
            try:
                # the SDK hop of the run trace: parented on the env
                # contract's BOBRA_TRACEPARENT (the StepRun's persisted
                # context), stitching controller -> worker across what
                # is a process boundary in production
                with ctx.start_span("sdk.step", host=host_id):
                    result = fn(ctx)
                if result is not None and host_id == 0:
                    ctx.output(result)
                host_results[host_id] = {"hostId": host_id, "exitCode": 0}
            except EngramExit as e:
                host_results[host_id] = {
                    "hostId": host_id,
                    "exitCode": e.code,
                    "message": str(e),
                }
                # gang fail-fast: one host dying of a signal kills the
                # whole gang now instead of the survivors burning the
                # step timeout on dead collectives
                if fail_fast and e.code in (
                    contract.EXIT_SIGKILL, contract.EXIT_SIGTERM
                ):
                    cancel.set()
            except Exception as e:  # noqa: BLE001 - user code failure
                host_results[host_id] = {
                    "hostId": host_id,
                    "exitCode": 1,
                    "message": f"{type(e).__name__}: {e}",
                    "traceback": traceback.format_exc(limit=10),
                }

        try:
            if self.mode == "threaded" and hosts > 1:
                threads = [
                    threading.Thread(target=run_host, args=(i,), daemon=True)
                    for i in range(hosts)
                ]
                for t in threads:
                    t.start()
                deadline = None if timeout is None else self.clock.now() + float(timeout)
                for i, t in enumerate(threads):
                    remain = None if deadline is None else max(0.0, deadline - self.clock.now())
                    t.join(remain)
                    if t.is_alive():
                        cancel.set()
                        host_results[i] = {
                            "hostId": i,
                            "exitCode": contract.EXIT_TIMEOUT,
                            "message": "host deadline exceeded",
                        }
            elif self.mode == "threaded":
                t = threading.Thread(target=run_host, args=(0,), daemon=True)
                t.start()
                t.join(None if timeout is None else float(timeout))
                if t.is_alive():
                    cancel.set()
                    host_results[0] = {
                        "hostId": 0,
                        "exitCode": contract.EXIT_TIMEOUT,
                        "message": "host deadline exceeded",
                    }
            else:
                for i in range(hosts):
                    run_host(i)
        finally:
            with self._lock:
                self._cancels.pop((ns, name), None)

        # gang outcome: every host must succeed (all-or-nothing
        # semantics). A non-signal failure outranks signal deaths in the
        # aggregate: fail-fast SIGTERMs the survivors of any host crash,
        # and a genuine application error must keep its terminal
        # classification whatever the host ordering (and whether or not
        # a preemption was injected in the same attempt).
        exit_code = 0
        message = ""
        for r in host_results:
            code = int(r.get("exitCode", -1))
            if code == 0:
                continue
            signal_death = code in (contract.EXIT_SIGKILL, contract.EXIT_SIGTERM)
            if exit_code == 0 or (
                not signal_death
                and exit_code in (contract.EXIT_SIGKILL, contract.EXIT_SIGTERM)
            ):
                exit_code = code
                message = r.get("message", "")
        finished = self.clock.now()
        outcome = "success" if exit_code == 0 else "failure"
        metrics.job_executions.inc(outcome)
        started_at = job.status.get("startedAt")
        if started_at is not None:
            metrics.job_execution_duration.observe(finished - started_at, outcome)

        # the notice requires the gang's outcome to BE the victim's
        # signal death: a genuine application error on another host must
        # keep its terminal classification even when an injection fired
        # in the same attempt
        preempted = bool(
            fuse is not None
            and fuse.fired
            and exit_code in (contract.EXIT_SIGKILL, contract.EXIT_SIGTERM)
        )

        def finish(status: dict[str, Any]) -> None:
            status["phase"] = str(Phase.SUCCEEDED if exit_code == 0 else Phase.FAILED)
            status["exitCode"] = exit_code
            status["hostStatuses"] = host_results
            status["finishedAt"] = finished
            if message:
                status["message"] = message
            if preempted:
                # the node-condition half of the preemption notice: the
                # fleet watcher + exit classifier key off this marker
                status["preempted"] = True
                status["preemptedHost"] = plan["host"]

        try:
            self.store.patch_status(JOB_KIND, ns, name, finish)
        except Exception:  # noqa: BLE001 - job may have been deleted mid-run
            _log.warning("job %s/%s vanished before completion", ns, name)


class _PreemptionFuse:
    """Event-shaped trigger: reads as set after N ``is_set`` polls.

    Handed to the victim host's EngramContext as its cancel event, it
    turns the next cooperative deadline check after the fuse burns down
    into a SIGTERM — preemption lands *between* instructions, exactly
    like a real reclaim, without a second thread."""

    def __init__(self, inner: threading.Event, after_polls: int):
        self._inner = inner
        self._after = max(1, int(after_polls))
        self._polls = 0
        self.fired = False

    def is_set(self) -> bool:
        if self._inner.is_set():
            return True
        self._polls += 1
        if self._polls > self._after:
            self.fired = True
            return True
        return False

    def set(self) -> None:
        self._inner.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._inner.wait(timeout)
