"""Per-StoryRun RBAC: runner identity + sanitized grants.

Capability parity with the reference's run RBAC manager
(reference: internal/controller/runs/rbac.go — Reconcile:95,
collectStoryRBACRules:282, sanitizeStoryRBACRules:652,
isSafeStoryRBACRule:714): every StoryRun gets its own ServiceAccount +
Role + RoleBinding so engram pods act under a run-scoped identity, not a
shared one. Rules requested by templates/story policy pass a safety
allowlist (no wildcards, only namespaced kinds a worker legitimately
touches); storage provider annotations (IRSA / GKE workload identity)
land on the ServiceAccount so offload credentials follow the run.
"""

from __future__ import annotations

import hashlib
import json
import logging
from typing import Any

from ..api.catalog import (
    CLUSTER_NAMESPACE,
    ENGRAM_TEMPLATE_KIND,
    parse_engram_template,
)
from ..api.engram import KIND as ENGRAM_KIND, parse_engram
from ..api.story import StorySpec
from ..core.object import Resource, new_resource
from ..core.store import AlreadyExists, ResourceStore
from ..observability.metrics import metrics

_log = logging.getLogger(__name__)

SERVICE_ACCOUNT_KIND = "ServiceAccount"
ROLE_KIND = "Role"
ROLE_BINDING_KIND = "RoleBinding"

# resources a run-scoped worker may legitimately touch
# (reference: isSafeStoryRBACRule rbac.go:714 — no wildcards, bounded
# resource/verb vocabulary)
SAFE_RESOURCES = {
    "configmaps", "secrets", "pods", "pods/log", "services",
    "stepruns", "storyruns", "effectclaims", "storytriggers",
}
SAFE_VERBS = {"get", "list", "watch", "create", "update", "patch"}


def runner_sa_name(run_name: str) -> str:
    """(reference: pkg/runs/identity/engram_runner.go:12)"""
    return f"{run_name}-runner"


def sanitize_rules(rules: list[dict[str, Any]]) -> tuple[list[dict[str, Any]], list[str]]:
    """Drop unsafe rules; return (kept, rejection_reasons)
    (reference: sanitizeStoryRBACRules rbac.go:652)."""
    kept: list[dict[str, Any]] = []
    rejected: list[str] = []
    for rule in rules:
        resources = [str(r).lower() for r in rule.get("resources") or []]
        verbs = [str(v).lower() for v in rule.get("verbs") or []]
        groups = rule.get("apiGroups")
        if not resources or not verbs:
            rejected.append(f"rule {rule!r}: resources and verbs required")
            continue
        if "*" in resources or "*" in verbs or (groups and "*" in groups):
            rejected.append(f"rule {rule!r}: wildcards are not allowed")
            continue
        bad_res = [r for r in resources if r not in SAFE_RESOURCES]
        if bad_res:
            rejected.append(f"rule {rule!r}: resources {bad_res} outside allowlist")
            continue
        bad_verbs = [v for v in verbs if v not in SAFE_VERBS]
        if bad_verbs:
            rejected.append(f"rule {rule!r}: verbs {bad_verbs} outside allowlist")
            continue
        kept.append({"resources": resources, "verbs": verbs,
                     **({"apiGroups": groups} if groups else {})})
    return kept, rejected


class RunRBACManager:
    """(reference: rbac.go Reconcile:95)"""

    def __init__(self, store: ResourceStore):
        self.store = store

    # ------------------------------------------------------------------
    def ensure(self, run: Resource, story_spec: StorySpec) -> dict[str, Any]:
        """Materialize SA + Role + RoleBinding for one run. Returns a
        summary {serviceAccount, rules, rejectedRules}."""
        ns = run.meta.namespace
        sa_name = runner_sa_name(run.meta.name)
        rules = self._collect_rules(ns, story_spec)
        kept, rejected = sanitize_rules(rules)
        annotations = self._storage_annotations(story_spec)

        self._ensure_owned(run, new_resource(
            SERVICE_ACCOUNT_KIND, sa_name, ns,
            spec={"annotations": annotations} if annotations else {},
            owners=[run.owner_ref()],
        ))
        self._ensure_owned(run, new_resource(
            ROLE_KIND, sa_name, ns,
            spec={"rules": kept},
            owners=[run.owner_ref()],
        ))
        self._ensure_owned(run, new_resource(
            ROLE_BINDING_KIND, sa_name, ns,
            spec={
                "roleRef": sa_name,
                "subjects": [{"kind": SERVICE_ACCOUNT_KIND, "name": sa_name}],
            },
            owners=[run.owner_ref()],
        ))
        return {
            "serviceAccount": sa_name,
            "rules": kept,
            "rejectedRules": rejected,
            # digest over all three desired specs: the quick path compares
            # it against the live objects so ANY out-of-band drift (rules,
            # binding subjects, SA cloud-identity annotations) forces the
            # full repair
            "objectsHash": objects_hash([
                {"annotations": annotations} if annotations else {},
                {"rules": kept},
                {
                    "roleRef": sa_name,
                    "subjects": [
                        {"kind": SERVICE_ACCOUNT_KIND, "name": sa_name}
                    ],
                },
            ]),
        }

    # ------------------------------------------------------------------
    def _collect_rules(self, ns: str, story_spec: StorySpec) -> list[dict[str, Any]]:
        """(reference: collectStoryRBACRules rbac.go:282 — template
        execution-policy rules for every engram the story uses + story
        policy rules)"""
        rules: list[dict[str, Any]] = []
        if story_spec.policy and story_spec.policy.execution:
            rules.extend(story_spec.policy.execution.rbac_rules or [])
        for step in story_spec.all_steps_deep():
            if step.ref is None:
                continue
            engram = self.store.try_get(ENGRAM_KIND, ns, step.ref.name)
            if engram is None:
                continue
            es = parse_engram(engram)
            template = self.store.try_get(
                ENGRAM_TEMPLATE_KIND, CLUSTER_NAMESPACE,
                es.template_ref.name if es.template_ref else "",
            )
            if template is None:
                continue
            ts = parse_engram_template(template)
            if ts.execution_policy is not None:
                rules.extend(ts.execution_policy.rbac_rules or [])
        # dedup (stable order)
        seen: set[str] = set()
        unique = []
        for r in rules:
            key = repr(sorted(r.items(), key=lambda kv: kv[0]))
            if key not in seen:
                seen.add(key)
                unique.append(r)
        return unique

    def _storage_annotations(self, story_spec: StorySpec) -> dict[str, str]:
        """IRSA / workload-identity annotations follow the run's storage
        provider (reference: storage annotations on SA, rbac.go + IRSA
        podspec/storage.go:42)."""
        policy = story_spec.policy.storage if story_spec.policy else None
        if policy is None or policy.s3 is None:
            return {}
        return dict(policy.s3.service_account_annotations or {})

    def _ensure_owned(self, run: Resource, desired: Resource) -> None:
        """Create-or-validate: an existing object not owned by this run is
        an identity-hijack attempt and is NOT adopted
        (reference: ownership validation against SA hijack, rbac.go)."""
        try:
            self.store.create(desired)
            metrics.rbac_ops.inc("create")
            return
        except AlreadyExists:
            pass
        existing = self.store.try_get(
            desired.kind, desired.meta.namespace, desired.meta.name
        )
        if existing is None:
            return
        if not existing.has_owner(run):
            raise RBACOwnershipError(
                f"{desired.kind} {desired.meta.name!r} exists but is not "
                f"owned by StoryRun {run.meta.name!r} — refusing to adopt"
            )
        if existing.spec != desired.spec:
            def sync(r: Resource) -> None:
                r.spec = dict(desired.spec)

            self.store.mutate(
                desired.kind, desired.meta.namespace, desired.meta.name, sync
            )
            metrics.rbac_ops.inc("update")


#: verbs a controller needs on kinds it fully manages
_MANAGE_VERBS = ["get", "list", "watch", "create", "update", "patch", "delete"]


def manager_cluster_rules() -> list[dict[str, Any]]:
    """The ClusterRole rules the MANAGER deployment needs against a real
    cluster, derived from code-level registrations — the schema registry
    (CRD groups), the workload kinds the materializer emits and the
    executors watch, and the election Lease — so the chart's
    hand-maintained ``serviceaccount.yaml`` can be diffed against what
    the code actually touches (test_chart_rbac_drift.py), the same
    chart<->code contract as ``webhook_configurations()``.

    Shape notes: CRD kinds get wildcard resources per group (the
    manager owns every kind it registers, including future ones in the
    same groups) plus the status subresource; Pods are read-only (exit
    code extraction only — the Job controller owns their lifecycle).
    """
    from ..api.schemas import _registry
    from ..cluster.kubeclient import plural_for
    from ..gke.materialize import JOBSET_API_VERSION
    from ..utils.leader import KubeLeaseElector
    from .streaming import DEPLOYMENT_KIND, SERVICE_KIND, STATEFULSET_KIND

    crd_groups = sorted({e.group for e in _registry()})
    jobset_group = JOBSET_API_VERSION.split("/", 1)[0]
    lease_group = KubeLeaseElector.API_VERSION.split("/", 1)[0]
    return [
        {"apiGroups": crd_groups, "resources": ["*"], "verbs": _MANAGE_VERBS},
        {"apiGroups": crd_groups, "resources": ["*/status"],
         "verbs": ["get", "update", "patch"]},
        {"apiGroups": ["batch"], "resources": [plural_for("Job")],
         "verbs": _MANAGE_VERBS},
        {"apiGroups": [jobset_group], "resources": [plural_for("JobSet")],
         "verbs": _MANAGE_VERBS},
        {"apiGroups": ["apps"],
         "resources": sorted(
             [plural_for(DEPLOYMENT_KIND), plural_for(STATEFULSET_KIND)]),
         "verbs": _MANAGE_VERBS},
        {"apiGroups": [""], "resources": [plural_for("Pod")],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": [""], "resources": [plural_for(SERVICE_KIND)],
         "verbs": _MANAGE_VERBS},
        {"apiGroups": [lease_group], "resources": [plural_for("Lease")],
         "verbs": _MANAGE_VERBS},
    ]


def objects_hash(specs: list[dict[str, Any]]) -> str:
    """Stable digest of the [SA, Role, RoleBinding] spec list — lets the
    StoryRun controller's quick path detect out-of-band drift of any of
    the three identity objects without re-collecting rules."""
    canon = json.dumps(specs, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


class RBACOwnershipError(Exception):
    pass
