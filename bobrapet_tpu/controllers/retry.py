"""Exit-code classification + retry delay computation.

Capability parity with the reference's failure engine
(reference: classifyExitCode steprun_controller.go:4815,
computeRetryDelay:2251, RetryPolicy shared_types.go:400).
"""

from __future__ import annotations

import random
from typing import Optional

from ..api.enums import BackoffStrategy, ExitClass
from ..api.shared import RetryPolicy
from ..sdk import contract
from ..utils.duration import parse_duration


def classify_exit_code(code: Optional[int], preempted: bool = False) -> ExitClass:
    """Map a worker exit code to an ExitClass
    (reference: classifyExitCode steprun_controller.go:4815):

    - 0 success
    - -1/None: pod state indeterminate -> unknown (retries without
      consuming budget)
    - 124: timeout -> retry
    - 119: contract rate-limit signal -> rateLimited (the reference
      carries 429 at the StructuredError level; one exit byte can't)
    - 137/143 (SIGKILL/SIGTERM): evicted/preempted -> retry
    - 125-127: container/config failure -> terminal
    - 1-127: application error -> terminal
    - 128-255: killed by signal -> retry

    ``preempted`` is the node-condition half of a GKE preemption notice
    (SIGTERM alone is ambiguous — a timeout kill and a slice reclaim
    both deliver 143). When the infrastructure attests the node was
    reclaimed, ANY nonzero death classifies as PREEMPTED, which routes
    through the fleet subsystem's checkpoint-resuming redrive instead
    of the user retry budget.
    """
    if code is None or code < 0:
        return ExitClass.UNKNOWN
    if code == 0:
        return ExitClass.SUCCESS
    if preempted:
        return ExitClass.PREEMPTED
    if code == contract.EXIT_TIMEOUT:
        return ExitClass.RETRY
    if code == contract.EXIT_RATE_LIMITED:
        return ExitClass.RATE_LIMITED
    if code in (contract.EXIT_SIGKILL, contract.EXIT_SIGTERM):
        return ExitClass.RETRY
    if contract.EXIT_CONFIG_TERMINAL_MIN <= code <= contract.EXIT_CONFIG_TERMINAL_MAX:
        return ExitClass.TERMINAL
    if 1 <= code <= 127:
        return ExitClass.TERMINAL
    if 128 <= code <= 255:
        return ExitClass.RETRY
    return ExitClass.UNKNOWN


def compute_retry_delay(
    policy: RetryPolicy,
    attempt: int,
    rng: Optional[random.Random] = None,
    rate_limited: bool = False,
) -> float:
    """Delay before retry ``attempt`` (1-based)
    (reference: computeRetryDelay steprun_controller.go:2251 —
    exponential/linear/constant + jitter pct + maxDelay; rate-limited
    failures take at least the max delay's floor)."""
    base = parse_duration(policy.delay, default=5.0) or 5.0
    max_delay = parse_duration(policy.max_delay, default=300.0) or 300.0
    strategy = policy.backoff or BackoffStrategy.EXPONENTIAL
    if strategy is BackoffStrategy.EXPONENTIAL:
        delay = base * (2 ** max(0, attempt - 1))
    elif strategy is BackoffStrategy.LINEAR:
        delay = base * attempt
    else:
        delay = base
    if rate_limited:
        delay = max(delay, min(30.0, max_delay))
    delay = min(delay, max_delay)
    jitter_pct = policy.jitter or 0
    if jitter_pct:
        r = rng or random
        delay *= 1 + (r.random() * 2 - 1) * (jitter_pct / 100.0)
    return max(0.0, delay)


def retry_budget_left(policy: RetryPolicy, retries_consumed: int) -> bool:
    max_retries = policy.max_retries if policy.max_retries is not None else 3
    return retries_consumed < max_retries
