"""Impulse controller: materialize the always-on trigger workload.

Capability parity with the reference's Impulse reconciler
(reference: internal/controller/impulse_controller.go — Reconcile:134,
ensureImpulseWorkloads:276, buildImpulsePodTemplate:1437,
appendTriggerDeliveryEnvVars:1477, syncImpulseTriggerStats:1151):

- resolve the ImpulseTemplate (Blocked when missing; delivery defaults
  merge template -> impulse),
- materialize the long-running workload on the bus: a Deployment (or
  StatefulSet) record + Service + ServiceAccount, pod env carrying the
  trigger contract (story ref, mapping template, delivery/throttle
  policy JSON) so the in-pod SDK can create StoryTriggers,
- sync trigger stats from StoryTriggers/StoryRuns referencing this
  impulse with idempotent token counting (a run/trigger counts once, an
  annotation on the counted child records consumption).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Optional

from ..api import conditions
from ..api.catalog import (
    CLUSTER_NAMESPACE,
    IMPULSE_TEMPLATE_KIND,
    parse_impulse_template,
)
from ..api.enums import Phase, WorkloadMode
from ..api.impulse import KIND as IMPULSE_KIND, parse_impulse
from ..api.runs import STORY_RUN_KIND, STORY_TRIGGER_KIND
from ..core.events import EventRecorder
from ..core.object import Resource, new_resource
from ..core.store import AlreadyExists, ResourceStore
from ..observability.metrics import metrics
from ..sdk import contract
from .manager import Clock
from .resources import ANNO_COUNTED_IMPULSE, ANNO_COUNTED_IMPULSE_OUTCOME, _consume_tokens
from .streaming import DEPLOYMENT_KIND, SERVICE_KIND, STATEFULSET_KIND

_log = logging.getLogger(__name__)

SERVICE_ACCOUNT_KIND = "ServiceAccount"

INDEX_TRIGGER_IMPULSE = "impulseRef"
#: status/annotation-derived counter indexes (registered by the
#: runtime; same O(interesting-children) pattern as
#: controllers/resources.py — the full-bucket lists were the N^2 term
#: the r5 scale soak exposed)
INDEX_TRIGGER_UNCOUNTED = "impulseRefUncounted"
INDEX_TRIGGER_THROTTLED = "impulseRefThrottled"
INDEX_STORYRUN_IMPULSE_UNCOUNTED = "impulseRefUncounted"
INDEX_STORYRUN_IMPULSE_OUTCOME = "impulseRefOutcomeUncounted"


class ImpulseController:
    """(reference: impulse_controller.go Reconcile:134)"""

    def __init__(
        self,
        store: ResourceStore,
        config_manager,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
    ):
        self.store = store
        self.config_manager = config_manager
        self.recorder = recorder or EventRecorder()
        self.clock = clock or Clock()

    # ------------------------------------------------------------------
    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        impulse = self.store.try_get(IMPULSE_KIND, namespace, name)
        if impulse is None or impulse.meta.deletion_timestamp is not None:
            return None
        spec = parse_impulse(impulse)
        now = self.clock.now()

        template_name = spec.template_ref.name if spec.template_ref else ""
        template = self.store.try_get(
            IMPULSE_TEMPLATE_KIND, CLUSTER_NAMESPACE, template_name
        )
        if template is None:
            self._set_status(
                impulse, Phase.BLOCKED, ready=False,
                reason=conditions.Reason.TEMPLATE_NOT_FOUND,
                message=f"impulse template {template_name!r} not found",
            )
            return None
        tspec = parse_impulse_template(template)

        story_name = spec.story_ref.name if spec.story_ref else ""
        if story_name:
            from ..api.story import KIND as STORY_KIND

            story_ns = (spec.story_ref.namespace or namespace)
            if self.store.try_get(STORY_KIND, story_ns, story_name) is None:
                self._set_status(
                    impulse, Phase.BLOCKED, ready=False,
                    reason=conditions.Reason.STORY_NOT_FOUND,
                    message=f"story {story_ns}/{story_name} not found",
                )
                return None

        self._ensure_workloads(impulse, spec, tspec)
        stats = self._sync_trigger_stats(impulse, now)

        self._set_status(
            impulse, Phase.RUNNING, ready=True,
            reason=conditions.Reason.LISTENING,
            message="impulse workload materialized",
            extra=stats,
        )
        return None

    # ------------------------------------------------------------------
    def _ensure_workloads(self, impulse: Resource, spec, tspec) -> None:
        """(reference: ensureImpulseWorkloads impulse_controller.go:276,
        buildImpulsePodTemplate:1437)"""
        ns, name = impulse.meta.namespace, impulse.meta.name
        owner = [impulse.owner_ref()]
        mode = (
            (spec.workload.mode if spec.workload and spec.workload.mode else None)
            or WorkloadMode.DEPLOYMENT
        )
        kind = STATEFULSET_KIND if mode == WorkloadMode.STATEFULSET else DEPLOYMENT_KIND
        cfg = self.config_manager.config

        # delivery defaults merge: template recommendation -> impulse spec
        # (reference: appendTriggerDeliveryEnvVars:1477)
        delivery = (
            spec.delivery.to_dict() if spec.delivery is not None
            else (tspec.delivery.to_dict() if tspec.delivery is not None else {})
        )
        env: dict[str, str] = {
            contract.ENV_CONTRACT_VERSION: contract.CONTRACT_VERSION,
            contract.ENV_NAMESPACE: ns,
            contract.ENV_IMPULSE: name,
            contract.ENV_GRPC_PORT: str(cfg.engram.grpc_port),
            contract.ENV_MAX_INLINE_SIZE: str(cfg.engram.max_inline_size),
            contract.ENV_TRIGGER_STORY: (
                spec.story_ref.name if spec.story_ref else ""
            ),
            contract.ENV_TRIGGER_DELIVERY: json.dumps(
                delivery, separators=(",", ":"), sort_keys=True
            ),
        }
        if spec.story_ref and spec.story_ref.namespace:
            env[contract.ENV_TRIGGER_STORY_NAMESPACE] = spec.story_ref.namespace
        if spec.mapping:
            env[contract.ENV_TRIGGER_MAPPING] = json.dumps(
                spec.mapping, separators=(",", ":"), sort_keys=True
            )
        if spec.throttle is not None:
            env[contract.ENV_TRIGGER_THROTTLE] = json.dumps(
                spec.throttle.to_dict(), separators=(",", ":"), sort_keys=True
            )
        if spec.with_config:
            env[contract.ENV_CONFIG] = json.dumps(
                spec.with_config, separators=(",", ":"), sort_keys=True
            )

        sa_name = f"{name}-impulse-sa"
        rbac_rules = (
            list(tspec.execution_policy.rbac_rules)
            if tspec.execution_policy and tspec.execution_policy.rbac_rules
            else []
        )
        self._apply(new_resource(
            SERVICE_ACCOUNT_KIND, sa_name, ns,
            spec={"rbacRules": rbac_rules} if rbac_rules else {},
            owners=owner,
        ))
        self._apply(new_resource(
            kind, f"{name}-impulse", ns,
            spec={
                "image": tspec.image,
                "replicas": (
                    spec.workload.replicas
                    if spec.workload and spec.workload.replicas is not None
                    else 1
                ),
                "env": env,
                "serviceAccountName": sa_name,
                "selector": {"bobrapet.io/impulse": name},
                "secrets": dict(spec.secrets or {}),
            },
            labels={"bobrapet.io/impulse": name},
            owners=owner,
        ))
        self._apply(new_resource(
            SERVICE_KIND, f"{name}-impulse-svc", ns,
            spec={
                "selector": {"bobrapet.io/impulse": name},
                "port": cfg.engram.grpc_port,
            },
            owners=owner,
        ))

    def _apply(self, desired: Resource) -> None:
        """Create-or-update keyed on spec equality
        (reference: pkg/workload Ensure ensure.go:58 with
        normalization-aware diffing)."""
        try:
            self.store.create(desired)
        except AlreadyExists:
            existing = self.store.try_get(
                desired.kind, desired.meta.namespace, desired.meta.name
            )
            if existing is not None and existing.spec != desired.spec:
                def sync(r: Resource) -> None:
                    r.spec = dict(desired.spec)

                self.store.mutate(
                    desired.kind, desired.meta.namespace, desired.meta.name, sync
                )

    # ------------------------------------------------------------------
    def _sync_trigger_stats(self, impulse: Resource, now: float) -> dict[str, int]:
        """(reference: syncImpulseTriggerStats impulse_controller.go:1151
        — token-based idempotent counting)"""
        from .resources import COUNT_BATCH, _bounded_fetch

        ns, name = impulse.meta.namespace, impulse.meta.name
        # O(interesting) index reads (see resources.py): only the
        # still-uncounted children are fetched, throttle counts come
        # from a status-derived bucket
        uncounted_triggers = _bounded_fetch(
            self.store, STORY_TRIGGER_KIND, ns,
            (INDEX_TRIGGER_UNCOUNTED, name), COUNT_BATCH,
        )
        uncounted_runs = _bounded_fetch(
            self.store, STORY_RUN_KIND, ns,
            (INDEX_STORYRUN_IMPULSE_UNCOUNTED, name), COUNT_BATCH,
        )
        # the outcome index already excludes non-terminal runs, so the
        # value_fn's "defer until terminal" None-return never consumes
        # batch budget scanning still-running children
        uncounted_outcomes = _bounded_fetch(
            self.store, STORY_RUN_KIND, ns,
            (INDEX_STORYRUN_IMPULSE_OUTCOME, name), COUNT_BATCH,
        )

        received_inc = _consume_tokens(
            self.store, uncounted_triggers, ANNO_COUNTED_IMPULSE, now
        ).get("", 0)
        launched_inc = _consume_tokens(
            self.store, uncounted_runs, ANNO_COUNTED_IMPULSE, now
        ).get("", 0)

        def outcome(run: Resource) -> Optional[str]:
            phase = run.status.get("phase")
            if not phase or not Phase(phase).is_terminal:
                return None  # count outcomes only when terminal
            return "success" if phase == str(Phase.SUCCEEDED) else "failed"

        outcome_inc = _consume_tokens(
            self.store, uncounted_outcomes, ANNO_COUNTED_IMPULSE_OUTCOME, now,
            value_fn=outcome,
        )
        throttled = self.store.count(
            STORY_TRIGGER_KIND, namespace=ns,
            index=(INDEX_TRIGGER_THROTTLED, name),
        )
        metrics.impulse_throttled.set(throttled, f"{ns}/{name}")
        metrics.trigger_backfills.inc(IMPULSE_KIND)
        return {
            "_received": received_inc,
            "_launched": launched_inc,
            "_succeeded": outcome_inc.get("success", 0),
            "_failed": outcome_inc.get("failed", 0),
            "_throttled": throttled,
        }

    # ------------------------------------------------------------------
    def _set_status(
        self,
        impulse: Resource,
        phase: Phase,
        ready: bool,
        reason: str,
        message: str,
        extra: Optional[dict[str, int]] = None,
    ) -> None:
        now = self.clock.now()
        extra = extra or {}

        def patch(st: dict[str, Any]) -> None:
            st["phase"] = str(phase)
            st["observedGeneration"] = impulse.meta.generation
            st["triggersReceived"] = int(st.get("triggersReceived", 0)) + extra.get("_received", 0)
            st["storiesLaunched"] = int(st.get("storiesLaunched", 0)) + extra.get("_launched", 0)
            st["storiesSucceeded"] = int(st.get("storiesSucceeded", 0)) + extra.get("_succeeded", 0)
            st["storiesFailed"] = int(st.get("storiesFailed", 0)) + extra.get("_failed", 0)
            st["triggersThrottled"] = extra.get("_throttled", st.get("triggersThrottled", 0))
            conds = st.setdefault("conditions", [])
            conditions.set_condition(
                conds, conditions.READY, ready, reason, message, now=now
            )
            conditions.set_condition(
                conds, conditions.LISTENING, phase is Phase.RUNNING,
                reason, message, now=now,
            )

        self.store.patch_status(IMPULSE_KIND, impulse.meta.namespace, impulse.meta.name, patch)
        if not ready:
            self.recorder.warning(impulse, reason, message)
