"""Realtime (streaming) StepRun materialization — the full control plane.

Capability parity with the reference's run-scoped realtime path
(reference: steprun_controller.go reconcileRunScopedRealtimeStep:2527,
ensureRunTransportBinding:3701, ensureRealtimeService:2677,
ensureRealtimeDeployment:2762, computeDownstreamTargets:1405,
ensureDownstreamTargets:1548, deriveRealtimePhase:2838, handoff
:4395-4494):

1. resolve the step's declared transport (story.spec.transports entry ->
   cluster Transport resource),
2. ensure the per-run **TransportBinding** with negotiated codecs (or the
   ICI mesh descriptor) + merged streaming settings in status; bump
   ``connectorGeneration`` when the negotiated contract changes,
3. ensure the per-run **Service** + **Deployment** records (env carries
   the binding info + downstream targets, so the engram SDK and
   connector sidecar need no API access),
4. compute **downstream targets** from the stream topology (hub vs P2P)
   and patch them into this StepRun's spec,
5. maintain **handoff** status across connector generations
   (drain/cutover from lifecycle settings),
6. derive the StepRun phase from binding + deployment readiness.

The data plane (engram workers streaming gRPC/ICI) never passes through
the operator.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Optional

from ..api import conditions
from ..api.catalog import CLUSTER_NAMESPACE
from ..api.enums import HandoffPhase, Phase
from ..api.runs import STEP_RUN_KIND, STORY_RUN_KIND
from ..api.story import KIND as STORY_KIND, parse_story
from ..api.transport import (
    MediaBinding,
    TRANSPORT_BINDING_KIND,
    TRANSPORT_KIND,
    parse_transport,
)
from ..core.object import Resource, new_resource
from ..core.store import AlreadyExists, NotFound
from ..observability.metrics import metrics
from ..sdk import contract
from ..transport import (
    CodecError,
    analyze_topology,
    compute_downstream_targets,
    merge_streaming_settings,
    negotiate_binding,
    step_needs_hub,
)

_log = logging.getLogger(__name__)

SERVICE_KIND = "Service"
DEPLOYMENT_KIND = "Deployment"
STATEFULSET_KIND = "StatefulSet"

#: running realtime steps re-reconcile at this cadence to refresh their
#: binding's heartbeat; must be well below the Transport controller's
#: staleness window so a quiet healthy topology never reads as stale
HEARTBEAT_REFRESH = 600.0
CANCEL_ANNOTATION = "runs.bobrapet.io/cancel"


# ---------------------------------------------------------------------------
# entry point (called from StepRunController._reconcile_realtime)
# ---------------------------------------------------------------------------

def reconcile_realtime_step(ctrl, sr, spec, engram_spec, template_spec):
    ns, name = sr.meta.namespace, sr.meta.name

    if CANCEL_ANNOTATION in sr.meta.annotations:
        return _terminate_topology(ctrl, sr)

    ctx = _build_runtime_context(ctrl, sr, spec)
    if ctx is None:
        return _set_pending(ctrl, sr, conditions.Reason.AWAITING_STORY_RUN,
                            "story context unavailable")

    binding = None
    if ctx["transport"] is not None:
        binding, err = _ensure_binding(ctrl, sr, spec, ctx)
        if err is not None:
            return _set_failed_transport(ctrl, sr, err)

    svc_name, port = _ensure_service(ctrl, sr, spec, engram_spec)
    targets = _ensure_downstream_targets(ctrl, sr, ctx, svc_name, port)
    generation = (binding.status.get("connectorGeneration", 1) if binding else 1)
    deployment = _ensure_deployment(
        ctrl, sr, spec, engram_spec, template_spec, ctx,
        svc_name, port, binding, targets, generation,
    )

    _sync_handoff(ctrl, sr, ctx, deployment, generation)
    return _derive_phase(ctrl, sr, binding, deployment, svc_name, port)


# ---------------------------------------------------------------------------
# context
# ---------------------------------------------------------------------------

def _build_runtime_context(ctrl, sr, spec) -> Optional[dict[str, Any]]:
    """(reference: buildRealtimeRuntimeContext steprun_controller.go:2563)"""
    ns = sr.meta.namespace
    run_name = (sr.spec.get("storyRunRef") or {}).get("name")
    # read-only views (PR 1 copy-on-write idiom): the context chain is
    # resolved every reconcile and never mutated here
    run = ctrl.store.try_get_view(STORY_RUN_KIND, ns, run_name) if run_name else None
    if run is None:
        return None
    story_name = (run.spec.get("storyRef") or {}).get("name")
    story_ns = (run.spec.get("storyRef") or {}).get("namespace") or ns
    story = ctrl.store.try_get_view(STORY_KIND, story_ns, story_name) if story_name else None
    if story is None:
        return None
    story_spec = parse_story(story)
    step = story_spec.step(spec.step_id or "")

    # streaming predicate: a step streams when its engram's effective mode
    # is deployment/statefulset (reference: topology.go:46)
    def is_streaming(s) -> bool:
        if s.ref is None:
            return False
        from ..api.catalog import ENGRAM_TEMPLATE_KIND, parse_engram_template
        from ..api.engram import KIND as ENGRAM_KIND, parse_engram

        e = ctrl.store.try_get_view(ENGRAM_KIND, ns, s.ref.name)
        if e is None:
            return False
        es = parse_engram(e)
        mode = es.mode
        if mode is None:
            t = ctrl.store.try_get_view(
                ENGRAM_TEMPLATE_KIND, CLUSTER_NAMESPACE,
                es.template_ref.name if es.template_ref else "",
            )
            if t is not None:
                modes = parse_engram_template(t).supported_modes
                mode = modes[0] if modes else None
        return bool(mode and mode.is_realtime)

    topology = analyze_topology(story_spec, is_streaming)

    # transport declaration: step.transport names a story transports entry
    transport = None
    declared = None
    if step is not None and step.transport:
        for t in story_spec.transports or []:
            if (t.name or t.transport_ref) == step.transport:
                declared = t
                break
        if declared is not None:
            tname = declared.transport_ref or declared.name
            tr = ctrl.store.try_get_view(TRANSPORT_KIND, CLUSTER_NAMESPACE, tname)
            if tr is not None:
                transport = tr

    settings = None
    if transport is not None:
        settings = merge_streaming_settings(
            parse_transport(transport).streaming,
            declared.streaming or declared.settings if declared else None,
            (step.runtime or {}).get("streaming") if step is not None else None,
        )

    return {
        "run": run,
        "story": story_spec,
        "story_name": story.meta.name,
        "step": step,
        "topology": topology,
        "transport": transport,
        "declared": declared,
        "settings": settings,
    }


# ---------------------------------------------------------------------------
# binding
# ---------------------------------------------------------------------------

def binding_name(sr_name: str) -> str:
    return f"{sr_name}-binding"


def _offered(step, kind: str) -> Optional[MediaBinding]:
    runtime = step.runtime if step is not None else None
    raw = (runtime or {}).get(kind)
    return MediaBinding.from_dict(raw) if raw else None


def _ensure_binding(ctrl, sr, spec, ctx):
    """(reference: ensureRunTransportBinding steprun_controller.go:3701;
    codec negotiation via pkg/transport/codecs.go:11,58)"""
    started = time.monotonic()
    try:
        return _ensure_binding_inner(ctrl, sr, spec, ctx)
    finally:
        metrics.binding_op_duration.observe(time.monotonic() - started, "ensure")


def _ensure_binding_inner(ctrl, sr, spec, ctx):
    ns = sr.meta.namespace
    transport = ctx["transport"]
    tspec = parse_transport(transport)
    step = ctx["step"]
    now = ctrl.clock.now()

    try:
        negotiated = negotiate_binding(
            tspec,
            audio=_offered(step, "audio"),
            video=_offered(step, "video"),
            binary=_offered(step, "binary"),
            slice_grant=sr.spec.get("sliceGrant"),
        )
    except CodecError as e:
        return None, str(e)

    settings_dict = ctx["settings"].to_dict() if ctx["settings"] is not None else {}
    bname = binding_name(sr.meta.name)
    desired_spec = {
        "transportRef": transport.meta.name,
        "storyRunRef": {"name": (sr.spec.get("storyRunRef") or {}).get("name", "")},
        "stepName": spec.step_id or "",
        "engramName": spec.engram_ref.name if spec.engram_ref else "",
        "driver": tspec.driver,
        "rawSettings": settings_dict,
    }

    existing = ctrl.store.try_get_view(TRANSPORT_BINDING_KIND, ns, bname)
    if existing is None:
        b = new_resource(TRANSPORT_BINDING_KIND, bname, ns, desired_spec,
                         labels={"bobrapet.io/step-run": sr.meta.name},
                         owners=[sr.owner_ref()])
        try:
            ctrl.store.create(b)
        except AlreadyExists:
            pass
        metrics.binding_ops.inc("create")
        ctrl.store.patch_status(
            TRANSPORT_BINDING_KIND, ns, bname,
            lambda st: st.update({
                "phase": "Ready",
                "negotiated": negotiated,
                "negotiatedAt": now,
                "connectorGeneration": 1,
            }),
        )
        return ctrl.store.get_view(TRANSPORT_BINDING_KIND, ns, bname), None

    # re-negotiate: a changed contract bumps the connector generation
    # (reference: connector generation bumps steprun_controller.go:2711)
    st = existing.status
    if st.get("negotiated") != negotiated or existing.spec.get("rawSettings") != settings_dict:
        if existing.spec.get("rawSettings") != settings_dict:
            ctrl.store.mutate(
                TRANSPORT_BINDING_KIND, ns, bname,
                lambda r: r.spec.__setitem__("rawSettings", settings_dict),
            )
        ctrl.store.patch_status(
            TRANSPORT_BINDING_KIND, ns, bname,
            lambda s: s.update({
                "phase": "Ready",
                "negotiated": negotiated,
                "negotiatedAt": now,
                "connectorGeneration": int(s.get("connectorGeneration", 1)) + 1,
            }),
        )
        metrics.binding_ops.inc("update")
    return ctrl.store.get_view(TRANSPORT_BINDING_KIND, ns, bname), None


# ---------------------------------------------------------------------------
# service + deployment
# ---------------------------------------------------------------------------

def _ensure_service(ctrl, sr, spec, engram_spec):
    """(reference: ensureRealtimeService steprun_controller.go:2677)"""
    ns, name = sr.meta.namespace, sr.meta.name
    port = (
        engram_spec.transport.grpc_port
        if engram_spec.transport and engram_spec.transport.grpc_port
        else ctrl.config_manager.config.engram.grpc_port
    )
    svc_name = f"{name}-svc"
    svc = new_resource(
        SERVICE_KIND, svc_name, ns,
        spec={
            "selector": {"bobrapet.io/step-run": name},
            "port": port,
            "engram": spec.engram_ref.name if spec.engram_ref else "",
            "stepName": spec.step_id or name,
        },
        owners=[sr.owner_ref()],
    )
    try:
        ctrl.store.create(svc)
    except AlreadyExists:
        pass
    return svc_name, port


def _ensure_downstream_targets(ctrl, sr, ctx, svc_name, port):
    """(reference: computeDownstreamTargets:1405 /
    ensureDownstreamTargets:1548 — endpoints patched into THIS step's
    spec so its SDK knows the next hops)"""
    ns = sr.meta.namespace
    step = ctx["step"]
    if step is None or step.name not in ctx["topology"].streaming_steps:
        return []
    run_name = (sr.spec.get("storyRunRef") or {}).get("name", "")

    def endpoint_for(dep_step: str) -> Optional[tuple[str, int]]:
        from ..utils.naming import steprun_name

        dep_sr_name = steprun_name(run_name, dep_step)
        dep_svc = ctrl.store.try_get_view(SERVICE_KIND, ns, f"{dep_sr_name}-svc")
        if dep_svc is None:
            return None
        return (f"{dep_sr_name}-svc.{ns}.svc", int(dep_svc.spec.get("port", port)))

    tls = bool(
        ctx["transport"] is not None
        and (sr.spec.get("tls") or (ctx["declared"].settings or {}).get("tls")
             if ctx["declared"] else False)
    )
    targets = compute_downstream_targets(
        ctx["topology"], step.name, ns, endpoint_for,
        settings=ctx["settings"], tls=tls,
    )
    if targets != sr.spec.get("downstreamTargets"):
        try:
            ctrl.store.mutate(
                STEP_RUN_KIND, ns, sr.meta.name,
                lambda r: r.spec.__setitem__("downstreamTargets", targets),
            )
            metrics.downstream_target_mutations.inc()
        except NotFound:
            pass
    return targets


def _static_config(ctrl, ctx, sr) -> dict[str, Any]:
    """Static `with` evaluation for realtime steps — inputs-only scope;
    step outputs do not exist in a live topology
    (reference: evaluateStepConfigForRealtime steprun_controller.go:4868)."""
    raw = sr.spec.get("input") or {}
    try:
        scope = {"inputs": ctx["run"].spec.get("inputs") or {}, "steps": {}, "run": {
            "name": ctx["run"].meta.name, "namespace": ctx["run"].meta.namespace,
        }}
        return ctrl.evaluator.evaluate_value(raw, scope)
    except Exception:  # noqa: BLE001 - runtime templates stay verbatim
        return raw


def _ensure_deployment(ctrl, sr, spec, engram_spec, template_spec, ctx,
                       svc_name, port, binding, targets, generation):
    """(reference: ensureRealtimeDeployment steprun_controller.go:2762)"""
    ns, name = sr.meta.namespace, sr.meta.name
    cfg = ctrl.config_manager.config
    run_name = (sr.spec.get("storyRunRef") or {}).get("name", "")
    env = contract.build_env(
        namespace=ns,
        story=ctx["story_name"],
        story_run=run_name,
        step=spec.step_id or "",
        step_run=name,
        engram=spec.engram_ref.name if spec.engram_ref else "",
        execution_mode="deployment",
        max_inline_size=cfg.engram.max_inline_size,
        storage_timeout_seconds=cfg.engram.storage_timeout_seconds,
        max_recursion_depth=cfg.engram.max_recursion_depth,
        grpc_port=port,
        config=_static_config(ctrl, ctx, sr),
        downstream_targets=targets or None,
        # the status-persisted trace rides the env contract into the
        # serving workers (BOBRA_TRACEPARENT), exactly like the batch
        # path — the serving request lifecycle then stitches into the
        # run trace instead of starting its own
        trace_context=sr.status.get("trace"),
    )
    if binding is not None:
        env[contract.ENV_BINDING_INFO] = json.dumps({
            "binding": binding.meta.name,
            "driver": binding.spec.get("driver"),
            "negotiated": binding.status.get("negotiated") or {},
            # merged settings ride to the SDK so open_output_streams /
            # open_input_stream enforce the negotiated backpressure
            # contract without the engram re-supplying it
            "settings": binding.spec.get("rawSettings") or {},
            "generation": generation,
        }, separators=(",", ":"), sort_keys=True)

    # EngramTLSSpec -> data-plane mTLS: advertise the shared-CA mount
    # to the SDK and carry the secret name for the GKE materializer
    # (reference: engram_types.go:91-107 + pkg/transport/security.go:11)
    tls_secret = None
    if (engram_spec.transport is not None
            and engram_spec.transport.tls is not None
            and engram_spec.transport.tls.enabled):
        from ..dataplane.tls import DEFAULT_TLS_MOUNT

        env[contract.ENV_TLS_DIR] = DEFAULT_TLS_MOUNT
        tls_secret = engram_spec.transport.tls.secret_name or f"{name}-tls"

    desired_spec = {
        "image": template_spec.image or "",
        "entrypoint": template_spec.entrypoint or "",
        "replicas": 1,
        "env": env,
        "selector": {"bobrapet.io/step-run": name},
        "connectorGeneration": generation,
        "serviceName": svc_name,
    }
    if tls_secret:
        desired_spec["tlsSecret"] = tls_secret
    dep_name = f"{name}-rt"
    existing = ctrl.store.try_get_view(DEPLOYMENT_KIND, ns, dep_name)
    if existing is None:
        d = new_resource(DEPLOYMENT_KIND, dep_name, ns, desired_spec,
                         labels={"bobrapet.io/step-run": name},
                         owners=[sr.owner_ref()])
        try:
            ctrl.store.create(d)
        except AlreadyExists:
            pass
        return ctrl.store.get_view(DEPLOYMENT_KIND, ns, dep_name)
    if existing.spec != desired_spec:
        def sync(r: Resource) -> None:
            r.spec = dict(desired_spec)

        ctrl.store.mutate(DEPLOYMENT_KIND, ns, dep_name, sync)
    return ctrl.store.get_view(DEPLOYMENT_KIND, ns, dep_name)


# ---------------------------------------------------------------------------
# handoff + phase
# ---------------------------------------------------------------------------

def _sync_handoff(ctrl, sr, ctx, deployment, generation) -> None:
    """(reference: handoff/upgrade strategy steprun_controller.go:4395-4494,
    HandoffStatus steprun_types.go:175-191) — when the connector
    generation moves past what the live deployment serves, record the
    in-flight handoff; cutover completes when the deployment observes the
    new generation."""
    ns, name = sr.meta.namespace, sr.meta.name
    observed = int(deployment.status.get("observedConnectorGeneration", 0))
    # per-generation readiness: the new generation's workers passed
    # their readiness probe (for a TPU engram: model compiled + warm).
    # Workloads that don't report it fall back to observation — the GKE
    # pod template carries a real readiness probe instead.
    ready_gen = int(deployment.status.get("readyGeneration", observed))
    current = sr.status.get("handoff") or {}
    strategy = "drain"
    settings = ctx.get("settings")
    if settings is not None and settings.lifecycle is not None and settings.lifecycle.upgrade_strategy:
        strategy = settings.lifecycle.upgrade_strategy

    if observed and (observed < generation or ready_gen < generation):
        if current.get("newGeneration") != generation or current.get("phase") == HandoffPhase.COMPLETED:
            now = ctrl.clock.now()
            ctrl.store.patch_status(
                STEP_RUN_KIND, ns, name,
                lambda st: st.__setitem__("handoff", {
                    "strategy": strategy,
                    "phase": str(
                        HandoffPhase.DRAINING if strategy == "drain"
                        else HandoffPhase.CUTTING_OVER
                    ),
                    "oldGeneration": min(observed, ready_gen) or observed,
                    "newGeneration": generation,
                    "startedAt": now,
                }),
            )
    elif (
        current
        and current.get("phase") != HandoffPhase.COMPLETED
        and observed >= generation
        and ready_gen >= generation
    ):
        # cutover/drain completes only when the NEW generation is ready
        # to serve — old workers keep the stream until then
        ctrl.store.patch_status(
            STEP_RUN_KIND, ns, name,
            lambda st: st.__setitem__(
                "handoff", {**current, "phase": str(HandoffPhase.COMPLETED)}
            ),
        )


def _derive_phase(ctrl, sr, binding, deployment, svc_name, port):
    """(reference: deriveRealtimePhase steprun_controller.go:2838)"""
    ns, name = sr.meta.namespace, sr.meta.name
    now = ctrl.clock.now()
    binding_ready = binding is None or binding.status.get("phase") == "Ready"
    ready_replicas = int(deployment.status.get("readyReplicas", 0))
    dep_ready = ready_replicas >= int(deployment.spec.get("replicas", 1))

    # connector-heartbeat role: a binding whose workers are up counts as
    # heartbeating (a real connector stamps this itself; locally the
    # controller observes workload readiness), which keeps the Transport
    # controller's staleness sweep meaningful outside unit tests. The
    # refresh is rate-limited: re-stamping every reconcile would emit a
    # watch event that triggers the next reconcile (hot loop). A running
    # step requeues itself at HEARTBEAT_REFRESH so a quiescent healthy
    # topology keeps beating with no external events.
    requeue = None
    if binding is not None and binding_ready and dep_ready:
        last_beat = binding.status.get("heartbeatAt")
        if last_beat is None or now - last_beat >= 30.0:
            try:
                ctrl.store.patch_status(
                    TRANSPORT_BINDING_KIND, ns, binding.meta.name,
                    lambda st: st.update({"heartbeatAt": now}),
                )
            except NotFound:
                pass
        requeue = HEARTBEAT_REFRESH

    def patch(st: dict[str, Any]) -> None:
        st["serviceName"] = svc_name
        st["endpoint"] = f"{svc_name}.{ns}.svc:{port}"
        if binding is not None:
            st["bindingName"] = binding.meta.name
        conds = st.setdefault("conditions", [])
        conditions.set_condition(
            conds, conditions.TRANSPORT_READY, binding_ready,
            conditions.Reason.TRANSPORT_READY if binding_ready
            else conditions.Reason.AWAITING_TRANSPORT,
            "binding negotiated" if binding_ready else "binding not ready",
            now=now,
        )
        if binding_ready and dep_ready:
            st["phase"] = str(Phase.RUNNING)
            st.setdefault("startedAt", now)
        else:
            st["phase"] = str(Phase.PENDING)
            st["message"] = (
                "waiting for stream workers"
                if binding_ready else "waiting for transport binding"
            )

    ctrl.store.patch_status(STEP_RUN_KIND, ns, name, patch)
    return requeue


def _terminate_topology(ctrl, sr):
    """Graceful cancel reached a streaming step: tear the topology down
    (reference: realtime topology termination, ReasonTopologyTerminated
    conditions.go:119 consumed at dag.go:441)."""
    ns, name = sr.meta.namespace, sr.meta.name
    now = ctrl.clock.now()
    bname = binding_name(name)
    b = ctrl.store.try_get_view(TRANSPORT_BINDING_KIND, ns, bname)
    if b is not None:
        ctrl.store.patch_status(
            TRANSPORT_BINDING_KIND, ns, bname,
            lambda st: st.update({"phase": "Terminated", "terminatedAt": now}),
        )

    def patch(st: dict[str, Any]) -> None:
        st["phase"] = str(Phase.CANCELED)
        st["finishedAt"] = now
        conds = st.setdefault("conditions", [])
        conditions.set_condition(
            conds, conditions.TRANSPORT_READY, False,
            conditions.Reason.TOPOLOGY_TERMINATED, "topology terminated",
            now=now,
        )

    ctrl.store.patch_status(STEP_RUN_KIND, ns, name, patch)
    return None


def _set_pending(ctrl, sr, reason, message):
    now = ctrl.clock.now()

    def patch(st: dict[str, Any]) -> None:
        st["phase"] = str(Phase.PENDING)
        st["message"] = message
        conds = st.setdefault("conditions", [])
        conditions.set_condition(conds, conditions.TRANSPORT_READY, False,
                                 reason, message, now=now)

    ctrl.store.patch_status(STEP_RUN_KIND, sr.meta.namespace, sr.meta.name, patch)
    return None


def _set_failed_transport(ctrl, sr, message):
    """Codec negotiation failure is terminal for the step
    (reference: TransportFailed)."""
    now = ctrl.clock.now()

    def patch(st: dict[str, Any]) -> None:
        st["phase"] = str(Phase.FAILED)
        st["message"] = message
        st["finishedAt"] = now
        st["error"] = {
            "version": "v1", "type": "initialization",
            "message": message, "retryable": False,
        }
        conds = st.setdefault("conditions", [])
        conditions.set_condition(conds, conditions.TRANSPORT_READY, False,
                                 conditions.Reason.TRANSPORT_FAILED, message,
                                 now=now)

    ctrl.store.patch_status(STEP_RUN_KIND, sr.meta.namespace, sr.meta.name, patch)
    return None


# backwards-compat export (pre-transport-layer core used this name)
def ensure_realtime_topology(ctrl, sr, spec, engram_spec, template_spec):
    return reconcile_realtime_step(ctrl, sr, spec, engram_spec, template_spec)
