"""Streaming topology materialization (control plane, minimal core).

Full codec negotiation / routing / handoff arrives with the transport
layer; this core keeps realtime StepRuns functional: per-run Service +
worker record, phase derived from readiness
(reference: ensureRealtimeService:2677, ensureRealtimeDeployment:2762,
deriveRealtimePhase:2838).
"""

from __future__ import annotations

from typing import Any

from ..api.enums import Phase
from ..api.runs import STEP_RUN_KIND
from ..core.object import new_resource
from ..core.store import AlreadyExists

SERVICE_KIND = "Service"


def ensure_realtime_topology(ctrl, sr, spec, engram_spec, template_spec):
    """Materialize the per-run service record and mark the step Running.

    The local data plane connects engram workers directly (they resolve
    each other through these Service records); on GKE this becomes a real
    Service + Deployment pair.
    """
    ns, name = sr.meta.namespace, sr.meta.name
    engram_name = spec.engram_ref.name if spec.engram_ref else ""
    port = ctrl.config_manager.config.engram.grpc_port
    svc_name = f"{name}-svc"
    svc = new_resource(
        SERVICE_KIND,
        svc_name,
        ns,
        spec={
            "selector": {"bobrapet.io/step-run": name},
            "port": port,
            "engram": engram_name,
            "stepName": spec.step_id or name,
        },
        owners=[sr.owner_ref()],
    )
    try:
        ctrl.store.create(svc)
    except AlreadyExists:
        pass

    def patch(status: dict[str, Any]) -> None:
        status["phase"] = str(Phase.RUNNING)
        status["serviceName"] = svc_name
        status["endpoint"] = f"{svc_name}.{ns}.svc:{port}"

    ctrl.store.patch_status(STEP_RUN_KIND, ns, name, patch)
    return None
