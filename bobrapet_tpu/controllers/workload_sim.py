"""Local workload simulator — the "kubelet" for long-running workloads.

The reference relies on real kubelets to bring Deployments/StatefulSets
up; its envtest suites simulate that by patching status
(reference: SURVEY §4 — "tests patch Job/StepRun status to simulate SDK
and kubelet behavior"). This simulator plays the same role for the local
runtime: it watches Deployment/StatefulSet records and marks them ready
(readyReplicas = replicas, observedConnectorGeneration synced), which
drives realtime StepRuns from Pending to Running. On GKE this module is
replaced by actual kubelets; nothing above it changes.

Disable (``auto_ready=False``) to exercise Pending/handoff states in
tests.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..core.store import ADDED, MODIFIED, ResourceStore, NotFound, WatchEvent
from .manager import Clock
from .streaming import DEPLOYMENT_KIND, STATEFULSET_KIND

_log = logging.getLogger(__name__)


class WorkloadSimulator:
    def __init__(
        self,
        store: ResourceStore,
        clock: Optional[Clock] = None,
        auto_ready: bool = True,
    ):
        self.store = store
        self.clock = clock or Clock()
        self.auto_ready = auto_ready
        store.watch(self._on_event, kinds=[DEPLOYMENT_KIND, STATEFULSET_KIND])

    def _on_event(self, ev: WatchEvent) -> None:
        if not self.auto_ready or ev.type not in (ADDED, MODIFIED):
            return
        r = ev.resource
        replicas = int(r.spec.get("replicas", 1))
        generation = int(r.spec.get("connectorGeneration", 0))
        if (
            int(r.status.get("readyReplicas", 0)) == replicas
            and int(r.status.get("observedConnectorGeneration", 0)) == generation
        ):
            return

        def patch(st) -> None:
            st["readyReplicas"] = replicas
            st["availableReplicas"] = replicas
            if generation:
                st["observedConnectorGeneration"] = generation
            st.setdefault("startedAt", self.clock.now())

        try:
            self.store.patch_status(r.kind, r.meta.namespace, r.meta.name, patch)
        except NotFound:
            pass

    def mark_ready(self, kind: str, namespace: str, name: str,
                   ready: bool = True) -> None:
        """Manual control for tests exercising readiness transitions."""
        r = self.store.get(kind, namespace, name)
        replicas = int(r.spec.get("replicas", 1))

        def patch(st) -> None:
            st["readyReplicas"] = replicas if ready else 0

        self.store.patch_status(kind, namespace, name, patch)
