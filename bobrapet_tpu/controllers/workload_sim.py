"""Local workload simulator — the "kubelet" for long-running workloads,
plus the fleet chaos half: spot-preemption fault injection.

The reference relies on real kubelets to bring Deployments/StatefulSets
up; its envtest suites simulate that by patching status
(reference: SURVEY §4 — "tests patch Job/StepRun status to simulate SDK
and kubelet behavior"). This simulator plays the same role for the local
runtime: it watches Deployment/StatefulSet records and marks them ready
(readyReplicas = replicas, observedConnectorGeneration synced), which
drives realtime StepRuns from Pending to Running. On GKE this module is
replaced by actual kubelets; nothing above it changes.

:class:`PreemptionInjector` is the GKE spot reclaimer's stand-in: wired
into the gang executor, it picks gang hosts to kill mid-step
(cooperative SIGTERM + preemption notice), which drives the fleet
subsystem's quarantine / cordon-aware re-place / checkpoint-resume
machinery end to end in tests (the ``chaos`` pytest suite).

Disable (``auto_ready=False``) to exercise Pending/handoff states in
tests.
"""

from __future__ import annotations

import logging
import random
from typing import Any, Optional

from ..core.store import ADDED, MODIFIED, ResourceStore, NotFound, WatchEvent
from .manager import Clock
from .streaming import DEPLOYMENT_KIND, STATEFULSET_KIND

_log = logging.getLogger(__name__)


class PreemptionInjector:
    """Seeded fault plan: preempt a fraction of slice-granted gangs.

    ``plan(job)`` is consulted once per gang launch (so a redriven
    attempt re-rolls — repeated preemptions of the same step are
    possible, exactly like real spot capacity). A plan names one victim
    host and a fuse length in cooperative deadline polls; hosts that
    never poll ride out the plan unharmed, matching a workload that
    ignores SIGTERM until the hard kill.
    """

    def __init__(
        self,
        rate: float = 0.1,
        seed: int = 0,
        min_hosts: int = 2,
        max_polls: int = 3,
    ):
        self.rate = rate
        self.min_hosts = min_hosts
        self.max_polls = max(1, max_polls)
        self.rng = random.Random(seed)
        self.planned = 0

    def plan(self, job) -> Optional[dict[str, Any]]:
        hosts = int(job.spec.get("hosts") or 1)
        if hosts < self.min_hosts or not job.spec.get("sliceGrant"):
            return None
        if self.rng.random() >= self.rate:
            return None
        self.planned += 1
        return {
            "host": self.rng.randrange(hosts),
            "afterPolls": self.rng.randint(1, self.max_polls),
        }


class WorkloadSimulator:
    """Plays kubelet + readiness probe for long-running workloads.

    Readiness is modeled PER GENERATION: observing a new connector
    generation (spec seen, new pods scheduled) is distinct from that
    generation being READY (readiness probe passing — for a TPU engram
    that means the model is compiled and warm). Streaming cutover gates
    on ``readyGeneration``, not observation (SURVEY §7 hard parts:
    "cutover must wait for compiled-model readiness").

    ``warmup_seconds`` simulates compile/warmup latency: a new
    generation is observed immediately but reports ready only after the
    warmup elapses. ``hold_readiness`` freezes readiness entirely for
    tests that drive it manually via :meth:`mark_generation_ready`.
    """

    CONTROLLER = "workload-sim"

    def __init__(
        self,
        store: ResourceStore,
        clock: Optional[Clock] = None,
        auto_ready: bool = True,
        warmup_seconds: float = 0.0,
        hold_readiness: bool = False,
    ):
        self.store = store
        self.clock = clock or Clock()
        self.auto_ready = auto_ready
        self.warmup_seconds = warmup_seconds
        self.hold_readiness = hold_readiness
        self._manager = None
        #: (kind, ns, name, generation) -> warmup-complete time
        self._warm_at: dict[tuple[str, str, str, int], float] = {}
        store.watch(self._on_event, kinds=[DEPLOYMENT_KIND, STATEFULSET_KIND])

    def attach(self, manager) -> None:
        """Register with the reconcile manager so pending warmups
        self-complete: the simulator re-probes itself at warm_at
        instead of waiting for an unrelated watch event."""
        self._manager = manager
        manager.register(self.CONTROLLER, self._reprobe, watches={})

    def _reprobe(self, namespace: str, name: str) -> Optional[float]:
        for kind in (DEPLOYMENT_KIND, STATEFULSET_KIND):
            r = self.store.try_get(kind, namespace, name)
            if r is not None:
                self._on_event(WatchEvent(MODIFIED, r))
        return None

    def _on_event(self, ev: WatchEvent) -> None:
        if not self.auto_ready or ev.type not in (ADDED, MODIFIED):
            return
        r = ev.resource
        replicas = int(r.spec.get("replicas", 1))
        generation = int(r.spec.get("connectorGeneration", 0))
        ready_gen = self._ready_generation(r, generation)
        if ready_gen < generation and self._manager is not None and not self.hold_readiness:
            key = (r.kind, r.meta.namespace, r.meta.name, generation)
            remaining = self._warm_at.get(key, self.clock.now()) - self.clock.now()
            self._manager.enqueue(
                self.CONTROLLER, r.meta.namespace, r.meta.name,
                after=max(0.01, remaining),
            )
        if (
            int(r.status.get("readyReplicas", 0)) == replicas
            and int(r.status.get("observedConnectorGeneration", 0)) == generation
            and int(r.status.get("readyGeneration", 0)) == ready_gen
        ):
            return

        def patch(st) -> None:
            st["readyReplicas"] = replicas
            st["availableReplicas"] = replicas
            if generation:
                st["observedConnectorGeneration"] = generation
            if ready_gen:
                st["readyGeneration"] = max(
                    ready_gen, int(st.get("readyGeneration", 0))
                )
            st.setdefault("startedAt", self.clock.now())

        try:
            self.store.patch_status(r.kind, r.meta.namespace, r.meta.name, patch)
        except NotFound:
            pass

    def _ready_generation(self, r, generation: int) -> int:
        """Highest generation whose simulated readiness probe passes."""
        if self.hold_readiness:
            return int(r.status.get("readyGeneration", 0))
        if self.warmup_seconds <= 0:
            return generation
        key = (r.kind, r.meta.namespace, r.meta.name, generation)
        warm_at = self._warm_at.setdefault(
            key, self.clock.now() + self.warmup_seconds
        )
        if self.clock.now() >= warm_at:
            self._warm_at.pop(key, None)  # never consulted again
            return generation
        return int(r.status.get("readyGeneration", 0))

    def mark_ready(self, kind: str, namespace: str, name: str,
                   ready: bool = True) -> None:
        """Manual control for tests exercising readiness transitions."""
        r = self.store.get(kind, namespace, name)
        replicas = int(r.spec.get("replicas", 1))

        def patch(st) -> None:
            st["readyReplicas"] = replicas if ready else 0

        self.store.patch_status(kind, namespace, name, patch)

    def mark_generation_ready(self, kind: str, namespace: str, name: str,
                              generation: int) -> None:
        """Manual probe: generation finished compiling/warming."""
        self.store.patch_status(
            kind, namespace, name,
            lambda st: st.__setitem__(
                "readyGeneration",
                max(generation, int(st.get("readyGeneration", 0))),
            ),
        )
