"""Controller manager: watch-driven reconcile loops over the bus.

The role controller-runtime's manager plays for the reference
(reference: cmd/main.go:613-790 controller wiring; pkg/reconcile —
jittered requeue requeue.go:14, meaningful-update predicates
predicates.go:51-184): controllers declare which kinds they watch and a
reconcile function keyed by (namespace, name); events map to keys, keys
dedupe in a work queue, failures requeue with exponential backoff +
jitter, and ``requeue_after`` timers park keys until due.

Determinism for tests comes from an injectable clock: with a
:class:`ManualClock`, :meth:`run_until_quiet` advances virtual time to
the next due timer whenever the queue is idle, so sleep/gate/retry logic
runs instantly — the envtest analogue (SURVEY §4).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import random
import threading
import time
from typing import Callable, Iterable, Optional

from ..core.store import ResourceStore, WatchEvent
from ..observability.metrics import metrics

_log = logging.getLogger(__name__)


class Clock:
    """Wall clock; swap for ManualClock in tests."""

    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class ManualClock(Clock):
    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self._now += seconds

    def advance(self, seconds: float) -> None:
        self._now += seconds

    def advance_to(self, t: float) -> None:
        self._now = max(self._now, t)


#: A reconcile function: (namespace, name) -> optional requeue delay (s).
ReconcileFn = Callable[[str, str], Optional[float]]
#: Maps a watch event to the primary keys to reconcile.
MapperFn = Callable[[WatchEvent], Iterable[tuple[str, str]]]


def default_mapper(ev: WatchEvent) -> Iterable[tuple[str, str]]:
    return [(ev.resource.meta.namespace, ev.resource.meta.name)]


def owner_mapper(owner_kind: str) -> MapperFn:
    """Map child events to their controller-owner of the given kind
    (the reference's Owns() watches)."""

    def fn(ev: WatchEvent) -> Iterable[tuple[str, str]]:
        return [
            (ev.resource.meta.namespace, o.name)
            for o in ev.resource.meta.owner_references
            if o.kind == owner_kind
        ]

    return fn


@dataclasses.dataclass(order=True)
class _Timer:
    due: float
    seq: int
    key: tuple[str, str, str] = dataclasses.field(compare=False)  # (controller, ns, name)


class ControllerManager:
    """Single-dispatcher reconcile engine.

    Keys are processed on the calling thread of :meth:`run_until_quiet`
    (tests) or a dispatcher thread (:meth:`start`). Reconcilers therefore
    never race each other — matching the reference's default
    MaxConcurrentReconciles=1 per controller semantics, with cross-
    controller ordering serialized for determinism.
    """

    def __init__(
        self,
        store: ResourceStore,
        clock: Optional[Clock] = None,
        requeue_base_delay: float = 0.05,
        requeue_max_delay: float = 30.0,
        max_failures_logged: int = 10,
    ):
        self.store = store
        self.clock = clock or Clock()
        self._controllers: dict[str, ReconcileFn] = {}
        self._queue: list[tuple[str, str, str]] = []
        self._queued: set[tuple[str, str, str]] = set()
        self._timers: list[_Timer] = []
        self._timer_seq = 0
        self._failures: dict[tuple[str, str, str], int] = {}
        self._lock = threading.Lock()
        self._wakeup = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._requeue_base = requeue_base_delay
        self._requeue_max = requeue_max_delay
        self._max_failures_logged = max_failures_logged

    # -- registration ------------------------------------------------------

    def register(
        self,
        name: str,
        reconcile: ReconcileFn,
        watches: dict[str, Optional[MapperFn]],
    ) -> None:
        """Register a controller.

        watches: kind -> mapper (None = identity mapping). Every matching
        committed event enqueues the mapped keys for this controller.
        """
        self._controllers[name] = reconcile

        def on_event(ev: WatchEvent, _name=name, _watches=dict(watches)) -> None:
            mapper = _watches.get(ev.resource.kind)
            fn = mapper or default_mapper
            for ns, obj_name in fn(ev):
                self.enqueue(_name, ns, obj_name)

        self.store.watch(on_event, kinds=list(watches.keys()))

    # -- queue -------------------------------------------------------------

    def enqueue(self, controller: str, namespace: str, name: str, after: float = 0.0) -> None:
        key = (controller, namespace, name)
        with self._lock:
            if after > 0:
                self._timer_seq += 1
                heapq.heappush(
                    self._timers, _Timer(self.clock.now() + after, self._timer_seq, key)
                )
            elif key not in self._queued:
                self._queued.add(key)
                self._queue.append(key)
        self._wakeup.set()

    def _pop_due_timers_locked(self) -> None:
        now = self.clock.now()
        while self._timers and self._timers[0].due <= now:
            t = heapq.heappop(self._timers)
            if t.key not in self._queued:
                self._queued.add(t.key)
                self._queue.append(t.key)

    def _next(self) -> Optional[tuple[str, str, str]]:
        with self._lock:
            self._pop_due_timers_locked()
            if not self._queue:
                return None
            key = self._queue.pop(0)
            self._queued.discard(key)
            return key

    # -- dispatch ----------------------------------------------------------

    def _process(self, key: tuple[str, str, str]) -> None:
        controller, ns, name = key
        fn = self._controllers.get(controller)
        if fn is None:
            return
        started = time.monotonic()
        try:
            requeue_after = fn(ns, name)
            metrics.reconcile_total.inc(controller, "success")
            metrics.reconcile_duration.observe(time.monotonic() - started, controller)
            self._failures.pop(key, None)
            if requeue_after is not None and requeue_after >= 0:
                self.enqueue(controller, ns, name, after=max(requeue_after, 1e-9))
        except Exception:  # noqa: BLE001 - reconcile errors retry with backoff
            metrics.reconcile_total.inc(controller, "error")
            metrics.reconcile_duration.observe(time.monotonic() - started, controller)
            n = self._failures.get(key, 0) + 1
            self._failures[key] = n
            delay = jittered_backoff(n, self._requeue_base, self._requeue_max)
            if n <= self._max_failures_logged:
                _log.exception(
                    "reconcile %s %s/%s failed (attempt %d), requeue in %.2fs",
                    controller, ns, name, n, delay,
                )
            self.enqueue(controller, ns, name, after=delay)

    # -- test-mode pump ----------------------------------------------------

    def run_until_quiet(self, max_iterations: int = 100_000, max_virtual_seconds: float = 7 * 86400) -> int:
        """Process work until queue AND timers are exhausted.

        With a ManualClock, virtual time jumps to the next timer when the
        queue idles; with a real clock, pending timers end the pump (use
        ``start()`` for live operation). Returns iterations processed.
        """
        processed = 0
        horizon = self.clock.now() + max_virtual_seconds
        for _ in range(max_iterations):
            key = self._next()
            if key is None:
                with self._lock:
                    next_due = self._timers[0].due if self._timers else None
                if next_due is None:
                    break
                if not isinstance(self.clock, ManualClock):
                    break
                if next_due > horizon:
                    break
                self.clock.advance_to(next_due)
                continue
            self._process(key)
            processed += 1
        return processed

    # -- live mode ---------------------------------------------------------

    def is_running(self) -> bool:
        """Readiness signal for /readyz (live dispatcher up)."""
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="reconcile-dispatcher")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wakeup.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            key = self._next()
            if key is not None:
                self._process(key)
                continue
            with self._lock:
                next_due = self._timers[0].due if self._timers else None
            wait = 0.2 if next_due is None else max(0.0, min(next_due - self.clock.now(), 0.2))
            self._wakeup.wait(wait if wait > 0 else 0.001)
            self._wakeup.clear()


def jittered_backoff(attempt: int, base: float, max_delay: float, jitter: float = 0.2) -> float:
    """Exponential backoff with jitter
    (reference: pkg/reconcile/requeue.go:14 JitteredRequeueDelay)."""
    delay = min(base * (2 ** (attempt - 1)), max_delay)
    return delay * (1 + random.random() * jitter)
