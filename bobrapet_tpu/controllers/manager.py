"""Controller manager: watch-driven reconcile loops over the bus.

The role controller-runtime's manager plays for the reference
(reference: cmd/main.go:613-790 controller wiring; pkg/reconcile —
jittered requeue requeue.go:14, meaningful-update predicates
predicates.go:51-184): controllers declare which kinds they watch and a
reconcile function keyed by (namespace, name); events map to keys, keys
dedupe in a work queue, failures requeue with exponential backoff +
jitter, and ``requeue_after`` timers park keys until due.

Dispatch is per-controller (reference: ``controller.Options.
MaxConcurrentReconciles``, cmd/main.go:650-769): every controller owns a
worker pool sized by ``controllers.max-concurrent-reconciles`` (plus
``controllers.<name>.max-concurrent-reconciles`` overrides), so one slow
StepRun reconcile can no longer head-of-line-block every other
controller. Workqueue semantics are preserved exactly:

- a key is never reconciled concurrently with itself — an event
  arriving mid-reconcile marks the key *dirty* and it re-dispatches
  once the in-flight run completes (controller-runtime's
  processing-set behavior);
- queued keys dedupe; failures back off with jitter; ``requeue_after``
  timers park keys until due, popped under the shared lock and routed
  only to the pools that received work (idle pools stay asleep).

Determinism for tests comes from an injectable clock: with a
:class:`ManualClock`, :meth:`run_until_quiet` pumps every controller
serially on the calling thread — advancing virtual time to the next due
timer whenever the queue is idle — so sleep/gate/retry logic runs
instantly; the envtest analogue (SURVEY §4). The pump uses the same
active/dirty bookkeeping as the pools, so both modes share one
correctness story.
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import random
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

from ..analysis.racedetect import guarded_state
from ..core.store import ResourceStore, WatchEvent
from ..observability.metrics import metrics

_log = logging.getLogger(__name__)


class Clock:
    """Wall clock; swap for ManualClock in tests."""

    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class ManualClock(Clock):
    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self._now += seconds

    def advance(self, seconds: float) -> None:
        self._now += seconds

    def advance_to(self, t: float) -> None:
        self._now = max(self._now, t)


#: A reconcile function: (namespace, name) -> optional requeue delay (s).
ReconcileFn = Callable[[str, str], Optional[float]]
#: Maps a watch event to the primary keys to reconcile.
MapperFn = Callable[[WatchEvent], Iterable[tuple[str, str]]]


def default_mapper(ev: WatchEvent) -> Iterable[tuple[str, str]]:
    return [(ev.resource.meta.namespace, ev.resource.meta.name)]


def owner_mapper(owner_kind: str) -> MapperFn:
    """Map child events to their controller-owner of the given kind
    (the reference's Owns() watches)."""

    def fn(ev: WatchEvent) -> Iterable[tuple[str, str]]:
        return [
            (ev.resource.meta.namespace, o.name)
            for o in ev.resource.meta.owner_references
            if o.kind == owner_kind
        ]

    return fn


@dataclasses.dataclass(order=True)
class _Timer:
    due: float
    seq: int
    key: tuple[str, str, str] = dataclasses.field(compare=False)  # (controller, ns, name)


@guarded_state("queue", "queued")
class _Pool:
    """One controller's work queue + worker bookkeeping. All fields are
    guarded by the manager's shared lock; ``cond`` shares that lock so
    waking this pool cannot wake any other."""

    __slots__ = ("name", "queue", "queued", "cond", "target", "spawned",
                 "idle", "busy")

    def __init__(self, name: str, lock: threading.Lock, target: int):
        self.name = name
        #: FIFO of (global seq, enqueue monotonic time, (ns, name))
        self.queue: deque[tuple[int, float, tuple[str, str]]] = deque()
        self.queued: set[tuple[str, str]] = set()
        self.cond = threading.Condition(lock)
        self.target = target  # desired worker count
        self.spawned = 0  # live worker threads
        self.idle = 0  # workers waiting on cond
        self.busy = 0  # reconciles in flight


@guarded_state("_active", "_controllers", "_dirty", "_failures",
               "_per_controller_max", "_pools", "_registered_max", "_timers")
class ControllerManager:
    """Per-controller-pool reconcile engine (see module docstring).

    Keys are processed on the calling thread of :meth:`run_until_quiet`
    (tests; strictly serial, global-FIFO across controllers) or on the
    per-controller worker pools (:meth:`start`). In both modes the
    active/dirty sets guarantee a key never overlaps itself.
    """

    def __init__(
        self,
        store: ResourceStore,
        clock: Optional[Clock] = None,
        requeue_base_delay: float = 0.05,
        requeue_max_delay: float = 30.0,
        max_failures_logged: int = 10,
        default_max_concurrent: int = 1,
    ):
        self.store = store
        self.clock = clock or Clock()
        self._controllers: dict[str, ReconcileFn] = {}
        self._pools: dict[str, _Pool] = {}
        self._timers: list[_Timer] = []
        self._timer_seq = 0
        self._queue_seq = 0
        self._active: set[tuple[str, str, str]] = set()
        self._dirty: set[tuple[str, str, str]] = set()
        self._failures: dict[tuple[str, str, str], int] = {}
        self._lock = threading.Lock()
        self._timer_cond = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._started = False
        self._timer_thread: Optional[threading.Thread] = None
        self._requeue_base = requeue_base_delay
        self._requeue_max = requeue_max_delay
        self._max_failures_logged = max_failures_logged
        self._default_max_concurrent = max(1, int(default_max_concurrent))
        #: soft reconcile budget (controllers.reconcile-timeout): threads
        #: cannot be killed, so an overrun is detected after the fact —
        #: logged + counted so a wedged reconciler is visible in metrics
        #: before it exhausts its pool
        self._reconcile_timeout = 30.0
        self._per_controller_max: dict[str, int] = {}
        #: widths pinned by register(max_concurrent=...) — these outrank
        #: config and survive apply_config reloads
        self._registered_max: dict[str, int] = {}
        #: sharded dispatch gate (bobrapet_tpu/shard): consulted before
        #: each reconcile with (controller, ns, name); None admits,
        #: >= 0 parks the key (requeue after that delay, e.g. awaiting a
        #: rebalance barrier), < 0 drops it (another shard's work).
        #: Runs OUTSIDE the manager lock and must be cheap.
        self.reconcile_gate: Optional[Callable[[str, str, str], Optional[float]]] = None
        #: reconcile start/finish hook (duck-typed: reconcile_started /
        #: reconcile_finished, both (controller, ns, name)) — the shard
        #: double-reconcile detector rides here in tests
        self.reconcile_observer = None

    # -- registration ------------------------------------------------------

    def register(
        self,
        name: str,
        reconcile: ReconcileFn,
        watches: dict[str, Optional[MapperFn]],
        max_concurrent: Optional[int] = None,
    ) -> None:
        """Register a controller.

        watches: kind -> mapper (None = identity mapping). Every matching
        committed event enqueues the mapped keys for this controller.
        ``max_concurrent`` pins this controller's pool width; without it
        the config default / per-controller override applies.
        """
        with self._lock:
            self._controllers[name] = reconcile
            if max_concurrent is not None:
                self._registered_max[name] = max(1, int(max_concurrent))
            if name not in self._pools:
                self._pools[name] = _Pool(
                    name, self._lock, self._target_width(name)
                )
            else:
                # pool may pre-exist (auto-created by an early enqueue,
                # or a second registration sharing the name): a pinned
                # width must take effect now, not at the next reload
                self._pools[name].target = self._target_width(name)

        def on_event(ev: WatchEvent, _name=name, _watches=dict(watches)) -> None:
            mapper = _watches.get(ev.resource.kind)
            fn = mapper or default_mapper
            for ns, obj_name in fn(ev):
                self.enqueue(_name, ns, obj_name)

        if watches:
            self.store.watch(on_event, kinds=list(watches.keys()))

    def _target_width(self, name: str) -> int:
        pinned = self._registered_max.get(name)
        if pinned is not None:
            return pinned
        return self._per_controller_max.get(name, self._default_max_concurrent)

    # -- config ------------------------------------------------------------

    def apply_config(self, cfg) -> None:
        """Adopt the live ``controllers.*`` tuning (called at startup and
        on every ConfigMap reload — reference: ApplyRuntimeToggles,
        controller_config.go:176). Growing a pool spawns workers on
        demand; shrinking lets excess workers retire as they go idle."""
        tuning = cfg.controllers
        with self._lock:
            self._requeue_base = tuning.requeue_base_delay
            self._requeue_max = tuning.requeue_max_delay
            self._reconcile_timeout = max(0.0, float(tuning.reconcile_timeout))
            self._default_max_concurrent = max(
                1, int(tuning.max_concurrent_reconciles)
            )
            self._per_controller_max = {
                name: max(1, int(width))
                for name, width in (tuning.per_controller or {}).items()
            }
            for pool in self._pools.values():
                pool.target = self._target_width(pool.name)
                if self._started and pool.queue:
                    self._spawn_workers_locked(pool)
                # shrink: idle workers re-check target when notified
                pool.cond.notify_all()

    # -- queue -------------------------------------------------------------

    def enqueue(self, controller: str, namespace: str, name: str, after: float = 0.0) -> None:
        key = (controller, namespace, name)
        with self._lock:
            if after > 0:
                self._timer_seq += 1
                heapq.heappush(
                    self._timers, _Timer(self.clock.now() + after, self._timer_seq, key)
                )
                # only the timer waiter needs to recompute its sleep;
                # no worker pool has runnable work yet
                self._timer_cond.notify()
            else:
                self._enqueue_ready_locked(key)

    def _enqueue_ready_locked(self, key: tuple[str, str, str]) -> None:
        """Queue a key for immediate dispatch. MUST hold the lock.

        A key currently reconciling is marked dirty instead of queued:
        it re-dispatches exactly once after the in-flight run completes
        (controller-runtime's processing-set semantics), so the
        reconcile that follows observes the event's state."""
        if key in self._active:
            self._dirty.add(key)
            return
        controller, ns, name = key
        pool = self._pools.get(controller)
        if pool is None:
            pool = self._pools[controller] = _Pool(
                controller, self._lock, self._target_width(controller)
            )
        if (ns, name) in pool.queued:
            return
        pool.queued.add((ns, name))
        self._queue_seq += 1
        pool.queue.append((self._queue_seq, time.monotonic(), (ns, name)))
        if self._started:
            metrics.reconcile_queue_depth.set(len(pool.queue), controller)
            # one notify per enqueued key: notifies sent under the lock
            # wake DISTINCT waiters, so k keys wake k idle workers. When
            # queued work exceeds idle waiters the surplus gets real
            # threads — relying on notify alone can strand a key when
            # consecutive enqueues outnumber the waiters (each extra
            # notify is lost, and no one spawns).
            pool.cond.notify()
            if pool.idle < len(pool.queue):
                self._spawn_workers_locked(pool)

    def _pop_due_timers_locked(self) -> None:
        now = self.clock.now()
        while self._timers and self._timers[0].due <= now:
            t = heapq.heappop(self._timers)
            self._enqueue_ready_locked(t.key)

    def _pump_next_locked(self) -> Optional[tuple[str, str, str]]:
        """Serial-pump pop: the oldest queued key across all pools
        (global FIFO order, as if there were one queue)."""
        self._pop_due_timers_locked()
        best: Optional[_Pool] = None
        for pool in self._pools.values():
            if pool.queue and (best is None or pool.queue[0][0] < best.queue[0][0]):
                best = pool
        if best is None:
            return None
        _seq, _enq_t, (ns, name) = best.queue.popleft()
        best.queued.discard((ns, name))
        # no gauge/latency samples here: the serial pump runs in virtual
        # time at soak rates — dispatcher metrics are live-mode signals
        return (best.name, ns, name)

    # -- dispatch ----------------------------------------------------------

    def _process(self, key: tuple[str, str, str]) -> None:
        controller, ns, name = key
        # register() may run mid-flight (a joining shard's runtime wires
        # controllers while earlier pools already dispatch): reads of
        # the registry share its lock
        with self._lock:
            fn = self._controllers.get(controller)
        if fn is None:
            return
        gate = self.reconcile_gate
        if gate is not None:
            try:
                verdict = gate(controller, ns, name)
            except Exception:  # noqa: BLE001 - a broken gate must not kill the worker thread
                # fail CLOSED (ownership unknown -> don't reconcile;
                # running anyway could double-own the key on another
                # shard) but stay live: requeue and retry shortly
                _log.exception(
                    "reconcile gate failed for %s %s/%s; parking key",
                    controller, ns, name,
                )
                self.enqueue(controller, ns, name, after=0.1)
                return
            if verdict is not None:
                if verdict >= 0:
                    self.enqueue(controller, ns, name,
                                 after=max(verdict, 1e-9))
                return
        observer = self.reconcile_observer
        if observer is not None:
            try:
                observer.reconcile_started(controller, ns, name)
            except Exception:  # noqa: BLE001 - diagnostics must not affect dispatch
                _log.exception("reconcile observer failed (start)")
                observer = None  # keep start/finish balanced
        try:
            self._process_inner(key)
        finally:
            if observer is not None:
                try:
                    observer.reconcile_finished(controller, ns, name)
                except Exception:  # noqa: BLE001 - diagnostics must not affect dispatch
                    _log.exception("reconcile observer failed (finish)")

    def _process_inner(self, key: tuple[str, str, str]) -> None:
        controller, ns, name = key
        with self._lock:
            fn = self._controllers[controller]
        started = time.monotonic()
        try:
            requeue_after = fn(ns, name)
            metrics.reconcile_total.inc(controller, "success")
            self._observe_duration(controller, ns, name, started)
            with self._lock:
                self._failures.pop(key, None)
            if requeue_after is not None and requeue_after >= 0:
                self.enqueue(controller, ns, name, after=max(requeue_after, 1e-9))
        except Exception:  # noqa: BLE001 - reconcile errors retry with backoff
            metrics.reconcile_total.inc(controller, "error")
            self._observe_duration(controller, ns, name, started)
            # keyed serialization keeps each key's COUNT consistent, but
            # the dict itself is shared across every worker thread —
            # entries for different keys land under the manager lock
            with self._lock:
                n = self._failures.get(key, 0) + 1
                self._failures[key] = n
            delay = jittered_backoff(n, self._requeue_base, self._requeue_max)
            if n <= self._max_failures_logged:
                _log.exception(
                    "reconcile %s %s/%s failed (attempt %d), requeue in %.2fs",
                    controller, ns, name, n, delay,
                )
            self.enqueue(controller, ns, name, after=delay)

    def _observe_duration(
        self, controller: str, ns: str, name: str, started: float
    ) -> None:
        dur = time.monotonic() - started
        metrics.reconcile_duration.observe(dur, controller)
        if 0 < self._reconcile_timeout < dur:
            metrics.reconcile_overruns.inc(controller)
            _log.warning(
                "reconcile %s %s/%s took %.2fs (budget %.2fs, "
                "controllers.reconcile-timeout)",
                controller, ns, name, dur, self._reconcile_timeout,
            )

    def active_keys(self) -> list[tuple[str, str, str]]:
        """Snapshot of in-flight reconcile keys (controller, ns, name)
        — the shard coordinator's drain check reads this to decide when
        every reconcile for families it is losing has completed."""
        with self._lock:
            return list(self._active)

    def _finish_locked(self, key: tuple[str, str, str]) -> None:
        """Retire an in-flight key; a dirty mark re-queues it once."""
        self._active.discard(key)
        if key in self._dirty:
            self._dirty.discard(key)
            self._enqueue_ready_locked(key)

    # -- test-mode pump ----------------------------------------------------

    def run_until_quiet(self, max_iterations: int = 100_000, max_virtual_seconds: float = 7 * 86400) -> int:
        """Process work until queue AND timers are exhausted.

        Strictly serial on the calling thread, oldest key first across
        every controller — identical scheduling to the pre-pool
        dispatcher, so deterministic tests stay deterministic. With a
        ManualClock, virtual time jumps to the next timer when the
        queue idles; with a real clock, pending timers end the pump
        (use ``start()`` for live operation). Returns iterations
        processed.
        """
        processed = 0
        horizon = self.clock.now() + max_virtual_seconds
        for _ in range(max_iterations):
            with self._lock:
                key = self._pump_next_locked()
                if key is not None:
                    self._active.add(key)
            if key is None:
                with self._lock:
                    next_due = self._timers[0].due if self._timers else None
                if next_due is None:
                    break
                if not isinstance(self.clock, ManualClock):
                    break
                if next_due > horizon:
                    break
                self.clock.advance_to(next_due)
                continue
            try:
                self._process(key)
            finally:
                with self._lock:
                    self._finish_locked(key)
            processed += 1
        return processed

    # -- live mode ---------------------------------------------------------

    def is_running(self) -> bool:
        """Readiness signal for /readyz (live dispatcher up)."""
        return bool(
            self._started
            and self._timer_thread is not None
            and self._timer_thread.is_alive()
        )

    def start(self) -> None:
        if self._started:
            return
        self._stop.clear()
        self._started = True
        self._timer_thread = threading.Thread(
            target=self._timer_loop, daemon=True, name="reconcile-timers"
        )
        self._timer_thread.start()
        with self._lock:
            for pool in self._pools.values():
                if pool.queue:
                    self._spawn_workers_locked(pool)

    def stop(self, timeout: float = 5.0) -> None:
        if not self._started:
            return
        self._stop.set()
        self._started = False
        with self._lock:
            self._timer_cond.notify_all()
            for pool in self._pools.values():
                pool.cond.notify_all()
        if self._timer_thread is not None:
            self._timer_thread.join(timeout)
            self._timer_thread = None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if all(p.spawned == 0 for p in self._pools.values()):
                    return
            time.sleep(0.005)

    def _spawn_workers_locked(self, pool: _Pool) -> None:
        """Grow a pool toward its target, one worker per queued key at
        most (lazy: an idle controller holds no threads)."""
        want = min(pool.target, pool.spawned + len(pool.queue))
        while pool.spawned < want:
            pool.spawned += 1
            threading.Thread(
                target=self._worker_loop, args=(pool,), daemon=True,
                name=f"reconcile-{pool.name}-{pool.spawned}",
            ).start()

    def _worker_loop(self, pool: _Pool) -> None:
        while True:
            with self._lock:
                item = None
                while item is None:
                    if self._stop.is_set() or pool.spawned > pool.target:
                        pool.spawned -= 1
                        if not self._stop.is_set() and pool.queue:
                            # don't swallow a notify meant for work: hand
                            # the queued key to a surviving worker (or
                            # respawn if this was the last one)
                            if pool.idle > 0:
                                pool.cond.notify()
                            else:
                                self._spawn_workers_locked(pool)
                        return
                    if pool.queue:
                        item = pool.queue.popleft()
                        break
                    pool.idle += 1
                    try:
                        notified = pool.cond.wait(timeout=5.0)
                    finally:
                        pool.idle -= 1
                    if not notified and not pool.queue and not self._stop.is_set():
                        # idle past the grace window: retire so a quiet
                        # controller holds no threads (spawn is lazy)
                        pool.spawned -= 1
                        return
                _seq, enq_t, (ns, name) = item
                key = (pool.name, ns, name)
                pool.queued.discard((ns, name))
                self._active.add(key)
                pool.busy += 1
                metrics.reconcile_queue_depth.set(len(pool.queue), pool.name)
                metrics.reconcile_busy_workers.set(pool.busy, pool.name)
            metrics.reconcile_queue_latency.observe(
                time.monotonic() - enq_t, pool.name
            )
            try:
                self._process(key)
            finally:
                with self._lock:
                    pool.busy -= 1
                    metrics.reconcile_busy_workers.set(pool.busy, pool.name)
                    self._finish_locked(key)

    def _timer_loop(self) -> None:
        """Pop due timers under the shared lock and route their keys to
        the owning pools — enqueue notifies exactly the pools that
        received work, so idle pools never wake on a foreign timer."""
        while not self._stop.is_set():
            with self._lock:
                self._pop_due_timers_locked()
                next_due = self._timers[0].due if self._timers else None
                wait = 0.2 if next_due is None else max(
                    0.001, min(next_due - self.clock.now(), 0.2)
                )
                self._timer_cond.wait(wait)


def jittered_backoff(attempt: int, base: float, max_delay: float, jitter: float = 0.2) -> float:
    """Exponential backoff with jitter
    (reference: pkg/reconcile/requeue.go:14 JitteredRequeueDelay)."""
    delay = min(base * (2 ** (attempt - 1)), max_delay)
    return delay * (1 + random.random() * jitter)
