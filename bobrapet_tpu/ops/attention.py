"""Causal attention: Pallas flash-attention TPU kernel + XLA reference.

Where the FLOPs live. The Pallas kernel is an online-softmax (flash)
blockwise attention: one q block stays in VMEM while k/v stream through
it, so the S x S score matrix never touches HBM. GQA maps each query
head to its kv head in the BlockSpec index map (no repeat/materialize).
Long-context goes through :mod:`bobrapet_tpu.parallel.ring_attention`,
which wraps this kernel per-shard and rotates kv blocks over the ICI
ring.

Tests run the kernel in interpret mode on CPU; on TPU it compiles to
MXU matmuls with fp32 accumulation.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: int = 0,
    sm_scale: float | None = None,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Plain XLA attention with GQA.

    q: [B, Sq, Hq, D]; k/v: [B, Sk, Hkv, D]. q_offset shifts query
    positions for decode (q token i sits at absolute position
    q_offset + i). kv_mask [B, Sk] marks valid keys (padding keys get
    -inf bias so they cannot contaminate any query's context).
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    group = hq // hkv
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if group > 1:
        kf = jnp.repeat(kf, group, axis=2)
        vf = jnp.repeat(vf, group, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf)
    if causal:
        q_pos = q_offset + jnp.arange(sq)
        k_pos = jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    if kv_mask is not None:
        scores = jnp.where(
            kv_mask.astype(bool)[:, None, None, :], scores, NEG_INF
        )
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    return out.astype(q.dtype)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int, causal: bool, sm_scale: float
):
    # shapes: q_ref [1, block_q, 1, D]; k_ref/v_ref [1, Sk, 1, D]
    qi = pl.program_id(2)
    d = q_ref.shape[-1]
    sk = k_ref.shape[1]
    q = q_ref[0, :, 0, :].astype(jnp.float32) * sm_scale
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), 0, :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    num_kb = sk // block_k
    if causal:
        # tight bound: k blocks 0..ceil((qi+1)*block_q / block_k)-1
        upper = jnp.minimum(num_kb, ((qi + 1) * block_q + block_k - 1) // block_k)
    else:
        upper = num_kb
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, :, 0, :] = (acc / l[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "sm_scale", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Blockwise flash attention. q: [B, Sq, Hq, D], k/v: [B, Sk, Hkv, D]."""
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q != 0 or sk % block_k != 0:
        # ragged shapes take the XLA path rather than padded kernels
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)

    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        sm_scale=scale,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(b, hq, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, 1, d), lambda bi, h, i: (bi, i, h, 0)),
            pl.BlockSpec((1, sk, 1, d), lambda bi, h, i, _g=group: (bi, 0, h // _g, 0)),
            pl.BlockSpec((1, sk, 1, d), lambda bi, h, i, _g=group: (bi, 0, h // _g, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, 1, d), lambda bi, h, i: (bi, i, h, 0)),
        interpret=interpret,
    )(q, k, v)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: int = 0,
    sm_scale: float | None = None,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Dispatch: flash kernel on TPU for aligned prefill shapes, XLA
    reference otherwise (decode with q_offset always takes the XLA path —
    a 1-token query is bandwidth-bound, not kernel-bound). A kv_mask
    (padding validity) forces the XLA path; padded encoder batches are
    short and the masked softmax fuses fine."""
    if (
        kv_mask is None
        and jax.default_backend() == "tpu"
        and q_offset == 0
        and q.shape[1] >= 128
        and q.shape[1] % 128 == 0
        and k.shape[1] % 128 == 0
    ):
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return attention_reference(
        q, k, v, causal=causal, q_offset=q_offset, sm_scale=sm_scale,
        kv_mask=kv_mask,
    )
