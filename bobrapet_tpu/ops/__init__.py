"""Hot ops: Pallas TPU kernels with XLA references."""

from .attention import attention, attention_reference, flash_attention
from .rmsnorm import rmsnorm, rmsnorm_pallas, rmsnorm_reference
from .rope import apply_rope, rope_frequencies

__all__ = [
    "attention",
    "attention_reference",
    "flash_attention",
    "rmsnorm",
    "rmsnorm_pallas",
    "rmsnorm_reference",
    "apply_rope",
    "rope_frequencies",
]
