"""Rotary position embeddings (RoPE) for the Llama family.

Pure XLA: RoPE is elementwise and fuses into the surrounding
projections; a hand kernel buys nothing here (the MXU work is in the
matmuls around it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(
    jax.jit, static_argnames=("dim", "max_seq_len", "theta", "scaling")
)
def rope_frequencies(
    dim: int,
    max_seq_len: int,
    theta: float = 500_000.0,
    scaling: tuple[float, float, float, int] | None = None,
) -> jax.Array:
    """Complex rotation table [max_seq_len, dim//2] as (cos, sin) stacked.

    theta=500k is the Llama-3 base. ``scaling`` is the Llama-3.1
    long-context frequency remap ``(factor, low_freq_factor,
    high_freq_factor, original_max_position_embeddings)``: wavelengths
    beyond the original context divide by ``factor``, short wavelengths
    stay, the band between interpolates smoothly (the published llama3
    rope_type; matches transformers' implementation).
    """
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    if scaling is not None:
        factor, low_f, high_f, orig_len = scaling
        wavelen = 2.0 * jnp.pi / inv_freq
        low_wavelen = orig_len / low_f
        high_wavelen = orig_len / high_f
        smooth = (orig_len / wavelen - low_f) / (high_f - low_f)
        interpolated = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen < high_wavelen,
            inv_freq,
            jnp.where(wavelen > low_wavelen, inv_freq / factor, interpolated),
        )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [S, dim/2]
    return jnp.stack([jnp.cos(freqs), jnp.sin(freqs)], axis=-1)  # [S, dim/2, 2]


def apply_rope(x: jax.Array, freqs: jax.Array, positions: jax.Array | None = None) -> jax.Array:
    """Rotate q/k.

    x: [..., S, H, D]; freqs: [max_S, D/2, 2]; positions: [..., S] absolute
    positions (defaults to arange — pass real positions for decode).
    """
    seq_len = x.shape[-3]
    if positions is None:
        table = freqs[:seq_len]  # [S, D/2, 2]
    else:
        table = freqs[positions]  # [..., S, D/2, 2]
    cos = table[..., 0][..., :, None, :]  # [..., S, 1, D/2]
    sin = table[..., 1][..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)
