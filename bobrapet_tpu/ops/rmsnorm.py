"""RMSNorm: Pallas TPU kernel + XLA reference.

The hot normalization op for the Llama family. The Pallas path keeps the
row in VMEM and fuses square-mean, rsqrt, and the weight multiply in one
pass (one HBM read + one write per element); the reference path lets XLA
fuse, which it does well — the kernel mainly wins when fused into longer
chains on real TPUs. Tests run the kernel in interpret mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def rmsnorm_reference(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """y = x / rms(x) * w computed in fp32, cast back to x.dtype."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * weight


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[:] = (x * scale * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "block_rows", "interpret"))
def rmsnorm_pallas(
    x: jax.Array,
    weight: jax.Array,
    eps: float = 1e-5,
    block_rows: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Pallas RMSNorm over the last axis; leading axes are flattened into
    a row grid."""
    orig_shape = x.shape
    d = x.shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    # grid must tile evenly; fall back to one block when it doesn't
    if rows % block_rows != 0:
        block_rows = rows
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, weight)
    return out.reshape(orig_shape)


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Dispatch: Pallas on TPU, XLA reference elsewhere."""
    if jax.default_backend() == "tpu":
        return rmsnorm_pallas(x, weight, eps=eps)
    return rmsnorm_reference(x, weight, eps)
