"""Durability for the store service: append-only journal + snapshots.

The write path mirrors etcd's WAL discipline scaled to one box:

- Every commit appends one JSON line to ``journal.jsonl`` *while the
  store's commit lock is held*, so journal order is exactly commit
  order.
- fsync is **group-committed**: a single worker thread makes pending
  records durable in batches (``store.journal-fsync-batch`` caps how
  many records may share one fsync; 1 = per-record fsync baseline).
  There is no artificial wait window — the worker syncs whatever is
  pending the moment it wakes, so batches form naturally under load
  and latency stays one fsync under none.
- Durability precedes visibility: :class:`DurableResourceStore` blocks
  in ``_drain`` until its commit's journal record is durable, so no
  watcher (and no store-service response) ever observes a write that a
  crash could lose.
- Periodic **snapshot+truncate** bounds replay: under the commit lock
  the full object set is written to ``snapshot.json`` (tmp + fsync +
  rename) and the journal truncated. Crash between the two is safe:
  replaying a journal onto the snapshot of its own final state is
  convergent (puts overwrite, dels are idempotent, order preserved).
- Recovery (:func:`load_state`) loads the snapshot, replays the whole
  journal in order, and tolerates a torn final line (the only record a
  crash mid-append can damage).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from ..analysis.racedetect import guarded_state
from ..core.object import Resource
from ..core.store import ResourceStore

_log = logging.getLogger(__name__)

JOURNAL_FILE = "journal.jsonl"
SNAPSHOT_FILE = "snapshot.json"

#: Default records-per-fsync cap (the ``store.journal-fsync-batch`` knob).
DEFAULT_FSYNC_BATCH = 64
#: Default journal records between snapshot+truncate compactions.
DEFAULT_SNAPSHOT_EVERY = 4096


@guarded_state("_pending")
class Journal:
    """Append-only journal with a group-commit fsync worker.

    ``append`` is cheap (encode + enqueue under the condition) and
    returns a sequence number; ``wait_durable(seq)`` blocks until that
    record has been fsynced. The worker writes and syncs at most
    ``fsync_batch`` records per fsync, so the knob trades commit
    latency against fsyncs/second honestly in both directions.
    """

    def __init__(self, path: str, fsync_batch: int = DEFAULT_FSYNC_BATCH):
        self.path = path
        # explicit lock under the Condition so the lock-order/race
        # sanitizers track it (a bare Condition() allocates its RLock
        # inside stdlib threading, which they deliberately skip)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: deque[bytes] = deque()
        self._seq = 0        # last sequence handed out by append()
        self._durable = 0    # last sequence known fsynced
        self._batch = max(1, int(fsync_batch))
        self._closed = False
        #: first live-file write/fsync failure; once set, the journal can
        #: no longer promise durability and every append/wait fails loud
        self._error: Optional[Exception] = None
        self._file = open(path, "ab")
        self._worker = threading.Thread(
            target=self._fsync_loop, name="journal-fsync", daemon=True
        )
        self._worker.start()

    # -- write side --------------------------------------------------------
    def append(self, record: dict[str, Any]) -> int:
        """Enqueue one record; returns its sequence number."""
        line = json.dumps(record, separators=(",", ":")).encode("utf-8") + b"\n"
        with self._cond:
            if self._closed:
                raise RuntimeError("journal is closed")
            if self._error is not None:
                raise RuntimeError(
                    f"journal write failed: {self._error}"
                ) from self._error
            self._seq += 1
            self._pending.append(line)
            self._cond.notify_all()
            return self._seq

    def wait_durable(self, seq: int, timeout: Optional[float] = None) -> None:
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while self._durable < seq:
                if self._error is not None:
                    # The fsync worker hit a genuine I/O failure on the
                    # live file: this record may never have reached disk.
                    # Failing here keeps "durability precedes visibility"
                    # honest — the commit is reported as an error, never
                    # acked as durable.
                    raise RuntimeError(
                        f"journal write failed: {self._error}"
                    ) from self._error
                if self._closed:
                    # reset()/close() account for every outstanding seq
                    # before flipping state, so this is unreachable in
                    # normal operation — fail loud rather than hang.
                    raise RuntimeError("journal closed below awaited seq")
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(f"journal record {seq} not durable in time")
                self._cond.wait(remaining)

    def set_fsync_batch(self, n: int) -> None:
        """Live-reload seam for ``store.journal-fsync-batch``."""
        with self._cond:
            self._batch = max(1, int(n))
            self._cond.notify_all()

    @property
    def fsync_batch(self) -> int:
        return self._batch

    @property
    def durable_seq(self) -> int:
        with self._cond:
            return self._durable

    # -- compaction --------------------------------------------------------
    def reset(self) -> None:
        """Truncate after a snapshot superseded every journaled record.

        Pending (not yet fsynced) records are dropped: the snapshot that
        triggered the reset was taken under the store's commit lock, so
        it already contains their effects durably. Waiters are released
        by advancing ``_durable`` to ``_seq``.
        """
        with self._cond:
            self._pending.clear()
            self._file.close()
            self._file = open(self.path, "wb")
            os.fsync(self._file.fileno())
            self._durable = self._seq
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=5.0)
        with self._cond:
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
            except (OSError, ValueError):
                pass
            try:
                self._file.close()
            except OSError:
                # close() flushes too; a file that already failed its
                # fsync may refuse even that
                pass

    # -- fsync worker ------------------------------------------------------
    def _fsync_loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending and self._closed:
                    return
                batch = []
                while self._pending and len(batch) < self._batch:
                    batch.append(self._pending.popleft())
                file = self._file
            failure: Optional[Exception] = None
            try:
                file.write(b"".join(batch))
                file.flush()
                os.fsync(file.fileno())
            except (OSError, ValueError) as e:
                failure = e
            with self._cond:
                if file is self._file:
                    if failure is not None:
                        # Genuine live-file write/fsync failure (ENOSPC,
                        # EIO, …): this batch never reached disk. Marking
                        # it durable would ack committed-and-lost records,
                        # so fail the journal loudly instead — appenders
                        # and durability waiters all raise from here on.
                        self._error = failure
                        self._cond.notify_all()
                        _log.critical(
                            "journal %s write/fsync failed; failing all "
                            "durability waiters: %s", self.path, failure,
                        )
                        return
                    self._durable += len(batch)
                # else: reset() swapped the file mid-batch — a failure on
                # the retired fd is benign, and either way the snapshot
                # that triggered the reset owns these records' durability
                # (reset already advanced _durable past them).
                self._cond.notify_all()
            try:
                from ..observability.metrics import metrics

                metrics.store_journal_fsync_batch.observe(len(batch))
            except Exception:  # pragma: no cover - metrics must never kill fsync
                pass


# -- snapshot + recovery ---------------------------------------------------
def write_snapshot(data_dir: str, objects: list[dict[str, Any]], rv: int) -> None:
    """Atomically publish ``snapshot.json`` (tmp + fsync + rename +
    directory fsync), the state all journal replay starts from."""
    path = os.path.join(data_dir, SNAPSHOT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"rv": rv, "objects": objects}, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dirfd = os.open(data_dir, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def load_state(
    data_dir: str,
) -> tuple[dict[tuple[str, str, str], Resource], int, int, float]:
    """Recover (objects, rv, replayed_records, duration_seconds).

    Replays the *entire* journal onto the snapshot: a crash between
    snapshot publish and journal truncate leaves records the snapshot
    already contains, and replaying a history onto its own final state
    converges (puts overwrite, dels tolerate absence). A torn final
    line — the one record an append-time crash can damage — is dropped.
    """
    t0 = time.monotonic()
    objects: dict[tuple[str, str, str], Resource] = {}
    rv = 0
    snap_path = os.path.join(data_dir, SNAPSHOT_FILE)
    if os.path.exists(snap_path):
        with open(snap_path) as f:
            snap = json.load(f)
        rv = int(snap["rv"])
        for d in snap["objects"]:
            obj = Resource.from_dict(d)
            objects[obj.key] = obj
    replayed = 0
    journal_path = os.path.join(data_dir, JOURNAL_FILE)
    if os.path.exists(journal_path):
        with open(journal_path, "rb") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    break  # torn tail: crash mid-append
                if rec["op"] == "put":
                    obj = Resource.from_dict(rec["obj"])
                    objects[obj.key] = obj
                else:
                    objects.pop(tuple(rec["key"]), None)
                rv = max(rv, int(rec.get("rv", 0)))
                replayed += 1
    duration = time.monotonic() - t0
    return objects, rv, replayed, duration


class DurableResourceStore(ResourceStore):
    """A :class:`ResourceStore` whose commits survive ``kill -9``.

    Hooks the store's own ``_persist``/``_unpersist`` seam (called at
    every commit site with the lock held) to journal in commit order,
    and overrides ``_drain`` so durability precedes visibility: the
    drainer blocks on the group-commit barrier before any watcher —
    and therefore any store-service response or watch frame — sees the
    write.
    """

    def __init__(
        self,
        data_dir: str,
        fsync_batch: int = DEFAULT_FSYNC_BATCH,
        snapshot_every: int = DEFAULT_SNAPSHOT_EVERY,
    ):
        super().__init__()
        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        self._snapshot_every = max(1, int(snapshot_every))
        self._records = 0  # journal records since last snapshot
        self._tls = threading.local()  # per-writer (last seq, commit t0)
        objects, rv, replayed, duration = load_state(data_dir)
        # Pre-publication: no watchers or indexes exist yet, so the
        # recovered state installs directly; add_index backfills later.
        with self._lock:
            self._objects.update(objects)
            self._rv_counter = rv
        self.replayed_records = replayed
        self.replay_duration = duration
        self._journal = Journal(
            os.path.join(data_dir, JOURNAL_FILE), fsync_batch=fsync_batch
        )
        if replayed and duration > 0:
            try:
                from ..observability.metrics import metrics

                metrics.store_journal_replay_rate.set(replayed / duration)
            except Exception:  # pragma: no cover
                pass

    # -- journaling commit hooks (store lock held) -------------------------
    def _persist(self, obj: Resource) -> None:
        seq = self._journal.append(
            {"op": "put", "rv": obj.meta.resource_version, "obj": obj.to_dict()}
        )
        self._note_seq(seq)

    def _unpersist(self, obj: Resource) -> None:
        # Stamp dels with the current counter so recovery restores the
        # exact rv even when the last commit was a finalizer-completed
        # removal (which bumps the counter without a put record).
        seq = self._journal.append(
            {"op": "del", "rv": self._rv_counter, "key": list(obj.key)}
        )
        self._note_seq(seq)

    def _note_seq(self, seq: int) -> None:
        tls = self._tls
        if getattr(tls, "seq", None) is None:
            tls.t0 = time.monotonic()
        tls.seq = seq
        self._records += 1

    # -- durability barrier ------------------------------------------------
    def _barrier(self) -> None:
        """Block until this thread's last commit is fsynced (no-op for
        threads that have not written since their last barrier)."""
        tls = self._tls
        seq = getattr(tls, "seq", None)
        if seq is None:
            return
        tls.seq = None
        self._journal.wait_durable(seq)
        try:
            from ..observability.metrics import metrics

            metrics.store_journal_append_latency.observe(
                time.monotonic() - tls.t0
            )
        except Exception:  # pragma: no cover
            pass
        if self._records >= self._snapshot_every:
            self.snapshot()

    def _drain(self) -> None:
        self._barrier()
        super()._drain()

    # -- snapshot + introspection ------------------------------------------
    def snapshot(self) -> None:
        """Snapshot+truncate under the commit lock discipline: the
        object set is serialized inside the store's critical section so
        the snapshot is a real commit-order point, then the journal is
        truncated (its records are all <= the snapshot by lock order)."""
        t0 = time.monotonic()
        with self._lock:
            if self._records == 0:
                return
            objs = [
                self._objects[k].to_dict() for k in sorted(self._objects.keys())
            ]
            rv = self._rv_counter
            write_snapshot(self.data_dir, objs, rv)
            self._journal.reset()
            self._records = 0
        try:
            from ..observability.metrics import metrics

            metrics.store_journal_snapshot_duration.observe(
                time.monotonic() - t0
            )
        except Exception:  # pragma: no cover
            pass

    def dump(self) -> bytes:
        """Canonical bytes of the full store state — the byte-identity
        probe the crash-recovery soak compares across replay."""
        with self._lock:
            state = {
                "rv": self._rv_counter,
                "objects": [
                    self._objects[k].to_dict() for k in sorted(self._objects.keys())
                ],
            }
        return json.dumps(state, sort_keys=True, separators=(",", ":")).encode("utf-8")

    def close(self) -> None:
        self._journal.close()


def dump_recovered(data_dir: str) -> bytes:
    """Offline replay → canonical bytes (same encoding as
    :meth:`DurableResourceStore.dump`) without starting a journal."""
    objects, rv, _, _ = load_state(data_dir)
    state = {
        "rv": rv,
        "objects": [objects[k].to_dict() for k in sorted(objects.keys())],
    }
    return json.dumps(state, sort_keys=True, separators=(",", ":")).encode("utf-8")
