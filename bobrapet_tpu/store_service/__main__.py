"""Store-service process entrypoint.

    python -m bobrapet_tpu.store_service --socket /run/bobra.sock \
        --data-dir /var/lib/bobra [--fsync-batch N] [--snapshot-every N]

Owns the durable store, serves every shard manager, and runs an
OperatorConfigManager over its OWN store so ``store.journal-fsync-batch``
/ ``store.snapshot-every-records`` live-reload from the same ConfigMap
resource the shard processes read — one config plane, no side channel.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from ..config.operator import CONFIG_MAP_KIND, OperatorConfigManager
from .journal import (
    DEFAULT_FSYNC_BATCH,
    DEFAULT_SNAPSHOT_EVERY,
    DurableResourceStore,
)
from .service import StoreService

_log = logging.getLogger("bobrapet_tpu.store_service")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m bobrapet_tpu.store_service")
    parser.add_argument("--socket", required=True, help="Unix socket path to serve on")
    parser.add_argument("--data-dir", required=True, help="journal + snapshot directory")
    parser.add_argument("--fsync-batch", type=int, default=DEFAULT_FSYNC_BATCH)
    parser.add_argument("--snapshot-every", type=int, default=DEFAULT_SNAPSHOT_EVERY)
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s store-service %(levelname)s %(name)s: %(message)s",
    )

    store = DurableResourceStore(
        args.data_dir,
        fsync_batch=args.fsync_batch,
        snapshot_every=args.snapshot_every,
    )
    if store.replayed_records:
        _log.info(
            "recovered %d journal records in %.3fs (rv=%d, %d objects)",
            store.replayed_records, store.replay_duration,
            store._rv_counter, len(store),
        )
    service = StoreService(store, args.socket).start()

    manager = OperatorConfigManager(store)

    def apply_store_config(cfg) -> None:
        store._journal.set_fsync_batch(cfg.store.journal_fsync_batch)
        store._snapshot_every = max(1, cfg.store.snapshot_every_records)

    manager.subscribe(apply_store_config)
    # A ConfigMap recovered from the journal was swapped in before the
    # subscription existed — apply it once, explicitly. CLI flags stand
    # only while no operator-config resource does.
    if store.try_get_view(CONFIG_MAP_KIND, "bobrapet-system", "operator-config"):
        apply_store_config(manager.config)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    _log.info("serving on %s (data in %s)", args.socket, args.data_dir)
    stop.wait()
    service.close()
    store.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
