"""StoreBackend: the seam selecting in-process vs service-backed stores.

Unit tests (and every existing call site) keep the zero-setup
in-process :class:`~..core.store.ResourceStore`; the process harness
sets ``BOBRA_STORE_BACKEND=service`` (+ ``BOBRA_STORE_SOCKET``) in
child processes so the same construction path yields a
:class:`.client.StoreClient` against the shared store service.
"""

from __future__ import annotations

import enum
import os
from typing import Optional

from ..core.store import ResourceStore, StoreError

ENV_BACKEND = "BOBRA_STORE_BACKEND"
ENV_SOCKET = "BOBRA_STORE_SOCKET"


class StoreBackend(str, enum.Enum):
    INPROC = "inproc"
    SERVICE = "service"


def make_store(
    backend: Optional[str] = None,
    socket_path: Optional[str] = None,
    **kwargs,
):
    """Build the store the current process should coordinate through.

    ``backend`` defaults to ``$BOBRA_STORE_BACKEND`` then "inproc";
    "service" requires a socket path (argument or
    ``$BOBRA_STORE_SOCKET``). Extra kwargs pass through to the chosen
    constructor.
    """
    chosen = backend or os.environ.get(ENV_BACKEND) or StoreBackend.INPROC.value
    if chosen == StoreBackend.INPROC.value:
        return ResourceStore(**kwargs)
    if chosen == StoreBackend.SERVICE.value:
        path = socket_path or os.environ.get(ENV_SOCKET)
        if not path:
            raise StoreError(
                "service store backend needs a socket path "
                f"(argument or ${ENV_SOCKET})"
            )
        from .client import StoreClient

        return StoreClient(path, **kwargs)
    raise StoreError(f"unknown store backend {chosen!r}")
