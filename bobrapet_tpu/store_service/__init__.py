"""The store service: the coordination bus as a real OS process.

The reference architecture is controller-runtime over etcd — N manager
*processes* reconciling against one durable, watch-filtered API server.
This package is that split for the in-repo bus (``core/store.py``):

- :mod:`wire` — a thin length-prefixed JSON codec over a Unix domain
  socket (one frame = one request / response / watch event).
- :mod:`journal` — append-only journal with group-committed fsync
  batching, periodic snapshot+truncate under the store's commit lock
  discipline, and crash-recovery replay (``DurableResourceStore``).
- :mod:`service` — the store-service process: owns the authoritative
  ``ResourceStore``, serves get/list/commit/watch per session, and
  evaluates the PR-6 per-watcher watch filters SERVER-side
  (``shard.router.router_from_spec``) so each shard process only
  receives events for run families it owns. The bus-wide scheduling
  gate (named-queue caps) is served here too, so check-then-reserve
  still serializes across ALL shard processes.
- :mod:`client` — ``StoreClient``, a shim implementing the existing
  store surface so Runtime/manager/dag code runs unmodified over the
  wire; admission (defaulters/validators) runs client-side where the
  registered callables live.
- :mod:`backend` — the ``StoreBackend`` seam selecting in-process
  (default, unit tests) vs service-backed stores.

``python -m bobrapet_tpu.store_service --socket S --data-dir D`` runs
the service (``__main__``); ``shard/procharness.py`` spawns it plus one
OS process per shard for the process-mode harness.
"""

from .backend import StoreBackend, make_store  # noqa: F401
from .client import StoreClient  # noqa: F401
from .journal import DurableResourceStore, Journal  # noqa: F401
from .service import StoreService  # noqa: F401
