"""The store-service process: the authoritative store behind a socket.

One process owns the :class:`~..core.store.ResourceStore` (durable via
:class:`.journal.DurableResourceStore`) and serves every shard manager
over a Unix domain socket. Three things deliberately live HERE rather
than in the client shim, because they cannot or must not cross the
wire:

- **watch filters** — each session may push its shard router's ring
  spec (``set_filter``); the service rebuilds the router
  (:func:`~..shard.router.router_from_spec`) and evaluates
  ``router.wants`` inside the store's own per-watcher fan-out, so a
  shard process only ever RECEIVES events for run families it owns —
  the PR-6 delivery partition, now saving socket bytes instead of just
  dispatcher wakeups.
- **the scheduling gate** — named-queue caps are bus-wide admission
  invariants, so the check-then-reserve window must serialize across
  ALL shard processes. :class:`_RemoteGate` serves the PR-1
  (lock, reservations) pair with per-session delta tracking: a shard
  killed between reserve and launch has its net reservations rolled
  back, so caps neither over-admit nor leak shut.
- **field indexes + shard admission** — index functions and the
  ShardMap fence validator run where the objects live
  (``register_core_indexes`` / ``register_shard_admission`` at boot),
  keeping list/count O(bucket) and fence checks atomic with the
  commit.

Per session: a reader thread dispatches requests serially (matching
the in-process one-caller-at-a-time feel), EXCEPT ``gate_acquire``
which blocks arbitrarily long and gets a one-off thread; a writer
thread drains the watch-event queue, serializing resources off the
store drainer's critical path.
"""

from __future__ import annotations

import base64
import logging
import os
import socket
import threading
from collections import deque
from typing import Any, Optional

from ..analysis.racedetect import guarded_state
from ..core.object import Resource
from ..core.store import (
    MODIFIED,
    AdmissionDenied,
    AlreadyExists,
    Conflict,
    NotFound,
    ResourceStore,
    StoreError,
    WatchEvent,
)
from ..shard.router import router_from_spec
from .wire import FrameConn

_log = logging.getLogger(__name__)


def encode_key(k: Any) -> Any:
    """Scheduling-gate keys are strs or (nested) tuples; JSON has no
    tuples, so tag them: ``{"t": [...]}`` vs ``{"v": scalar}``."""
    if isinstance(k, tuple):
        return {"t": [encode_key(x) for x in k]}
    return {"v": k}


def decode_key(d: Any) -> Any:
    if isinstance(d, dict) and "t" in d:
        return tuple(decode_key(x) for x in d["t"])
    return d["v"]


def encode_error(exc: Exception) -> dict[str, Any]:
    if isinstance(exc, NotFound):
        args = [exc.kind, exc.namespace, exc.name]
    elif isinstance(exc, AlreadyExists):
        args = [exc.kind, exc.namespace, exc.name]
    elif isinstance(exc, Conflict):
        args = [exc.kind, exc.namespace, exc.name, exc.expected, exc.actual]
    else:
        args = [str(exc)]
    return {"type": type(exc).__name__, "args": args}


def decode_error(err: dict[str, Any]) -> Exception:
    typ, args = err.get("type"), err.get("args", [])
    if typ == "NotFound":
        return NotFound(*args)
    if typ == "AlreadyExists":
        return AlreadyExists(*args)
    if typ == "Conflict":
        return Conflict(*args)
    if typ == "AdmissionDenied":
        return AdmissionDenied(*args)
    return StoreError(*args)


@guarded_state("_dead", "_deltas", "_reservations", "_waiting")
class _RemoteGate:
    """The bus-wide scheduling gate, served over the wire.

    Preserves the PR-1 shape — one lock, one reservations dict, shared
    by every DAG engine on the bus — across process boundaries, plus
    what processes add: per-session NET deltas, so ``kill -9`` of a
    shard between its reserve and its unreserve rolls back exactly its
    outstanding contribution (a leaked reservation would wedge a
    named-queue cap shut forever; a lost rollback would over-admit)."""

    def __init__(self) -> None:
        # explicit lock under the Condition: sanitizer-tracked (a bare
        # Condition()'s internal RLock allocates in stdlib threading,
        # outside the monitors' tracked source prefixes)
        self._gate_lock = threading.Lock()
        self._cond = threading.Condition(self._gate_lock)
        self._owner: Optional[int] = None
        self._reservations: dict[Any, Any] = {}
        self._deltas: dict[int, dict[Any, float]] = {}
        #: sid -> threads currently blocked in acquire()
        self._waiting: dict[int, int] = {}
        #: sids whose session died while they still had blocked acquires;
        #: entries are pruned when the last waiter for that sid leaves
        self._dead: set[int] = set()

    def acquire(self, sid: int) -> None:
        """Block until the gate is free, then take it — UNLESS this
        session dies while we wait. A client killed mid-``gate_acquire``
        must not take ownership after its close ran (close's
        ``session_died`` would never re-run, wedging the gate bus-wide
        forever), so ``session_died`` marks waiting sids dead and wakes
        them to abort here instead."""
        with self._cond:
            self._waiting[sid] = self._waiting.get(sid, 0) + 1
            try:
                while self._owner is not None and sid not in self._dead:
                    self._cond.wait()
                if sid in self._dead:
                    raise StoreError(
                        "session died while waiting for scheduling gate"
                    )
                self._owner = sid
            finally:
                left = self._waiting.get(sid, 1) - 1
                if left > 0:
                    self._waiting[sid] = left
                else:
                    self._waiting.pop(sid, None)
                    self._dead.discard(sid)

    def release(self, sid: int) -> None:
        with self._cond:
            # A reconnected client releasing a lock its DEAD session held
            # is a no-op: session_died already released it.
            if self._owner == sid:
                self._owner = None
                self._cond.notify_all()

    def get(self, key: Any, default: Any = None) -> Any:
        with self._cond:
            return self._reservations.get(key, default)

    def set(self, sid: int, key: Any, value: Any) -> None:
        with self._cond:
            old = self._reservations.get(key, 0)
            self._reservations[key] = value
            sd = self._deltas.setdefault(sid, {})
            sd[key] = sd.get(key, 0) + (value - old)

    def pop(self, sid: int, key: Any, default: Any = None) -> Any:
        with self._cond:
            if key not in self._reservations:
                return default
            old = self._reservations.pop(key)
            sd = self._deltas.setdefault(sid, {})
            sd[key] = sd.get(key, 0) - old
            return old

    def session_died(self, sid: int) -> None:
        with self._cond:
            if self._owner == sid:
                self._owner = None
            if self._waiting.get(sid):
                self._dead.add(sid)
            for key, delta in self._deltas.pop(sid, {}).items():
                if not delta:
                    continue
                remaining = self._reservations.get(key, 0) - delta
                if remaining > 0:
                    self._reservations[key] = remaining
                else:
                    self._reservations.pop(key, None)
            self._cond.notify_all()

    def reservations(self) -> dict[Any, Any]:
        with self._cond:
            return dict(self._reservations)


@guarded_state("_outq")
class _Session:
    """One connected client: reader (request dispatch), writer (watch
    event fan-out), one store watcher filtered by the session's pushed
    ring spec."""

    def __init__(self, service: "StoreService", sid: int, conn: FrameConn):
        self.service = service
        self.sid = sid
        self.conn = conn
        # explicit tracked lock under the Condition (see _RemoteGate)
        self._outq_lock = threading.Lock()
        self._cond = threading.Condition(self._outq_lock)
        self._outq: deque = deque()
        self._closed = False
        #: shard router rebuilt from the client's ``set_filter`` pushes;
        #: swapped atomically, read per event by ``_wants``
        self._router = None
        self._cancel_watch = service.store.watch(self._on_event, filter=self._wants)
        self._reader = threading.Thread(
            target=self._serve, name=f"store-sess-{sid}-reader", daemon=True
        )
        self._writer = threading.Thread(
            target=self._write_loop, name=f"store-sess-{sid}-writer", daemon=True
        )

    def start(self) -> None:
        self._reader.start()
        self._writer.start()

    # -- delivery (store drainer -> writer thread) -------------------------
    def _wants(self, obj: Resource) -> bool:
        router = self._router
        if router is None:
            return True
        try:
            return router.wants(obj)
        except Exception:  # noqa: BLE001 - a broken spec must not poison the bus
            _log.exception("session %d filter failed", self.sid)
            return True

    def _on_event(self, ev: WatchEvent) -> None:
        # Store drainer thread: enqueue only — to_dict runs on the
        # writer so serialization stays off the bus-wide delivery path.
        with self._cond:
            if self._closed:
                return
            self._outq.append((ev.type, ev.resource))
            self._cond.notify_all()

    def _write_loop(self) -> None:
        while True:
            with self._cond:
                while not self._outq and not self._closed:
                    self._cond.wait()
                if not self._outq:
                    return  # closed and drained
                ev_type, resource = self._outq.popleft()
            try:
                self.conn.send({"event": ev_type, "obj": resource.to_dict()})
            except (OSError, ValueError):
                self.close()
                return

    # -- request dispatch (reader thread) ----------------------------------
    def _serve(self) -> None:
        while True:
            try:
                req = self.conn.recv()
            except (OSError, ValueError, ConnectionError):
                break
            if req is None:
                break
            if not isinstance(req, dict) or "op" not in req:
                break
            if req["op"] == "gate_acquire":
                # blocks until the gate frees — must not stall this
                # session's other traffic
                threading.Thread(
                    target=self._respond, args=(req,), daemon=True,
                    name=f"store-sess-{self.sid}-gate",
                ).start()
                continue
            self._respond(req)
        self.close()

    def _respond(self, req: dict[str, Any]) -> None:
        rid = req.get("id")
        try:
            result = self._dispatch(req)
            frame = {"id": rid, "ok": True, "result": result}
        except (NotFound, AlreadyExists, Conflict, AdmissionDenied, StoreError) as e:
            frame = {"id": rid, "ok": False, "error": encode_error(e)}
        except Exception as e:  # noqa: BLE001 - op bugs must not kill the session
            _log.exception("session %d op %s failed", self.sid, req.get("op"))
            frame = {"id": rid, "ok": False,
                     "error": {"type": "StoreError", "args": [repr(e)]}}
        try:
            self.conn.send(frame)
        except ValueError:
            # Oversized response (e.g. list_views over a huge store):
            # the stream is still framed and healthy — fail just this
            # call instead of tearing down the watch stream and every
            # in-flight request with it.
            err = {
                "id": rid, "ok": False,
                "error": {"type": "StoreError", "args": [
                    f"response to {req.get('op')!r} exceeds the frame cap"
                ]},
            }
            try:
                self.conn.send(err)
            except (OSError, ValueError):
                self._send_failed()
        except OSError:
            self._send_failed()

    def _send_failed(self) -> None:
        """A response could not be delivered: the connection is dead.
        ``close()`` early-returns if the reader's EOF path already closed
        this session — but a gate acquisition that completed AFTER that
        close (a stranded ``gate_acquire`` thread taking ownership for a
        dead sid) would then never be rolled back, deadlocking the gate
        bus-wide. ``session_died`` is idempotent, so re-run it
        unconditionally here."""
        self.close()
        self.service.gate.session_died(self.sid)

    def _dispatch(self, req: dict[str, Any]) -> Any:
        op = req["op"]
        store = self.service.store
        gate = self.service.gate
        if op == "ping":
            return "pong"
        if op == "hello":
            with store._lock:
                return {
                    "indexes": [list(k) for k in sorted(store._indexes.keys())],
                    "rv": store._rv_counter,
                }
        if op == "get_view":
            return store.get_view(req["kind"], req["namespace"], req["name"]).to_dict()
        if op == "try_get_view":
            obj = store.try_get_view(req["kind"], req["namespace"], req["name"])
            return None if obj is None else obj.to_dict()
        if op == "list_views":
            index = tuple(req["index"]) if req.get("index") else None
            return [
                o.to_dict()
                for o in store.list_views(
                    req["kind"], req.get("namespace"), req.get("labels"), index
                )
            ]
        if op == "count":
            index = tuple(req["index"]) if req.get("index") else None
            return store.count(req["kind"], req.get("namespace"), index)
        if op == "list_keys":
            index = tuple(req["index"]) if req.get("index") else None
            return [
                list(t)
                for t in store.list_keys(req["kind"], req.get("namespace"), index)
            ]
        if op == "create":
            return store.create(Resource.from_dict(req["obj"])).to_dict()
        if op == "update":
            return store.update(Resource.from_dict(req["obj"])).to_dict()
        if op == "update_status":
            return store.update_status(Resource.from_dict(req["obj"])).to_dict()
        if op == "delete":
            store.delete(req["kind"], req["namespace"], req["name"])
            return None
        if op == "rv":
            with store._lock:
                return store._rv_counter
        if op == "len":
            return len(store)
        if op == "kinds":
            return sorted(store.kinds())
        if op == "set_filter":
            self._router = router_from_spec(store, req["spec"])
            return None
        if op == "resync":
            self._resync()
            return None
        if op == "gate_acquire":
            gate.acquire(self.sid)
            return None
        if op == "gate_release":
            gate.release(self.sid)
            return None
        if op == "gate_get":
            return gate.get(decode_key(req["key"]), req.get("default"))
        if op == "gate_set":
            gate.set(self.sid, decode_key(req["key"]), req["value"])
            return None
        if op == "gate_pop":
            return gate.pop(self.sid, decode_key(req["key"]), req.get("default"))
        if op == "dump":
            dump = getattr(store, "dump", None)
            return base64.b64encode(dump()).decode("ascii") if dump else None
        if op == "snapshot":
            snap = getattr(store, "snapshot", None)
            if snap:
                snap()
            return None
        raise StoreError(f"unknown op {op!r}")

    def _resync(self) -> None:
        """Synthetic MODIFIED for every object passing the session
        filter — the level-triggered heal a client requests after
        reconnecting (events during the outage are gone; state is
        not)."""
        store = self.service.store
        objs = []
        for kind in sorted(store.kinds()):
            objs.extend(o for o in store.list_views(kind) if self._wants(o))
        with self._cond:
            if self._closed:
                return
            for obj in objs:
                self._outq.append((MODIFIED, obj))
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._cancel_watch()
        self.service.gate.session_died(self.sid)
        self.conn.close()
        self.service._forget(self.sid)


@guarded_state("_sessions")
class StoreService:
    """The store service: accept loop + session registry around one
    authoritative store (plain for tests, durable in production)."""

    def __init__(self, store: ResourceStore, socket_path: str):
        self.store = store
        self.socket_path = socket_path
        self.gate = _RemoteGate()
        self._lock = threading.Lock()
        self._sessions: dict[int, _Session] = {}
        self._sid_counter = 0
        self._closed = False
        # Index functions and the ShardMap fence validator cannot cross
        # the wire: they live where the objects live. runtime is a heavy
        # import (jax) — only the service process pays it, never clients.
        from ..runtime import register_core_indexes
        from ..shard.map import register_shard_admission

        register_core_indexes(store)
        register_shard_admission(store)
        try:
            os.unlink(socket_path)
        except (FileNotFoundError, OSError):
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(socket_path)
        self._listener.listen(128)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="store-accept", daemon=True
        )

    def start(self) -> "StoreService":
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                if self._closed:
                    sock.close()
                    return
                self._sid_counter += 1
                sid = self._sid_counter
                session = _Session(self, sid, FrameConn(sock))
                self._sessions[sid] = session
            session.start()

    def _forget(self, sid: int) -> None:
        with self._lock:
            self._sessions.pop(sid, None)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            sessions = list(self._sessions.values())
        try:
            self._listener.close()
        except OSError:
            pass
        for session in sessions:
            session.close()
        try:
            os.unlink(self.socket_path)
        except (FileNotFoundError, OSError):
            pass
