"""Length-prefixed JSON framing for the store-service socket protocol.

One frame is a 4-byte big-endian length followed by a UTF-8 JSON body.
That is the entire codec: requests, responses, and watch events are all
single frames, and the only concurrency rule is that writers serialize
per connection (``FrameConn`` holds a send lock so the service's writer
thread and one-off responders never interleave partial frames).

The cap (``MAX_FRAME``) bounds a single resource plus envelope; it is a
corruption tripwire, not a quota — a length word above it means the
stream is desynchronised and the connection must die.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Optional

#: Corruption tripwire for the 4-byte length word (64 MiB).
MAX_FRAME = 64 * 1024 * 1024

_LEN = struct.Struct(">I")


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes, or ``None`` on clean EOF at a boundary."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (OSError, ValueError):
            return None
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, payload: Any) -> None:
    body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise ValueError(f"frame of {len(body)} bytes exceeds MAX_FRAME")
    sock.sendall(_LEN.pack(len(body)) + body)


def recv_frame(sock: socket.socket) -> Optional[Any]:
    """Receive one frame; ``None`` means the peer closed the stream."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ConnectionError(f"frame length {n} exceeds MAX_FRAME; stream desynchronised")
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return json.loads(body.decode("utf-8"))


class FrameConn:
    """A socket plus a send lock: many threads may send, one may receive."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()

    def send(self, payload: Any) -> None:
        with self._send_lock:
            send_frame(self.sock, payload)

    def recv(self) -> Optional[Any]:
        return recv_frame(self.sock)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
