"""StoreClient: the existing store surface, served over the socket.

Runtime/manager/dag code runs unmodified against this shim — same
methods, same exceptions, same watch/scheduling-gate/view semantics as
:class:`~..core.store.ResourceStore` — while the authoritative state
lives in the store-service process. What moves where:

- **admission runs client-side**: defaulters/validators are Python
  callables registered by whatever process constructs the Runtime, so
  they cannot cross the wire. create/update fetch the current object,
  merge exactly as ``ResourceStore._update`` does, run the local
  chains, and ship the result with the rv they read — the server
  re-checks the rv atomically at commit, so optimistic concurrency is
  still decided in exactly one place. The server runs its OWN chain
  (shard-map fence admission), which is the one that must be atomic
  with the commit.
- **watch filters run server-side**: ``set_watch_filter`` with a shard
  router's ``wants`` pushes the ring spec to the session (and re-pushes
  on every ring change via ``router.on_rings_changed``), so this
  process only receives events for families it owns. Local watchers
  still apply their own kinds/filter on dispatch, same as in-process.
- **the scheduling gate is remote**: ``scheduling_gate()`` returns
  (lock proxy, reservations proxy) whose operations are RPCs against
  the service's single bus-wide gate — named-queue caps never
  over-admit across shard processes, and the service rolls back a dead
  session's net reservations so a ``kill -9`` cannot wedge a cap shut.
- **crash windows are explicit**: on disconnect, idempotent reads
  retry transparently through reconnect; in-flight mutations raise
  ``StoreError`` (the caller cannot know whether they committed — the
  level-triggered reconcile retries); calls issued during an outage
  fail after ``reconnect_deadline``, but the client itself redials
  with backoff until the service returns, so an outage of ANY length
  heals; after reconnect the client re-pushes its filter spec and
  requests a resync (synthetic MODIFIED for all owned state), healing
  any events lost during the outage.
"""

from __future__ import annotations

import copy
import logging
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, Optional

from ..analysis.racedetect import guarded_state
from ..core.object import Resource
from ..core.store import (
    Conflict,
    NotFound,
    StoreError,
    WatchEvent,
    WatchFilter,
    WatchHandler,
)
from .service import decode_error, encode_key
from .wire import FrameConn

_log = logging.getLogger(__name__)


class _Call:
    __slots__ = ("event", "result", "error", "retry")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[Exception] = None
        self.retry = False


class _GateLock:
    """Context-manager proxy for the service-side scheduling-gate lock
    (session-scoped: a dead holder's lock is auto-released)."""

    def __init__(self, client: "StoreClient"):
        self._client = client

    def acquire(self) -> bool:
        self._client._call("gate_acquire", _idempotent=True)
        return True

    def release(self) -> None:
        try:
            self._client._call("gate_release", _idempotent=True)
        except StoreError:
            pass  # session died while holding: server already released

    def __enter__(self) -> "_GateLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.release()
        return False


class _GateMap:
    """dict-shaped proxy for the bus-wide reservations table (the ops
    the DAG engines use: get / __setitem__ / pop)."""

    def __init__(self, client: "StoreClient"):
        self._client = client

    def get(self, key: Any, default: Any = None) -> Any:
        return self._client._call(
            "gate_get", _idempotent=True, key=encode_key(key), default=default
        )

    def __setitem__(self, key: Any, value: Any) -> None:
        self._client._call(
            "gate_set", _idempotent=True, key=encode_key(key), value=value
        )

    def pop(self, key: Any, default: Any = None) -> Any:
        return self._client._call(
            "gate_pop", _idempotent=True, key=encode_key(key), default=default
        )

    def __contains__(self, key: Any) -> bool:
        return self.get(key, None) is not None


@guarded_state("_defaulters", "_events", "_indexes", "_pending",
               "_server_indexes", "_status_validators", "_validators",
               "_watchers")
class StoreClient:
    """Store-surface shim over one store-service session."""

    def __init__(
        self,
        socket_path: str,
        connect_timeout: float = 30.0,
        reconnect_deadline: float = 15.0,
    ):
        self.socket_path = socket_path
        self._reconnect_deadline = reconnect_deadline
        self._lock = threading.RLock()
        # explicit lock under the Condition: a bare Condition() allocates
        # its RLock inside stdlib threading, where the lock-order
        # sanitizer deliberately does not look — the event queue would
        # run untracked in the armed suites
        self._ev_lock = threading.Lock()
        self._ev_cond = threading.Condition(self._ev_lock)
        self._pending: dict[int, _Call] = {}
        self._events: deque = deque()  # raw event frames awaiting dispatch
        self._watchers: list = []
        self._indexes: dict[tuple[str, str], Callable] = {}
        self._defaulters: dict[str, list] = {}
        self._validators: dict[str, list] = {}
        self._status_validators: dict[str, list] = {}
        self._server_indexes: frozenset = frozenset()
        self._default_watch_filter: Optional[WatchFilter] = None
        self._router = None  # shard router whose spec is pushed server-side
        self._call_id = 0
        self._conn: Optional[FrameConn] = None
        self._connected = threading.Event()
        self._closing = False
        self._dead = False
        self._gate = (_GateLock(self), _GateMap(self))
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                self._connect(resync=False)
                break
            except OSError as e:
                if time.monotonic() >= deadline:
                    raise StoreError(
                        f"store service at {socket_path} unreachable: {e}"
                    ) from e
                time.sleep(0.05)
        self._connected.set()
        self._reader = threading.Thread(
            target=self._reader_loop, name="store-client-reader", daemon=True
        )
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="store-client-dispatch", daemon=True
        )
        self._reader.start()
        self._dispatcher.start()

    # -- connection management ---------------------------------------------
    def _connect(self, resync: bool) -> None:
        """Dial + handshake. Runs with the reader NOT consuming this
        conn (initial connect, or from the reader thread itself), so
        responses are received inline; event frames that race the
        handshake are buffered for the dispatcher."""
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.settimeout(5.0)
            sock.connect(self.socket_path)
            sock.settimeout(None)
            conn = FrameConn(sock)
            hello = self._rpc_inline(conn, "hello")
            with self._lock:
                self._server_indexes = frozenset(
                    tuple(pair) for pair in hello["indexes"]
                )
            router = self._router
            if router is not None:
                self._rpc_inline(conn, "set_filter", spec=router.filter_spec())
            if resync:
                self._rpc_inline(conn, "resync")
        except BaseException:
            # half-constructed dial: don't leave the socket to GC
            sock.close()
            raise
        old, self._conn = self._conn, conn
        if old is not None:
            # the previous conn already EOF'd, but its fd is still open —
            # without this every reconnect leaks one socket
            old.close()

    def _rpc_inline(self, conn: FrameConn, op: str, **params: Any) -> Any:
        with self._lock:
            self._call_id += 1
            cid = self._call_id
        conn.send({"id": cid, "op": op, **params})
        while True:
            frame = conn.recv()
            if frame is None:
                raise OSError(f"connection closed during {op} handshake")
            if "event" in frame:
                with self._ev_cond:
                    self._events.append(frame)
                    self._ev_cond.notify_all()
                continue
            if not frame.get("ok", False):
                raise decode_error(frame["error"])
            return frame["result"]

    def _reader_loop(self) -> None:
        while True:
            conn = self._conn
            if conn is None or self._closing:
                return
            try:
                frame = conn.recv()
            except (OSError, ValueError, ConnectionError):
                frame = None
            if frame is None:
                if self._closing:
                    return
                if not self._reconnect():
                    return
                continue
            if "event" in frame:
                with self._ev_cond:
                    self._events.append(frame)
                    self._ev_cond.notify_all()
            else:
                with self._lock:
                    call = self._pending.pop(frame.get("id"), None)
                if call is not None:
                    if frame.get("ok", False):
                        call.result = frame.get("result")
                    else:
                        call.error = decode_error(frame["error"])
                    call.event.set()

    def _reconnect(self) -> bool:
        """Reader-thread path after EOF: fail in-flight calls (their
        outcome is unknowable), then redial with backoff until the
        service returns or the client is closed — NEVER give up for
        good. Individual calls still fail after ``reconnect_deadline``
        (see ``_call``), but the client itself stays recoverable, so a
        store-service restart slower than the deadline heals instead of
        bricking every shard until process restart. On success the
        filter spec is re-pushed and a resync requested."""
        self._connected.clear()
        with self._lock:
            stranded = list(self._pending.values())
            self._pending.clear()
        for call in stranded:
            call.retry = True
            call.event.set()
        delay = 0.05
        while not self._closing:
            try:
                self._connect(resync=True)
            except (OSError, StoreError):
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
                continue
            self._connected.set()
            _log.info("store client reconnected to %s", self.socket_path)
            return True
        return False

    def _call(self, op: str, _idempotent: bool = False, **params: Any) -> Any:
        deadline = time.monotonic() + self._reconnect_deadline + 5.0
        while True:
            if self._dead or self._closing:
                raise StoreError(f"store service connection closed ({op})")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise StoreError(f"store service unreachable ({op})")
            if not self._connected.wait(timeout=remaining):
                continue
            if self._dead or self._closing:
                raise StoreError(f"store service connection closed ({op})")
            call = _Call()
            with self._lock:
                self._call_id += 1
                cid = self._call_id
                self._pending[cid] = call
                conn = self._conn
            try:
                conn.send({"id": cid, "op": op, **params})
            except (OSError, ValueError):
                with self._lock:
                    self._pending.pop(cid, None)
                time.sleep(0.05)  # reader notices EOF and reconnects
                continue
            call.event.wait()
            if call.retry:
                if _idempotent:
                    continue
                raise StoreError(
                    f"store connection lost during {op}; outcome unknown"
                )
            if call.error is not None:
                raise call.error
            return call.result

    def close(self) -> None:
        self._closing = True
        self._dead = True
        conn = self._conn
        if conn is not None:
            conn.close()
        with self._lock:
            stranded = list(self._pending.values())
            self._pending.clear()
        for call in stranded:
            call.retry = True
            call.event.set()
        self._connected.set()
        with self._ev_cond:
            self._ev_cond.notify_all()

    # -- event dispatch ----------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            with self._ev_cond:
                while not self._events and not self._closing and not self._dead:
                    self._ev_cond.wait()
                if not self._events:
                    return  # closing/dead and drained
                frame = self._events.popleft()
            try:
                resource = Resource.from_dict(frame["obj"])
            except Exception:  # noqa: BLE001 - one bad frame must not kill dispatch
                _log.exception("undecodable watch frame")
                continue
            ev = WatchEvent(frame["event"], resource)
            with self._lock:
                watchers = list(self._watchers)
            for kinds, flt, handler in watchers:
                if kinds is not None and resource.kind not in kinds:
                    continue
                try:
                    if flt is not None and not flt(resource):
                        continue
                    handler(ev)
                except Exception:  # noqa: BLE001 - same isolation as ResourceStore._drain
                    _log.exception(
                        "watch handler failed for %s %s/%s",
                        resource.kind, resource.meta.namespace, resource.meta.name,
                    )

    # -- admission registration (local: callables cannot cross the wire) --
    def register_defaulter(self, kind: str, fn: Callable) -> None:
        with self._lock:
            self._defaulters.setdefault(kind, []).append(fn)

    def register_validator(self, kind: str, fn: Callable) -> None:
        with self._lock:
            self._validators.setdefault(kind, []).append(fn)

    def register_status_validator(self, kind: str, fn: Callable) -> None:
        with self._lock:
            self._status_validators.setdefault(kind, []).append(fn)

    def admission_chain(self, kind: str) -> tuple[list, list, list]:
        with self._lock:
            return (
                list(self._defaulters.get(kind, [])),
                list(self._validators.get(kind, [])),
                list(self._status_validators.get(kind, [])),
            )

    # -- indexes -----------------------------------------------------------
    def add_index(self, kind: str, index_name: str, fn: Callable) -> None:
        """Remembered locally; queries pass through when the service
        registered the same name at boot (the core inventory), else
        fall back to a client-side scan with the local function."""
        with self._lock:
            if (kind, index_name) not in self._indexes:
                self._indexes[(kind, index_name)] = fn

    def _wire_index(self, kind: str, index: Optional[tuple]) -> Optional[list]:
        if index is None:
            return None
        if (kind, index[0]) in self._server_indexes:
            return [index[0], index[1]]
        return None  # unknown server-side: caller falls back locally

    def _local_index_filter(
        self, kind: str, index: tuple, objs: list[Resource]
    ) -> list[Resource]:
        with self._lock:
            fn = self._indexes.get((kind, index[0]))
        if fn is None:
            raise StoreError(f"unknown index {index[0]!r} for kind {kind}")
        return [o for o in objs if index[1] in fn(o)]

    # -- watch / filters / gate --------------------------------------------
    def watch(
        self,
        handler: WatchHandler,
        kinds: Optional[Iterable[str]] = None,
        filter: Optional[WatchFilter] = None,
    ) -> Callable[[], None]:
        if filter is None:
            filter = self._default_watch_filter
        entry = (frozenset(kinds) if kinds is not None else None, filter, handler)
        with self._lock:
            self._watchers.append(entry)

        def cancel() -> None:
            with self._lock:
                if entry in self._watchers:
                    self._watchers.remove(entry)

        return cancel

    def set_watch_filter(self, filter: Optional[WatchFilter]) -> None:
        """Same registration-time default binding as the in-process
        store — PLUS, when the predicate is a shard router's ``wants``,
        the ring spec is pushed so the SERVICE evaluates it per event
        and this process stops receiving other shards' run churn at
        all. Clearing the default (None) does not clear the session
        filter: that is the process's delivery partition, and ring
        changes keep flowing through ``router.on_rings_changed``."""
        self._default_watch_filter = filter
        router = getattr(filter, "__self__", None)
        if (
            filter is not None
            and getattr(filter, "__name__", "") == "wants"
            and router is not None
            and hasattr(router, "filter_spec")
        ):
            self._router = router
            router.on_rings_changed = self._push_filter
            self._push_filter()

    def _push_filter(self) -> None:
        router = self._router
        if router is None:
            return
        try:
            self._call("set_filter", _idempotent=True, spec=router.filter_spec())
        except StoreError:
            _log.warning("filter push failed; reconnect will re-push")

    def scheduling_gate(self) -> tuple[_GateLock, _GateMap]:
        return self._gate

    def resync(self) -> None:
        """Request synthetic MODIFIED for all (filtered) state."""
        self._call("resync", _idempotent=True)

    # -- reads -------------------------------------------------------------
    def get_view(self, kind: str, namespace: str, name: str) -> Resource:
        d = self._call(
            "get_view", _idempotent=True, kind=kind, namespace=namespace, name=name
        )
        return Resource.from_dict(d)

    def try_get_view(self, kind: str, namespace: str, name: str) -> Optional[Resource]:
        d = self._call(
            "try_get_view", _idempotent=True, kind=kind, namespace=namespace, name=name
        )
        return None if d is None else Resource.from_dict(d)

    # Wire objects are already private copies, so get == get_view here.
    get = get_view
    try_get = try_get_view

    def list_views(
        self,
        kind: str,
        namespace: Optional[str] = None,
        labels: Optional[dict[str, str]] = None,
        index: Optional[tuple[str, str]] = None,
    ) -> list[Resource]:
        wire_index = self._wire_index(kind, index)
        if index is not None and wire_index is None:
            objs = self.list_views(kind, namespace, labels, None)
            return self._local_index_filter(kind, index, objs)
        ds = self._call(
            "list_views", _idempotent=True, kind=kind, namespace=namespace,
            labels=labels, index=wire_index,
        )
        return [Resource.from_dict(d) for d in ds]

    list = list_views  # wire objects are private copies already

    def count(
        self,
        kind: str,
        namespace: Optional[str] = None,
        index: Optional[tuple[str, str]] = None,
    ) -> int:
        wire_index = self._wire_index(kind, index)
        if index is not None and wire_index is None:
            return len(self._local_index_filter(
                kind, index, self.list_views(kind, namespace)))
        return self._call(
            "count", _idempotent=True, kind=kind, namespace=namespace,
            index=wire_index,
        )

    def list_keys(
        self,
        kind: str,
        namespace: Optional[str] = None,
        index: Optional[tuple[str, str]] = None,
    ) -> list[tuple[str, str]]:
        wire_index = self._wire_index(kind, index)
        if index is not None and wire_index is None:
            picked = self._local_index_filter(
                kind, index, self.list_views(kind, namespace))
            return sorted((o.meta.namespace, o.meta.name) for o in picked)
        pairs = self._call(
            "list_keys", _idempotent=True, kind=kind, namespace=namespace,
            index=wire_index,
        )
        return [tuple(p) for p in pairs]

    # -- writes ------------------------------------------------------------
    def create(self, obj: Resource) -> Resource:
        new = obj.deepcopy()
        with self._lock:
            dfs = list(self._defaulters.get(new.kind, []))
            vds = list(self._validators.get(new.kind, []))
            svs = list(self._status_validators.get(new.kind, []))
        for fn in dfs:
            fn(new)
        for fn in vds:
            fn(new, None)
        if new.status:
            for fn in svs:
                fn(new, None)
        d = self._call("create", obj=new.to_dict())
        return Resource.from_dict(d)

    def update(self, obj: Resource) -> Resource:
        return self._update(obj, status_only=False)

    def update_status(self, obj: Resource) -> Resource:
        return self._update(obj, status_only=True)

    def _update(self, obj: Resource, status_only: bool) -> Resource:
        """Local admission needs the current object for fn(new, cur);
        the merge mirrors ``ResourceStore._update`` so validators see
        exactly what the server will commit. Exactness argument: the
        chains run only when the fetched cur carries the rv the caller
        read; the server re-checks that rv at commit, so a write that
        lands validated against the true predecessor, and a racing
        change turns into the same Conflict the in-process store would
        raise. Kinds with no local chains skip the extra round-trip."""
        kind = obj.kind
        op = "update_status" if status_only else "update"
        with self._lock:
            dfs = list(self._defaulters.get(kind, []))
            vds = list(self._validators.get(kind, []))
            svs = list(self._status_validators.get(kind, []))
        needs_local = bool(svs) if status_only else bool(dfs or vds or svs)
        if not needs_local:
            return Resource.from_dict(self._call(op, obj=obj.to_dict()))
        cur = self.try_get_view(kind, obj.meta.namespace, obj.meta.name)
        if cur is None:
            raise NotFound(kind, obj.meta.namespace, obj.meta.name)
        if obj.meta.resource_version != cur.meta.resource_version:
            raise Conflict(
                kind, obj.meta.namespace, obj.meta.name,
                obj.meta.resource_version, cur.meta.resource_version,
            )
        new = cur.deepcopy()
        if status_only:
            new.status = copy.deepcopy(obj.status)
            for fn in svs:
                fn(new, cur)
        else:
            new.spec = copy.deepcopy(obj.spec)
            new.status = copy.deepcopy(obj.status)
            new.meta.labels = dict(obj.meta.labels)
            new.meta.annotations = dict(obj.meta.annotations)
            new.meta.finalizers = list(obj.meta.finalizers)
            new.meta.owner_references = list(obj.meta.owner_references)
            for fn in dfs:
                fn(new)
            for fn in vds:
                fn(new, cur)
            if new.status != cur.status:
                for fn in svs:
                    fn(new, cur)
        return Resource.from_dict(self._call(op, obj=new.to_dict()))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._call("delete", kind=kind, namespace=namespace, name=name)

    def mutate(
        self,
        kind: str,
        namespace: str,
        name: str,
        fn: Callable[[Resource], None],
        status_only: bool = False,
        max_attempts: int = 10,
    ) -> Resource:
        last: Optional[Conflict] = None
        for _ in range(max_attempts):
            committed = self.get_view(kind, namespace, name)
            cur = committed.deepcopy()
            fn(cur)
            if cur == committed:
                return cur
            try:
                if status_only:
                    return self.update_status(cur)
                return self.update(cur)
            except Conflict as e:
                last = e
        raise last  # type: ignore[misc]

    def patch_status(
        self, kind: str, namespace: str, name: str, fn: Callable[[dict], None]
    ) -> Resource:
        return self.mutate(kind, namespace, name, lambda r: fn(r.status), status_only=True)

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        return self._call("len", _idempotent=True)

    def kinds(self) -> set[str]:
        return set(self._call("kinds", _idempotent=True))

    @property
    def _rv_counter(self) -> int:
        """The service's committed-version counter (harness helpers use
        it for unique run names)."""
        return self._call("rv", _idempotent=True)

    def dump_remote(self) -> bytes:
        """Canonical state bytes from the service (crash-soak probe)."""
        import base64

        b64 = self._call("dump", _idempotent=True)
        return b"" if b64 is None else base64.b64decode(b64)

    def snapshot_remote(self) -> None:
        self._call("snapshot", _idempotent=True)
