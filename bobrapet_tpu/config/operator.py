"""Operator configuration: typed knobs + live reload from a ConfigMap.

Capability parity with the reference's OperatorConfigManager
(reference: internal/config/operator.go:159,189,380 — the manager is
itself a reconciler on the operator ConfigMap; ~60 dotted keys parsed at
operator.go:385-1390; validation ValidateControllerConfig:256; runtime
toggles ApplyRuntimeToggles controller_config.go:176).

Here the "ConfigMap" is a resource of kind ``ConfigMap`` on the
coordination bus whose ``spec.data`` carries the dotted keys; the manager
watches it and atomically swaps the parsed config, notifying subscribers.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable, Optional

from ..api.enums import OffloadedDataPolicy
from ..core.object import Resource
from ..core.store import MODIFIED, ADDED, ResourceStore, WatchEvent
from ..utils.duration import parse_duration

_log = logging.getLogger(__name__)

CONFIG_MAP_KIND = "ConfigMap"


@dataclasses.dataclass
class QueueConfig:
    """Named scheduling queue (reference: controller_config.go:524-547)."""

    name: str = "default"
    max_concurrent: int = 0  # 0 = unlimited
    priority_aging_seconds: float = 300.0  # effective priority grows with age
    # TPU-native: queues map to slice pools (SURVEY §2.6); a queue may pin
    # an accelerator type + available chip budget for admission.
    accelerator: Optional[str] = None
    chip_budget: int = 0  # 0 = unlimited


@dataclasses.dataclass
class SchedulingConfig:
    """(reference: controller_config.go:524-547 SchedulingConfig)"""

    global_max_concurrent_steps: int = 0  # 0 = unlimited
    #: how often a capacity-parked run re-probes the scheduling gates
    #: (queueWaiting/placementWaiting requeue). The default matches the
    #: historical hardcoded 1s; latency-sensitive deployments (and the
    #: sharded soak) tighten it so a freed slot refills promptly
    #: (dotted: scheduling.queue-probe-interval)
    queue_probe_interval: float = 1.0
    #: default pool set for SPANNING gangs (multi-slice DCN
    #: data-parallel): a `parallel` step with a replicas/step fan-out
    #: that names no `pools` of its own spans these. Empty = replicated
    #: fan-outs stay single-pool on their queue's pool
    #: (dotted: scheduling.span-pools, comma-separated pool names)
    span_pools: list[str] = dataclasses.field(default_factory=list)
    #: when the balanced round-robin distribution of a spanning gang
    #: does not fit, allow the greedy first-fit fallback that may pack
    #: replicas unevenly across pools (off = balanced-or-park; uneven
    #: replica counts skew DCN gradient-sync stragglers)
    #: (dotted: scheduling.span-spill)
    span_spill: bool = True
    queues: dict[str, QueueConfig] = dataclasses.field(default_factory=dict)

    def queue(self, name: Optional[str]) -> QueueConfig:
        if name and name in self.queues:
            return self.queues[name]
        return self.queues.get("default", QueueConfig())


@dataclasses.dataclass
class TemplatingSettings:
    """(reference: controller_config.go:140-144 + cmd/main.go:585-590)"""

    evaluation_timeout: float = 1.0
    max_output_bytes: int = 1 << 20
    deterministic: bool = True
    offloaded_data_policy: OffloadedDataPolicy = OffloadedDataPolicy.FAIL
    materialize_engram: Optional[str] = None  # engram used for controller policy


@dataclasses.dataclass
class ControllerTuning:
    """Per-controller knobs (reference: operator.go:447-528)."""

    max_concurrent_reconciles: int = 4
    requeue_base_delay: float = 0.05
    requeue_max_delay: float = 30.0
    reconcile_timeout: float = 30.0
    #: horizontal sharding (bobrapet_tpu/shard): number of cooperating
    #: managers owning disjoint hash-ring ranges of run keys. 1 = the
    #: classic single-active manager. Live-reloaded: the elected shard
    #: leader republishes the map and a barrier rebalance follows
    #: (dotted: controllers.shard-count)
    shard_count: int = 1
    #: this replica's shard identity in [0, shard-count). Normally set
    #: per-process (BOBRA_SHARD_ID / Runtime(shard_id=...)) because the
    #: ConfigMap is shared by every replica; the dotted key exists for
    #: single-replica pinning and tooling (controllers.shard-id)
    shard_id: int = 0
    #: per-controller pool-width overrides, keyed by controller name
    #: (reference: the five per-controller ``*.max-concurrent-reconciles``
    #: families, operator.go:447-528); dotted key
    #: ``controllers.<name>.max-concurrent-reconciles``
    per_controller: dict[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DataplaneConfig:
    """Data-plane hub hot-path knobs (consumed live by
    ``dataplane.hub.apply_tuning`` — the writer threads read them at
    drain time, so a reload affects running streams)."""

    #: frames a hub writer thread drains per wakeup and flushes as one
    #: vectored/joined write
    writer_max_batch: int = 64
    #: collapse buffered cumulative-ack runs / merge adjacent credit
    #: grants into single frames
    coalesce_acks: bool = True


@dataclasses.dataclass
class FleetConfig:
    """Slice-fleet health & preemption recovery knobs (``fleet.*``;
    TPU-native addition, consumed live by :mod:`bobrapet_tpu.fleet`).

    A preemption redrive has its OWN retry cap — reclaimed slices are
    infrastructure events and must not consume the user-facing retry
    budget (shared_types.go:400 RetryPolicy stays untouched)."""

    #: checkpoint-resuming redrives allowed per StepRun before the
    #: preemption turns terminal (dotted: fleet.preemption-retry-cap)
    preemption_retry_cap: int = 5
    #: delay before relaunching a preempted gang (fleet.redrive-delay)
    redrive_delay_seconds: float = 1.0
    #: base quarantine for a cell whose host was reclaimed
    #: (fleet.quarantine); repeated strikes escalate up to
    #: fleet.max-quarantine-multiplier x this base, then decay out
    quarantine_seconds: float = 300.0
    max_quarantine_multiplier: float = 8.0
    #: suspicion score at which a cell is quarantined
    #: (fleet.suspicion-threshold); scores decay exponentially with
    #: fleet.suspicion-half-life
    suspicion_threshold: float = 2.0
    suspicion_half_life_seconds: float = 600.0
    #: a gang host silent for this long is reported suspect
    #: (fleet.heartbeat-timeout)
    heartbeat_timeout_seconds: float = 60.0
    #: kill the whole gang the moment one host dies of preemption
    #: instead of waiting for the step timeout (fleet.fail-fast)
    fail_fast: bool = True
    #: GKE materialization: target spot (preemptible) TPU slices —
    #: gke-spot nodeSelector + toleration on gang pods (fleet.gke-spot)
    gke_spot: bool = False
    #: SIGTERM->SIGKILL window on gang pods so a reclaimed worker can
    #: cut a final checkpoint (fleet.termination-grace; 0 = leave the
    #: cluster default). 30s matches the k8s default explicitly.
    termination_grace_seconds: float = 30.0


@dataclasses.dataclass
class ServingConfig:
    """Serving-engine knobs (``serving.*``; TPU-native addition,
    consumed live by :func:`bobrapet_tpu.serving.engram.apply_tuning`
    on every engine the process is serving — compiled horizon graphs
    are cached per length, so flipping these costs one compile on
    first use, nothing after)."""

    #: fused decode steps per host sync (the device-resident decode
    #: horizon; 1 = the classic single-step reference engine)
    #: (dotted: serving.decode-horizon)
    decode_horizon: int = 8
    #: decode horizons kept in flight on the device queue (double
    #: buffering); the host commits horizon N-1 and runs admission
    #: while N executes. 1 = the single-buffered reference path (each
    #: horizon fully committed before the next dispatch)
    #: (dotted: serving.dispatch-depth)
    dispatch_depth: int = 2
    #: draft proposals per speculative round on draft-capable engines
    #: (dotted: serving.spec-k)
    spec_k: int = 4
    #: share prefix-cache blocks ACROSS engine instances by content
    #: hash (weights-fingerprint scoped; see prefix_cache.py)
    #: (dotted: serving.prefix-cache-shared)
    prefix_cache_shared: bool = False
    #: disaggregated serving role for engines this process serves:
    #: unified (classic), prefill (retire at first token + KV export),
    #: decode (adopt + decode-only traffic); step `role` keys pin
    #: per-engine values (dotted: serving.role)
    role: str = "unified"
    #: minimum prompt tokens for the router to send a request through
    #: the prefill pool; 0 = every request while a prefill engine
    #: exists (dotted: serving.router-prefill-threshold)
    router_prefill_threshold: int = 0
    #: route decode admissions to the engine holding the longest
    #: matching prefix chain (False = pure least-loaded)
    #: (dotted: serving.router-prefix-affinity)
    router_prefix_affinity: bool = True
    #: weighted-fair tenant admission: "tenantA:4,tenantB:1" swaps the
    #: engine/router pending queues for a weighted deficit scheduler
    #: (traffic/fairness.py) so one tenant's burst cannot starve
    #: another's TTFT; empty = plain FIFO. Unlisted tenants weigh 1,
    #: the "*" key overrides that default
    #: (dotted: serving.tenant-weights)
    tenant_weights: str = ""


#: last serving config a Runtime applied in this process. The serving
#: engram module is jax-heavy and typically imported AFTER the control
#: plane boots, so Runtime cannot push startup knobs into it directly
#: — it parks them here (a no-jax module both sides can import) and
#: ``serving/engram.build_engine`` reads them as build-time defaults.
LAST_SERVING_TUNING: Optional[ServingConfig] = None


@dataclasses.dataclass
class TrafficConfig:
    """Traffic-harness autoscaler knobs (``traffic.*``; TPU-native
    addition, consumed live by
    :func:`bobrapet_tpu.traffic.autoscaler.apply_tuning` — a reload
    swaps every live autoscaler's policy/interval/enable flag; an
    invalid combination keeps the prior policy)."""

    #: run the SLO-driven replica autoscaler loop
    #: (dotted: traffic.autoscale-enabled)
    autoscale_enabled: bool = False
    #: seconds between decision passes (the burn/queue-wait windows ARE
    #: this interval) (dotted: traffic.autoscale-interval)
    autoscale_interval_seconds: float = 1.0
    #: replica clamps per pool (dotted: traffic.min-replicas /
    #: traffic.max-replicas); max counts draining replicas — their
    #: chips are still held
    min_replicas: int = 1
    max_replicas: int = 4
    #: decode pools scale UP past this SLO (tpot) burn fraction and
    #: DOWN only below the lower bound — the gap is the hysteresis
    #: (dotted: traffic.scale-up-burn / traffic.scale-down-burn)
    scale_up_burn: float = 0.30
    scale_down_burn: float = 0.05
    #: prefill pools scale on p95 router-queue wait instead (their
    #: pressure is arrival-shaped, not cadence-shaped)
    #: (dotted: traffic.scale-up-queue-wait / -down-queue-wait)
    scale_up_queue_wait_seconds: float = 0.50
    scale_down_queue_wait_seconds: float = 0.05
    #: either pool scales up when router backlog exceeds this many
    #: queued requests per routable replica
    #: (dotted: traffic.queue-depth-per-replica)
    queue_depth_per_replica: int = 8
    #: per-direction cooldowns (dotted: traffic.scale-up-cooldown /
    #: traffic.scale-down-cooldown)
    scale_up_cooldown_seconds: float = 5.0
    scale_down_cooldown_seconds: float = 30.0


#: last traffic config a Runtime applied in this process (same handoff
#: contract as LAST_SERVING_TUNING: autoscalers built after the control
#: plane booted read a pre-existing ConfigMap's knobs from here).
LAST_TRAFFIC_TUNING: Optional[TrafficConfig] = None


@dataclasses.dataclass
class StorageConfig:
    """Tiered payload/KV storage knobs (``storage.*``; TPU-native
    addition, consumed live by
    :meth:`bobrapet_tpu.runtime.Runtime._apply_storage_tier` — a reload
    attaches/detaches/resizes the slice-local disk tier on the running
    StorageManager; in-flight run pins are replayed onto a tier
    attached mid-run)."""

    #: interpose a slice-local disk tier (L2) between the in-memory
    #: hydrate LRU and the backing provider
    #: (dotted: storage.disk-cache-enabled)
    disk_cache_enabled: bool = False
    #: slice-local mount the disk tier lives on; the native C++ blob
    #: cache is preferred, the Python layout is the fallback
    #: (dotted: storage.disk-cache-dir)
    disk_cache_dir: str = ""
    #: LRU eviction byte budget for the disk tier; 0 = unbounded
    #: (dotted: storage.disk-cache-bytes)
    disk_cache_bytes: int = 0


@dataclasses.dataclass
class TelemetryConfig:
    """Observability-plane knobs (``telemetry.*``; consumed live by
    :meth:`bobrapet_tpu.runtime.Runtime._apply_observability_toggles` —
    the flight recorder re-bounds its rings, the serving SLO judges
    read the new thresholds on the very next request, and the debug
    endpoints consult the live flag per request)."""

    #: per-run flight-recorder ring depth
    #: (dotted: telemetry.flight-recorder-depth)
    flight_recorder_depth: int = 256
    #: TTFT within-threshold budget for the serving SLO counters
    #: (dotted: telemetry.slo.ttft-threshold)
    slo_ttft_threshold_seconds: float = 2.0
    #: TPOT within-threshold budget (telemetry.slo.tpot-threshold)
    slo_tpot_threshold_seconds: float = 0.1
    #: serve /debug/runs/<id> + /debug/traces/<traceId> on the manager
    #: HTTP server (token-gated like /metrics)
    #: (dotted: telemetry.debug-endpoints)
    debug_endpoints: bool = True
    #: continuous control-plane profiler (observability/profiler.py):
    #: a sampling wall-clock profiler thread over this manager's own
    #: threads, served at /debug/profile with lock-wait attribution
    #: (dotted: telemetry.profiler-enabled; live — flipping it starts/
    #: stops the sampler thread)
    profiler_enabled: bool = False
    #: seconds between stack samples (telemetry.profiler-interval);
    #: the soak smoke bounds the default's cost at <2% steps/s
    profiler_interval_seconds: float = 0.02
    #: innermost frames kept per sampled stack
    #: (telemetry.profiler-depth)
    profiler_depth: int = 12


@dataclasses.dataclass
class EngramDefaults:
    """Operator->SDK defaults (reference: operator.go engram defaults)."""

    grpc_port: int = 50051
    max_inline_size: int = 16 * 1024
    storage_timeout_seconds: int = 30
    max_recursion_depth: int = 10
    debug: bool = False


@dataclasses.dataclass
class RetentionDefaults:
    """Two-phase retention (reference: shared_types.go:376-397 defaults)."""

    children_ttl_seconds: float = 3600.0  # children cleanup after terminal
    storyrun_retention_seconds: float = 86400.0  # then run record itself


@dataclasses.dataclass
class TimeoutDefaults:
    """Per-purpose wait timeouts (reference: controller_config.go:116-118)."""

    approval_seconds: float = 86400.0  # gate default timeout
    external_data_seconds: float = 3600.0  # wait default timeout
    conditional_seconds: float = 60.0
    step_seconds: float = 3600.0
    story_seconds: float = 0.0  # 0 = none


@dataclasses.dataclass
class StoreServiceConfig:
    """Store-service durability knobs (``store.*``; consumed live by the
    store-service process — a reload retunes the running journal's
    group-commit cap via :meth:`~bobrapet_tpu.store_service.journal.
    Journal.set_fsync_batch` without restarting the service)."""

    #: records that may share one group-committed fsync; 1 = per-record
    #: fsync, the durability-latency baseline the bench compares against
    #: (dotted: store.journal-fsync-batch)
    journal_fsync_batch: int = 64
    #: journal records between snapshot+truncate compactions — bounds
    #: crash-recovery replay length (dotted: store.snapshot-every-records)
    snapshot_every_records: int = 4096


@dataclasses.dataclass
class OperatorConfig:
    """The full operator config tree
    (reference: ControllerConfig controller_config.go:55-168)."""

    controllers: ControllerTuning = dataclasses.field(default_factory=ControllerTuning)
    scheduling: SchedulingConfig = dataclasses.field(default_factory=SchedulingConfig)
    templating: TemplatingSettings = dataclasses.field(default_factory=TemplatingSettings)
    dataplane: DataplaneConfig = dataclasses.field(default_factory=DataplaneConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    traffic: TrafficConfig = dataclasses.field(default_factory=TrafficConfig)
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    telemetry: TelemetryConfig = dataclasses.field(default_factory=TelemetryConfig)
    engram: EngramDefaults = dataclasses.field(default_factory=EngramDefaults)
    retention: RetentionDefaults = dataclasses.field(default_factory=RetentionDefaults)
    timeouts: TimeoutDefaults = dataclasses.field(default_factory=TimeoutDefaults)
    store: StoreServiceConfig = dataclasses.field(default_factory=StoreServiceConfig)
    reference_cross_namespace_policy: str = "deny"  # deny | grant | allow
    max_story_with_block_size_bytes: int = 256 * 1024
    default_retry_max: int = 3
    default_retry_delay: float = 5.0
    default_retry_max_delay: float = 300.0
    default_retry_jitter_pct: int = 10
    telemetry_enabled: bool = False
    step_output_logging: bool = False
    verbosity: int = 0

    def validate(self) -> list[str]:
        """(reference: ValidateControllerConfig operator config validation)"""
        errs = []
        if self.reference_cross_namespace_policy not in ("deny", "grant", "allow"):
            errs.append(
                f"referenceCrossNamespacePolicy must be deny|grant|allow, got "
                f"{self.reference_cross_namespace_policy!r}"
            )
        if self.controllers.max_concurrent_reconciles < 1:
            errs.append("controllers.maxConcurrentReconciles must be >= 1")
        if self.scheduling.queue_probe_interval <= 0:
            # 0 would turn every capacity-parked run into an immediate
            # hot requeue loop — the exact timer churn the event-driven
            # refill exists to avoid
            errs.append("scheduling.queue-probe-interval must be > 0")
        if len(set(self.scheduling.span_pools)) != len(self.scheduling.span_pools):
            # a duplicated pool would double its round-robin share and
            # silently skew the balanced replica distribution
            errs.append("scheduling.span-pools must not repeat a pool")
        if self.controllers.shard_count < 1:
            errs.append("controllers.shard-count must be >= 1")
        if not (0 <= self.controllers.shard_id < max(1, self.controllers.shard_count)):
            errs.append(
                f"controllers.shard-id must be in [0, shard-count), got "
                f"{self.controllers.shard_id} of {self.controllers.shard_count}"
            )
        for cname, width in self.controllers.per_controller.items():
            if width < 1:
                errs.append(
                    f"controllers.{cname}.max-concurrent-reconciles "
                    f"must be >= 1, got {width}"
                )
        if self.templating.evaluation_timeout <= 0:
            errs.append("templating.evaluationTimeout must be > 0")
        if self.dataplane.writer_max_batch < 1:
            errs.append("dataplane.writer-max-batch must be >= 1")
        if self.fleet.preemption_retry_cap < 0:
            errs.append("fleet.preemption-retry-cap must be >= 0")
        if self.fleet.quarantine_seconds < 0:
            errs.append("fleet.quarantine must be >= 0")
        if self.fleet.suspicion_threshold <= 0:
            errs.append("fleet.suspicion-threshold must be > 0")
        if self.fleet.suspicion_half_life_seconds <= 0:
            errs.append("fleet.suspicion-half-life must be > 0")
        if self.fleet.redrive_delay_seconds < 0:
            errs.append("fleet.redrive-delay must be >= 0")
        if self.serving.decode_horizon < 1:
            errs.append("serving.decode-horizon must be >= 1")
        if self.serving.dispatch_depth < 1:
            errs.append("serving.dispatch-depth must be >= 1")
        if self.serving.spec_k < 1:
            errs.append("serving.spec-k must be >= 1")
        if self.serving.role not in ("unified", "prefill", "decode"):
            errs.append(
                f"serving.role must be unified|prefill|decode, got "
                f"{self.serving.role!r}"
            )
        if self.serving.router_prefill_threshold < 0:
            errs.append("serving.router-prefill-threshold must be >= 0")
        try:
            # one validator, shared with the live queue swap: a weights
            # string the scheduler could not consume never validates
            from ..traffic.fairness import parse_tenant_weights

            parse_tenant_weights(self.serving.tenant_weights)
        except ValueError as e:
            errs.append(f"serving.tenant-weights invalid: {e}")
        if self.traffic.autoscale_interval_seconds <= 0:
            errs.append("traffic.autoscale-interval must be > 0")
        # the threshold/clamp relationships live in AutoscalePolicy so
        # the pure decision tests and the config plane agree exactly
        from ..traffic.autoscaler import AutoscalePolicy

        errs.extend(AutoscalePolicy.from_config(self.traffic).validate())
        if self.storage.disk_cache_bytes < 0:
            errs.append("storage.disk-cache-bytes must be >= 0")
        if self.storage.disk_cache_enabled and not self.storage.disk_cache_dir:
            # enabling a tier with no mount would silently stay flat —
            # the operator asked for a capability the config can't build
            errs.append(
                "storage.disk-cache-enabled requires storage.disk-cache-dir"
            )
        if self.telemetry.flight_recorder_depth < 8:
            # below ~8 records a ring cannot even hold one launch's
            # causal chain — the recorder would be on but useless
            errs.append("telemetry.flight-recorder-depth must be >= 8")
        if self.telemetry.slo_ttft_threshold_seconds <= 0:
            errs.append("telemetry.slo.ttft-threshold must be > 0")
        if self.telemetry.slo_tpot_threshold_seconds <= 0:
            errs.append("telemetry.slo.tpot-threshold must be > 0")
        if self.telemetry.profiler_interval_seconds <= 0:
            # 0 would turn the sampler into a busy loop — the exact
            # overhead the interval exists to bound
            errs.append("telemetry.profiler-interval must be > 0")
        if self.telemetry.profiler_depth < 1:
            errs.append("telemetry.profiler-depth must be >= 1")
        if self.engram.max_inline_size < 0:
            errs.append("engram.maxInlineSize must be >= 0")
        if self.store.journal_fsync_batch < 1:
            # 0 would mean "never fsync" — a durability knob must not be
            # able to disable durability by typo
            errs.append("store.journal-fsync-batch must be >= 1")
        if self.store.snapshot_every_records < 1:
            errs.append("store.snapshot-every-records must be >= 1")
        for qname, q in self.scheduling.queues.items():
            if q.max_concurrent < 0:
                errs.append(f"queue {qname}: maxConcurrent must be >= 0")
        return errs


# dotted-key -> setter table (the reference parses ~60 dotted ConfigMap
# keys, operator.go:385-1390; same addressing style here)
def _apply_dotted(cfg: OperatorConfig, key: str, value: str) -> bool:
    def fset(obj: Any, attr: str, conv: Callable[[str], Any]) -> bool:
        try:
            setattr(obj, attr, conv(value))
            return True
        except (ValueError, TypeError) as e:
            _log.warning("config key %s=%r invalid: %s", key, value, e)
            return False

    as_bool = lambda v: str(v).lower() in ("1", "true", "yes", "on")  # noqa: E731
    as_dur = lambda v: parse_duration(v, default=0.0)  # noqa: E731

    table: dict[str, Callable[[], bool]] = {
        "controllers.max-concurrent-reconciles": lambda: fset(cfg.controllers, "max_concurrent_reconciles", int),
        "controllers.requeue-base-delay": lambda: fset(cfg.controllers, "requeue_base_delay", as_dur),
        "controllers.requeue-max-delay": lambda: fset(cfg.controllers, "requeue_max_delay", as_dur),
        "controllers.reconcile-timeout": lambda: fset(cfg.controllers, "reconcile_timeout", as_dur),
        "controllers.shard-count": lambda: fset(cfg.controllers, "shard_count", int),
        "controllers.shard-id": lambda: fset(cfg.controllers, "shard_id", int),
        "scheduling.global-max-concurrent-steps": lambda: fset(cfg.scheduling, "global_max_concurrent_steps", int),
        "scheduling.queue-probe-interval": lambda: fset(cfg.scheduling, "queue_probe_interval", as_dur),
        "scheduling.span-pools": lambda: fset(
            cfg.scheduling, "span_pools",
            lambda v: [p.strip() for p in str(v).split(",") if p.strip()],
        ),
        "scheduling.span-spill": lambda: fset(cfg.scheduling, "span_spill", as_bool),
        "templating.evaluation-timeout": lambda: fset(cfg.templating, "evaluation_timeout", as_dur),
        "templating.max-output-bytes": lambda: fset(cfg.templating, "max_output_bytes", int),
        "templating.deterministic": lambda: fset(cfg.templating, "deterministic", as_bool),
        "templating.offloaded-data-policy": lambda: fset(
            cfg.templating, "offloaded_data_policy", OffloadedDataPolicy
        ),
        "templating.materialize-engram": lambda: fset(cfg.templating, "materialize_engram", str),
        "dataplane.writer-max-batch": lambda: fset(cfg.dataplane, "writer_max_batch", int),
        "dataplane.coalesce-acks": lambda: fset(cfg.dataplane, "coalesce_acks", as_bool),
        "fleet.preemption-retry-cap": lambda: fset(cfg.fleet, "preemption_retry_cap", int),
        "fleet.redrive-delay": lambda: fset(cfg.fleet, "redrive_delay_seconds", as_dur),
        "fleet.quarantine": lambda: fset(cfg.fleet, "quarantine_seconds", as_dur),
        "fleet.max-quarantine-multiplier": lambda: fset(cfg.fleet, "max_quarantine_multiplier", float),
        "fleet.suspicion-threshold": lambda: fset(cfg.fleet, "suspicion_threshold", float),
        "fleet.suspicion-half-life": lambda: fset(cfg.fleet, "suspicion_half_life_seconds", as_dur),
        "fleet.heartbeat-timeout": lambda: fset(cfg.fleet, "heartbeat_timeout_seconds", as_dur),
        "fleet.fail-fast": lambda: fset(cfg.fleet, "fail_fast", as_bool),
        "fleet.gke-spot": lambda: fset(cfg.fleet, "gke_spot", as_bool),
        "fleet.termination-grace": lambda: fset(cfg.fleet, "termination_grace_seconds", as_dur),
        "serving.decode-horizon": lambda: fset(cfg.serving, "decode_horizon", int),
        "serving.dispatch-depth": lambda: fset(cfg.serving, "dispatch_depth", int),
        "serving.spec-k": lambda: fset(cfg.serving, "spec_k", int),
        "serving.prefix-cache-shared": lambda: fset(cfg.serving, "prefix_cache_shared", as_bool),
        "serving.role": lambda: fset(cfg.serving, "role", str),
        "serving.router-prefill-threshold": lambda: fset(cfg.serving, "router_prefill_threshold", int),
        "serving.router-prefix-affinity": lambda: fset(cfg.serving, "router_prefix_affinity", as_bool),
        "serving.tenant-weights": lambda: fset(cfg.serving, "tenant_weights", str),
        "traffic.autoscale-enabled": lambda: fset(cfg.traffic, "autoscale_enabled", as_bool),
        "traffic.autoscale-interval": lambda: fset(cfg.traffic, "autoscale_interval_seconds", as_dur),
        "traffic.min-replicas": lambda: fset(cfg.traffic, "min_replicas", int),
        "traffic.max-replicas": lambda: fset(cfg.traffic, "max_replicas", int),
        "traffic.scale-up-burn": lambda: fset(cfg.traffic, "scale_up_burn", float),
        "traffic.scale-down-burn": lambda: fset(cfg.traffic, "scale_down_burn", float),
        "traffic.scale-up-queue-wait": lambda: fset(cfg.traffic, "scale_up_queue_wait_seconds", as_dur),
        "traffic.scale-down-queue-wait": lambda: fset(cfg.traffic, "scale_down_queue_wait_seconds", as_dur),
        "traffic.queue-depth-per-replica": lambda: fset(cfg.traffic, "queue_depth_per_replica", int),
        "traffic.scale-up-cooldown": lambda: fset(cfg.traffic, "scale_up_cooldown_seconds", as_dur),
        "traffic.scale-down-cooldown": lambda: fset(cfg.traffic, "scale_down_cooldown_seconds", as_dur),
        "store.journal-fsync-batch": lambda: fset(cfg.store, "journal_fsync_batch", int),
        "store.snapshot-every-records": lambda: fset(cfg.store, "snapshot_every_records", int),
        "storage.disk-cache-enabled": lambda: fset(cfg.storage, "disk_cache_enabled", as_bool),
        "storage.disk-cache-dir": lambda: fset(cfg.storage, "disk_cache_dir", str),
        "storage.disk-cache-bytes": lambda: fset(cfg.storage, "disk_cache_bytes", int),
        "engram.grpc-port": lambda: fset(cfg.engram, "grpc_port", int),
        "engram.max-inline-size": lambda: fset(cfg.engram, "max_inline_size", int),
        "engram.storage-timeout-seconds": lambda: fset(cfg.engram, "storage_timeout_seconds", int),
        "engram.max-recursion-depth": lambda: fset(cfg.engram, "max_recursion_depth", int),
        "engram.debug": lambda: fset(cfg.engram, "debug", as_bool),
        "retention.children-ttl": lambda: fset(cfg.retention, "children_ttl_seconds", as_dur),
        "retention.storyrun-retention": lambda: fset(cfg.retention, "storyrun_retention_seconds", as_dur),
        "timeouts.approval": lambda: fset(cfg.timeouts, "approval_seconds", as_dur),
        "timeouts.external-data": lambda: fset(cfg.timeouts, "external_data_seconds", as_dur),
        "timeouts.conditional": lambda: fset(cfg.timeouts, "conditional_seconds", as_dur),
        "timeouts.step": lambda: fset(cfg.timeouts, "step_seconds", as_dur),
        "timeouts.story": lambda: fset(cfg.timeouts, "story_seconds", as_dur),
        "reference-cross-namespace-policy": lambda: fset(cfg, "reference_cross_namespace_policy", str),
        "max-story-with-block-size-bytes": lambda: fset(cfg, "max_story_with_block_size_bytes", int),
        "retry.default-max": lambda: fset(cfg, "default_retry_max", int),
        "retry.default-delay": lambda: fset(cfg, "default_retry_delay", as_dur),
        "retry.default-max-delay": lambda: fset(cfg, "default_retry_max_delay", as_dur),
        "retry.default-jitter-pct": lambda: fset(cfg, "default_retry_jitter_pct", int),
        "telemetry.enabled": lambda: fset(cfg, "telemetry_enabled", as_bool),
        "telemetry.flight-recorder-depth": lambda: fset(cfg.telemetry, "flight_recorder_depth", int),
        "telemetry.slo.ttft-threshold": lambda: fset(cfg.telemetry, "slo_ttft_threshold_seconds", as_dur),
        "telemetry.slo.tpot-threshold": lambda: fset(cfg.telemetry, "slo_tpot_threshold_seconds", as_dur),
        "telemetry.debug-endpoints": lambda: fset(cfg.telemetry, "debug_endpoints", as_bool),
        "telemetry.profiler-enabled": lambda: fset(cfg.telemetry, "profiler_enabled", as_bool),
        "telemetry.profiler-interval": lambda: fset(cfg.telemetry, "profiler_interval_seconds", as_dur),
        "telemetry.profiler-depth": lambda: fset(cfg.telemetry, "profiler_depth", int),
        "logging.step-output": lambda: fset(cfg, "step_output_logging", as_bool),
        "logging.verbosity": lambda: fset(cfg, "verbosity", int),
    }
    fn = table.get(key)
    if fn is not None:
        return fn()
    parts = key.split(".")
    # per-controller pool width: controllers.<name>.max-concurrent-reconciles
    # (reference: the per-controller MaxConcurrentReconciles families,
    # operator.go:447-528); consumed live by ControllerManager.apply_config
    if (
        len(parts) == 3
        and parts[0] == "controllers"
        and parts[2] == "max-concurrent-reconciles"
    ):
        try:
            cfg.controllers.per_controller[parts[1]] = int(value)
            return True
        except (ValueError, TypeError) as e:
            _log.warning("config key %s=%r invalid: %s", key, value, e)
            return False
    # queue keys: scheduling.queue.<name>.<field>
    if len(parts) == 4 and parts[0] == "scheduling" and parts[1] == "queue":
        qname, field = parts[2], parts[3]
        q = cfg.scheduling.queues.setdefault(qname, QueueConfig(name=qname))
        if field == "max-concurrent":
            return fset(q, "max_concurrent", int)
        if field == "priority-aging":
            return fset(q, "priority_aging_seconds", as_dur)
        if field == "accelerator":
            return fset(q, "accelerator", str)
        if field == "chip-budget":
            return fset(q, "chip_budget", int)
    _log.debug("unknown config key %s ignored", key)
    return False


def parse_config(data: dict[str, str]) -> OperatorConfig:
    """Parse a flat dotted-key map into an OperatorConfig; invalid values
    keep their defaults (reference tolerates per-key failures)."""
    cfg = OperatorConfig()
    for key in sorted(data):
        _apply_dotted(cfg, key, data[key])
    errs = cfg.validate()
    if errs:
        _log.warning("operator config has %d invalid fields: %s", len(errs), errs)
    return cfg


class OperatorConfigManager:
    """Holds the live config; watches the ConfigMap resource for reloads
    (reference: operator.go:356-383 — the manager is a reconciler on the
    operator ConfigMap)."""

    def __init__(
        self,
        store: Optional[ResourceStore] = None,
        namespace: str = "bobrapet-system",
        name: str = "operator-config",
        initial: Optional[OperatorConfig] = None,
    ):
        self._lock = threading.Lock()
        self._config = initial or OperatorConfig()
        self._subscribers: list[Callable[[OperatorConfig], None]] = []
        self._namespace = namespace
        self._name = name
        if store is not None:
            existing = store.try_get(CONFIG_MAP_KIND, namespace, name)
            if existing is not None:
                # same last-good-config gate as reloads: an invalid initial
                # ConfigMap leaves the defaults active
                self._maybe_swap(existing.spec.get("data") or {})
            store.watch(self._on_event, kinds=[CONFIG_MAP_KIND])

    @property
    def config(self) -> OperatorConfig:
        with self._lock:
            return self._config

    def subscribe(self, fn: Callable[[OperatorConfig], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def _on_event(self, ev: WatchEvent) -> None:
        if ev.type not in (ADDED, MODIFIED):
            return
        r: Resource = ev.resource
        if r.meta.namespace != self._namespace or r.meta.name != self._name:
            return
        self._maybe_swap(r.spec.get("data") or {})

    def _maybe_swap(self, data: dict[str, str]) -> None:
        new = parse_config(data)
        if new.validate():
            # invalid configs are logged but the prior good config stays
            # active (the reference keeps serving the last valid config)
            return
        self._swap(new)

    def _swap(self, cfg: OperatorConfig) -> None:
        with self._lock:
            self._config = cfg
            subs = list(self._subscribers)
        for fn in subs:
            try:
                fn(cfg)
            except Exception:  # noqa: BLE001
                _log.exception("config subscriber failed")
