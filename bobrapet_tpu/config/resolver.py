"""Hierarchical execution-config resolution.

Capability parity with the reference's Resolver
(reference: internal/config/resolver.go:113,257): one step's effective
execution config is the layered merge

    operator defaults
      -> EngramTemplate.executionPolicy   (template recommendations)
      -> Engram.execution                 (instance overrides)
      -> Story.policy.execution + Step.execution
      -> StepRun.executionOverrides       (runtime overrides)

Later layers win field-by-field; nested policies merge recursively (a
layer that sets only ``retry.maxRetries`` inherits the rest).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Optional

from ..api.shared import (
    CachePolicy,
    ExecutionOverrides,
    ExecutionPolicy,
    JobPolicy,
    PlacementPolicy,
    ProbeOverrides,
    ResourcePolicy,
    RetryPolicy,
    SecurityPolicy,
    StoragePolicy,
    TPUPolicy,
    WorkloadSpec,
)
from ..observability.metrics import metrics
from ..utils.duration import parse_duration
from .operator import OperatorConfig


@contextlib.contextmanager
def _stage(name: str):
    """Per-stage resolution observability (reference: stage chain with
    metrics observer, internal/config/chain/chain.go:14-60)."""
    started = time.monotonic()
    try:
        yield
    finally:
        metrics.resolver_stages.inc(name)
        metrics.resolver_stage_duration.observe(time.monotonic() - started, name)


@dataclasses.dataclass
class ResolvedExecutionConfig:
    """The flattened result (reference: resolver.go:171)."""

    image: Optional[str] = None
    entrypoint: Optional[str] = None
    image_pull_policy: Optional[str] = None
    resources: Optional[ResourcePolicy] = None
    security: Optional[SecurityPolicy] = None
    placement: Optional[PlacementPolicy] = None
    probes: Optional[ProbeOverrides] = None
    job: Optional[JobPolicy] = None
    workload: Optional[WorkloadSpec] = None
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    timeout_seconds: Optional[float] = None
    storage: Optional[StoragePolicy] = None
    cache: Optional[CachePolicy] = None
    tpu: Optional[TPUPolicy] = None
    max_inline_size: int = 16 * 1024
    max_recursion_depth: int = 10
    service_account_name: Optional[str] = None
    debug: bool = False


def _merge_spec(base, override):
    """Recursive field-wise merge of two SpecBase instances (same type);
    override's non-None fields win, nested SpecBase fields merge."""
    if base is None:
        return override
    if override is None:
        return base
    from ..api.specbase import SpecBase

    kwargs = {}
    for f in dataclasses.fields(base):
        b, o = getattr(base, f.name), getattr(override, f.name)
        if isinstance(b, SpecBase) and isinstance(o, SpecBase):
            kwargs[f.name] = _merge_spec(b, o)
        elif isinstance(o, dict) and isinstance(b, dict):
            kwargs[f.name] = {**b, **o}
        elif o is not None and o != [] and o != {}:
            kwargs[f.name] = o
        else:
            kwargs[f.name] = b
    return type(base)(**kwargs)


class Resolver:
    """(reference: internal/config/resolver.go:113)"""

    def __init__(self, operator_config: OperatorConfig):
        self.operator_config = operator_config

    def resolve(
        self,
        template_spec=None,  # api.catalog.EngramTemplateSpec
        engram_spec=None,  # api.engram.EngramSpec
        story_policy=None,  # api.story.StoryPolicy
        step=None,  # api.story.Step
        steprun_overrides: Optional[ExecutionOverrides] = None,
    ) -> ResolvedExecutionConfig:
        """Merge all layers into one ResolvedExecutionConfig
        (reference: ResolveExecutionConfig resolver.go:257)."""
        cfg = self.operator_config
        out = ResolvedExecutionConfig(
            retry=RetryPolicy(
                max_retries=cfg.default_retry_max,
                delay=f"{cfg.default_retry_delay}s",
                max_delay=f"{cfg.default_retry_max_delay}s",
                jitter=cfg.default_retry_jitter_pct,
            ),
            timeout_seconds=cfg.timeouts.step_seconds or None,
            max_inline_size=cfg.engram.max_inline_size,
            max_recursion_depth=cfg.engram.max_recursion_depth,
            debug=cfg.engram.debug,
        )

        # layer 2: template recommendations
        if template_spec is not None:
            with _stage("template"):
                out.image = template_spec.image or out.image
                out.entrypoint = template_spec.entrypoint or out.entrypoint
                self._apply_policy(out, template_spec.execution_policy)

        # layer 3: engram instance
        if engram_spec is not None:
            with _stage("engram"):
                self._apply_overrides(out, engram_spec.execution)
                if engram_spec.workload is not None:
                    out.workload = _merge_spec(out.workload, engram_spec.workload)

        # layer 4: story policy + step
        if story_policy is not None:
            with _stage("story"):
                self._apply_policy(out, story_policy.execution)
                if story_policy.storage is not None:
                    out.storage = _merge_spec(out.storage, story_policy.storage)
                if story_policy.timeouts is not None and story_policy.timeouts.step:
                    out.timeout_seconds = parse_duration(story_policy.timeouts.step)
                if (
                    story_policy.retries is not None
                    and story_policy.retries.step_retry_policy is not None
                ):
                    out.retry = _merge_spec(out.retry, story_policy.retries.step_retry_policy)
        if step is not None:
            with _stage("step"):
                self._apply_overrides(out, step.execution)
                if step.tpu is not None:
                    out.tpu = _merge_spec(out.tpu, step.tpu)

        # layer 5: steprun runtime overrides
        if steprun_overrides is not None:
            with _stage("steprun"):
                self._apply_overrides(out, steprun_overrides)

        if out.storage is not None and out.storage.max_inline_size is not None:
            out.max_inline_size = out.storage.max_inline_size
        return out

    @staticmethod
    def _apply_policy(out: ResolvedExecutionConfig, pol: Optional[ExecutionPolicy]) -> None:
        if pol is None:
            return
        out.resources = _merge_spec(out.resources, pol.resources)
        out.security = _merge_spec(out.security, pol.security)
        out.placement = _merge_spec(out.placement, pol.placement)
        out.probes = _merge_spec(out.probes, pol.probes)
        out.job = _merge_spec(out.job, pol.job)
        out.retry = _merge_spec(out.retry, pol.retry)
        out.storage = _merge_spec(out.storage, pol.storage)
        out.cache = _merge_spec(out.cache, pol.cache)
        if pol.timeout:
            out.timeout_seconds = parse_duration(pol.timeout)
        if pol.max_recursion_depth is not None:
            out.max_recursion_depth = pol.max_recursion_depth
        if pol.service_account_name:
            out.service_account_name = pol.service_account_name
        if pol.placement is not None and pol.placement.tpu is not None:
            out.tpu = _merge_spec(out.tpu, pol.placement.tpu)

    @staticmethod
    def _apply_overrides(
        out: ResolvedExecutionConfig, ov: Optional[ExecutionOverrides]
    ) -> None:
        if ov is None:
            return
        if ov.image:
            out.image = ov.image
        if ov.image_pull_policy:
            out.image_pull_policy = ov.image_pull_policy
        out.security = _merge_spec(out.security, ov.security)
        out.placement = _merge_spec(out.placement, ov.placement)
        out.probes = _merge_spec(out.probes, ov.probes)
        out.retry = _merge_spec(out.retry, ov.retry)
        out.storage = _merge_spec(out.storage, ov.storage)
        out.cache = _merge_spec(out.cache, ov.cache)
        if ov.workload is not None:
            out.workload = _merge_spec(out.workload, ov.workload)
        if ov.timeout:
            out.timeout_seconds = parse_duration(ov.timeout)
        if ov.max_inline_size is not None:
            out.max_inline_size = ov.max_inline_size
        if ov.service_account_name:
            out.service_account_name = ov.service_account_name
        if ov.debug is not None:
            out.debug = ov.debug
        if ov.placement is not None and ov.placement.tpu is not None:
            out.tpu = _merge_spec(out.tpu, ov.placement.tpu)
