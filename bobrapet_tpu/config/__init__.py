"""Operator config + hierarchical execution resolution."""

from .operator import (
    CONFIG_MAP_KIND,
    ControllerTuning,
    EngramDefaults,
    FleetConfig,
    OperatorConfig,
    OperatorConfigManager,
    QueueConfig,
    RetentionDefaults,
    SchedulingConfig,
    TemplatingSettings,
    TimeoutDefaults,
    parse_config,
)
from .resolver import ResolvedExecutionConfig, Resolver

__all__ = [
    "CONFIG_MAP_KIND",
    "ControllerTuning",
    "EngramDefaults",
    "FleetConfig",
    "OperatorConfig",
    "OperatorConfigManager",
    "QueueConfig",
    "RetentionDefaults",
    "SchedulingConfig",
    "TemplatingSettings",
    "TimeoutDefaults",
    "parse_config",
    "ResolvedExecutionConfig",
    "Resolver",
]
