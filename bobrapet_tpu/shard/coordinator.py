"""The "shard" controller each manager runs: membership + rebalance.

One reconcile key (``bobrapet-system/shard-map``) drives a small state
machine on every manager, at ``heartbeat_interval`` cadence and on any
ShardMap/ShardMember event:

1. **membership heartbeat** — renew this shard's ShardMember resource
   (leaving members keep renewing, flagged ``leaving``, until retired —
   they must stay ack-capable through the barrier). Renewals run on a
   **dedicated thread**, not just the dispatcher: a flooded queue would
   starve the beat past ``member_ttl`` and the leader would declare a
   live member dead (measured as real double-reconciles in the churn
   soak). The member-side half of that contract is the **self-fence**:
   when this member's own renewal goes stale past ``member_ttl / 2``
   the gate parks all family work until a renewal lands — so by the
   time survivors may promote past a presumed-dead member (one full
   TTL), it has refused new work for at least half of it. Non-overlap
   is therefore guaranteed for reconciles shorter than
   ``member_ttl / 2``; size the TTL accordingly;
2. **leader election** — a fenced ``shard-leader`` lease
   (``utils/leader.py``); the holder publishes a new ShardMap whenever
   the alive-member set differs from the published one (join, leave,
   heartbeat expiry — crash detection is just lease-style TTL on the
   member resources);
3. **rebalance barrier** — on observing a newer map epoch every member
   installs it as the router's pending ring, finishes in-flight
   reconciles for families it is losing (the dispatcher gate already
   refuses NEW work for them), then acks ``status.acks[shard] = epoch``.
   When every required member (new members + old members still alive)
   has acked, each member independently promotes pending -> active,
   releases parked keys, and resyncs the families it gained — so a run
   that went quiet mid-handoff is picked up without an event. No run is
   ever reconciled by two shards: the loser drains before the ack, the
   gainer parks until the promote (tests assert this with
   :class:`~bobrapet_tpu.shard.detector.DoubleReconcileDetector`).

The reference has nothing to compare against here — its operator shape
is deliberately single-active (internal/config/operator.go); this is
the scale-out past it (ROADMAP item 1).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..analysis.racedetect import guarded_state
from ..api.enums import is_nonterminal_phase
from ..api.runs import STEP_RUN_KIND, STORY_RUN_KIND
from ..controllers.step_executor import parse_trace_annotation
from ..core.store import AlreadyExists, Conflict, NotFound, ResourceStore
from ..observability.metrics import metrics
from ..observability.timeline import FLIGHT
from ..utils.leader import LeaseLeaderElector
from .map import (
    SHARD_LEASE_NAME,
    SHARD_MAP_KIND,
    SHARD_MAP_NAME,
    SHARD_MEMBER_KIND,
    SHARD_NAMESPACE,
    ShardMapPublisher,
    make_member,
    map_epoch,
    map_members,
    register_shard_admission,
)
from .ring import DEFAULT_VNODES
from .router import (
    ADMIT_OWN,
    ADMIT_PARK,
    LABEL_STORY_RUN,
    _AUX_CONTROLLER_KIND,
    _DEF_CONTROLLER_KIND,
    ShardRouter,
)

_log = logging.getLogger(__name__)

SHARD_CONTROLLER = "shard"


@guarded_state("_parked_labels")
class ShardCoordinator:
    """Runs inside one manager process; see module docstring."""

    def __init__(
        self,
        store: ResourceStore,
        router: ShardRouter,
        manager,
        recorder=None,
        clock=None,
        namespace: str = SHARD_NAMESPACE,
        heartbeat_interval: float = 2.0,
        member_ttl: float = 6.0,
        lease_duration: float = 10.0,
        resync_every: int = 10,
        vnodes: int = DEFAULT_VNODES,
    ):
        self.store = store
        self.router = router
        self.manager = manager
        self.recorder = recorder
        self.clock = clock or manager.clock
        self.namespace = namespace
        self.heartbeat_interval = float(heartbeat_interval)
        self.member_ttl = float(member_ttl)
        self.resync_every = max(1, int(resync_every))
        #: parked keys re-probe the gate at this cadence while a
        #: barrier is in flight
        self.park_delay = min(0.1, self.heartbeat_interval / 2)
        self.elector = LeaseLeaderElector(
            store,
            name=SHARD_LEASE_NAME,
            namespace=namespace,
            lease_duration=lease_duration,
            identity=f"shard-{router.me}",
            clock=self.clock,
        )
        self.publisher = ShardMapPublisher(
            store, self.elector, namespace=namespace, vnodes=vnodes
        )
        register_shard_admission(store, namespace=namespace)
        self._leaving = False
        self._retired = False
        self._acked_epoch = 0
        self._tick = 0
        #: gauge labels set by the last _update_parked_gauge pass;
        #: written from every dispatcher worker, hence its own lock
        self._parked_labels: set[str] = set()
        self._gauge_lock = threading.Lock()
        #: last wall-clock write of the member/lease heartbeats. Event-
        #: triggered reconciles (map changes, member joins) run the
        #: read-only state machine at full cadence but must NOT write a
        #: heartbeat each time: a renewal is itself a bus event that
        #: wakes every other coordinator, and unthrottled that feedback
        #: loop saturates the store with renewals (measured: it starved
        #: coordinator ticks past member_ttl and caused false deaths)
        self._last_beat = float("-inf")
        #: membership heartbeats CANNOT ride the dispatcher alone: the
        #: shard controller's reconcile competes with run work, and a
        #: flooded queue starves the renewal past member_ttl — the
        #: leader then declares a live member dead, promotes without
        #: its ack, and two shards reconcile one family (measured: 116
        #: double-reconciles in the churn soak). A dedicated renewal
        #: thread (started in register, kube leader-election's own
        #: shape) keeps liveness independent of dispatch latency; the
        #: reconcile's opportunistic beat stays as a cheap backstop.
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        #: last renewal that REACHED the bus; the self-fence reads this
        self._last_renew_ok = self.clock.now()
        #: self-fence margin: past this renewal staleness the leader
        #: may declare us dead at any moment, so the gate parks all
        #: family work (we cannot assume we still own anything). Half
        #: the TTL leaves the other half for in-flight reconciles to
        #: finish before survivors can promote past us — non-overlap is
        #: guaranteed for reconciles shorter than member_ttl/2.
        self._fence_after = self.member_ttl / 2

    # -- wiring ------------------------------------------------------------
    def register(self) -> None:
        """Register the shard controller + the handoff observer on the
        manager this coordinator serves."""

        def to_map_key(ev):
            return [(self.namespace, SHARD_MAP_NAME)]

        def member_to_map_key(ev):
            # membership CHANGES matter immediately (join/crash cleanup);
            # renew-only MODIFIED events are other coordinators'
            # heartbeats — reacting to each would couple every
            # coordinator to every other's cadence (liveness expiry is
            # caught by this controller's own timed requeue)
            if ev.type == "MODIFIED":
                return []
            return [(self.namespace, SHARD_MAP_NAME)]

        self.manager.register(
            SHARD_CONTROLLER,
            self.reconcile,
            watches={SHARD_MAP_KIND: to_map_key,
                     SHARD_MEMBER_KIND: member_to_map_key},
            max_concurrent=1,
        )
        self.store.watch(self._on_storyrun_added, kinds=[STORY_RUN_KIND])
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"shard-{self.router.me}-heartbeat",
            daemon=True,
        )
        self._hb_thread.start()
        self.kick()

    def kick(self) -> None:
        self.manager.enqueue(SHARD_CONTROLLER, self.namespace, SHARD_MAP_NAME)

    # -- the dispatcher gate ----------------------------------------------
    def gate(self, controller: str, ns: str, name: str) -> Optional[float]:
        """controllers/manager.py reconcile_gate: None = run it,
        >= 0 = park (requeue after that delay), < 0 = drop."""
        verdict, root = self.router.classify(controller, ns, name)
        key = (controller, ns, name)
        if root is not None and self._self_fenced():
            # renewal stale past the safety margin: the leader may have
            # already declared us dead and handed our families to
            # survivors — starting work now risks the double-reconcile
            # the barrier exists to prevent. Park until a renewal lands.
            if self.router.park(key):
                self._update_parked_gauge()
                metrics.shard_self_fenced.inc(self.router.me)
            return self.park_delay
        if verdict == ADMIT_OWN:
            if self.router.unpark(key):
                # released from a self-fence (barrier parks are cleared
                # wholesale at promote) — drop the gauge entry
                self._update_parked_gauge()
            return None
        if verdict == ADMIT_PARK:
            if self.router.park(key):
                self._update_parked_gauge()
            return self.park_delay
        if self.router.unpark(key):
            self._update_parked_gauge()
        return -1.0

    def _update_parked_gauge(self) -> None:
        counts: dict[str, int] = {}
        for controller, _ns, _name in self.router.parked_snapshot():
            counts[controller] = counts.get(controller, 0) + 1
        # gate() runs on every dispatcher worker: _parked_labels and the
        # zero-out pass must not interleave between workers
        with self._gauge_lock:
            # zero labels that emptied, or the gauge would read "parked"
            # forever after the barrier clears
            for stale in self._parked_labels - counts.keys():
                metrics.shard_parked_keys.set(0, stale)
            for controller, n in counts.items():
                metrics.shard_parked_keys.set(n, controller)
            self._parked_labels = set(counts)

    # -- cross-shard handoff accounting -----------------------------------
    def _on_storyrun_added(self, ev) -> None:
        # the store's default filter already scopes this to families we
        # have an interest in; count the ones we OWN whose parent lives
        # on another shard — an accepted executeStory handoff
        if ev.type != "ADDED":
            return
        r = ev.resource
        parent = r.meta.labels.get(LABEL_STORY_RUN)
        if not parent:
            return
        ns = r.meta.namespace
        if not self.router.owns_run(ns, r.meta.name):
            return
        if self.router.owner_of(f"{ns}/{parent}") == self.router.me:
            return
        metrics.shard_handoffs.inc(self.router.me)
        # trace context rides the handoff edge (the parent's trace is
        # annotated onto the child by the step executor) — the event AND
        # the flight-recorder record carry the ids, so the cross-shard
        # hop is queryable inside the ONE run trace
        trace = parse_trace_annotation(r.meta) or {}
        trace_note = (
            f" trace {trace.get('traceId')}/{trace.get('spanId')}"
            if trace.get("traceId") else ""
        )
        FLIGHT.record(
            ns, r.meta.name, "handoff",
            message=f"accepted by shard {self.router.me} (parent {parent} "
                    f"on shard {self.router.owner_of(f'{ns}/{parent}')})",
            trace_id=trace.get("traceId"), span_id=trace.get("spanId"),
            shard=self.router.me, at=self.clock.now(),
        )
        if self.recorder is not None:
            self.recorder.normal(
                r, "CrossShardHandoff",
                f"child of {parent} (shard "
                f"{self.router.owner_of(f'{ns}/{parent}')}) accepted"
                + trace_note,
            )

    # -- lifecycle ---------------------------------------------------------
    @property
    def retired(self) -> bool:
        return self._retired

    def request_leave(self) -> None:
        """Graceful leave: flag the member resource so the leader
        republishes without us; this coordinator keeps heartbeating and
        acking until the barrier that removes it clears."""
        self._leaving = True
        self.kick()

    def crash(self) -> None:
        """Test support: die WITHOUT releasing the lease or the member
        — the abrupt death the TTL-expiry and stale-leader fencing
        paths exist for. A subsequent stop() releases nothing (a
        crashed process cannot run cleanup)."""
        self._crashed = True
        self._hb_stop.set()
        if (self._hb_thread is not None
                and self._hb_thread is not threading.current_thread()):
            self._hb_thread.join(timeout=5.0)

    def stop(self) -> None:
        if getattr(self, "_crashed", False):
            return
        # the renewal thread must die with the runtime, or a "crashed"
        # shard would keep its member resource fresh forever and the
        # leader could never detect the death. JOIN it before releasing
        # the lease: an in-flight _beat -> elector.heartbeat() landing
        # after the release would steal the lease straight back and
        # leave this dead process as leaseholder for a full TTL.
        self._hb_stop.set()
        if (self._hb_thread is not None
                and self._hb_thread is not threading.current_thread()):
            self._hb_thread.join(timeout=5.0)
        self.elector.release()

    # -- the reconcile -----------------------------------------------------
    def reconcile(self, ns: str, name: str) -> Optional[float]:
        now = self.clock.now()
        self._tick += 1
        # write-side heartbeats at their own cadence only (see
        # _last_beat); event-triggered runs are read-mostly. The
        # dedicated renewal thread is the primary beat — this is the
        # backstop for clock shapes with no live thread (ManualClock
        # pumps drive time through reconciles alone).
        if now - self._last_beat >= self.heartbeat_interval * 0.5:
            self._beat(now)
        if self.elector.is_leader:
            self._leader_duties(now)
        self._observe_map(now)
        if self.router.rebalancing:
            self._advance_barrier(now)
        else:
            self._confirm_promoted()
            if self._tick % self.resync_every == 0:
                self._resync_definitions()
                self._refresh_owned_gauge()
        if self._retired:
            return None  # nothing left to coordinate; stop requeueing
        return self.heartbeat_interval

    # -- membership --------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        """Dedicated renewal thread (see __init__): member + lease
        heartbeats at half cadence, never queued behind run work."""
        while not self._hb_stop.wait(self.heartbeat_interval * 0.5):
            if self._retired:
                return
            try:
                self._beat(self.clock.now())
            except Exception:  # noqa: BLE001 - liveness must survive transient bus errors
                _log.exception("shard %s heartbeat failed", self.router.me)

    def _beat(self, now: float) -> None:
        """One member-renew + lease-heartbeat pass. Deliberately
        lock-free: the renewal thread and the reconcile backstop may
        overlap, but each store write is individually atomic and both
        writers renew the SAME member with near-identical timestamps —
        the retire race is handled by joining the thread instead
        (:meth:`_retire`), so no lock spans a bus call."""
        if self._retired:
            return
        self._last_beat = now
        self._heartbeat_member(now)
        self.elector.heartbeat()

    def _self_fenced(self) -> bool:
        """True when this member's last SUCCESSFUL renewal is stale
        past the safety margin — the member-side half of the fencing
        contract (a paused-then-resumed manager must not touch family
        work until it has proven it is still in the map)."""
        if self._retired:
            return False
        return self.clock.now() - self._last_renew_ok > self._fence_after

    def _heartbeat_member(self, now: float) -> None:
        me = self.router.me

        def renew(r) -> None:
            r.spec["renewTime"] = now
            if self._leaving:
                r.spec["leaving"] = True

        try:
            self.store.mutate(SHARD_MEMBER_KIND, self.namespace, me, renew)
        except NotFound:
            member = make_member(me, now, self.namespace)
            if self._leaving:
                member.spec["leaving"] = True
            try:
                self.store.create(member)
            except AlreadyExists:
                return  # another writer holds our name; retry next beat
        except Conflict:
            return  # next beat renews
        self._last_renew_ok = now

    def _alive_members(self, now: float) -> dict[str, dict]:
        """shard id -> member spec for members with a fresh heartbeat
        (self is always alive)."""
        out: dict[str, dict] = {}
        for m in self.store.list_views(SHARD_MEMBER_KIND, self.namespace):
            renew = float(m.spec.get("renewTime") or 0.0)
            if m.meta.name == self.router.me or renew + self.member_ttl >= now:
                out[m.meta.name] = m.spec
        return out

    def _leader_duties(self, now: float) -> None:
        alive = self._alive_members(now)
        desired = sorted(
            mid for mid, spec in alive.items() if not spec.get("leaving")
        )
        if not desired:
            return
        current = self.store.try_get_view(
            SHARD_MAP_KIND, self.namespace, SHARD_MAP_NAME
        )
        if current is not None and map_members(current) == desired:
            return
        if current is None and self._tick < 2:
            # first-publish grace: peers started in the same instant may
            # not have heartbeated yet — publishing a solo map now would
            # churn a shrink+grow rebalance pair for nothing
            return
        if current is not None:
            # serialize rebalances: publishing epoch N+1 while N's
            # barrier is still in flight lets members straddle THREE
            # rings (a laggard's active N-1 + pending N+1 vs a fast
            # peer's active N), and the two-ring own/park/drop gate is
            # only sound pairwise — measured as real double-reconciles
            # when a join+leave pair made ring N+1 == ring N-1. Wait
            # until every ALIVE member has promoted the current epoch
            # (crashed members are exempt, or a death would wedge the
            # map forever).
            epoch = map_epoch(current)
            promoted = current.status.get("promoted") or {}
            if any(int(promoted.get(mid) or 0) < epoch for mid in alive):
                return
        published = self.publisher.publish(desired)
        if published is not None:
            _log.info(
                "shard leader %s published map epoch %s members %s",
                self.router.me, map_epoch(published), desired,
            )
            if self.recorder is not None:
                self.recorder.normal(
                    published, "ShardMapPublished",
                    f"epoch {map_epoch(published)}: {','.join(desired)}",
                )

    # -- rebalance ---------------------------------------------------------
    def _observe_map(self, now: float) -> None:
        m = self.store.try_get_view(SHARD_MAP_KIND, self.namespace, SHARD_MAP_NAME)
        if m is None:
            return
        epoch = map_epoch(m)
        if epoch > max(self.router.active_epoch, self.router.pending_epoch):
            self.router.begin_rebalance(
                map_members(m), epoch, now,
                vnodes=int(m.spec.get("vnodes") or 0) or None,
            )

    def _advance_barrier(self, now: float) -> None:
        epoch = self.router.pending_epoch
        if self._acked_epoch < epoch:
            if self._draining():
                return  # in-flight losing reconciles; re-check next tick
            try:
                self.store.patch_status(
                    SHARD_MAP_KIND, self.namespace, SHARD_MAP_NAME,
                    lambda s: s.setdefault("acks", {}).__setitem__(
                        self.router.me, epoch
                    ),
                )
            except (Conflict, NotFound):
                return
            self._acked_epoch = epoch
        m = self.store.try_get_view(SHARD_MAP_KIND, self.namespace, SHARD_MAP_NAME)
        if m is None or map_epoch(m) != epoch:
            return
        acks = m.status.get("acks") or {}
        alive = self._alive_members(now)
        active, pending = self.router.rings()
        # only ALIVE members owe an ack: a member that crashes mid-
        # barrier (even a joiner named in the pending map) must not
        # wedge the promote — the leader's next map removes it
        required = {
            mid
            for mid in set(pending.members) | set(active.members)
            if mid in alive
        }
        if any(int(acks.get(mid) or 0) < epoch for mid in required):
            return
        old_n, new_n, started = self.router.promote()
        # the gauge means "epoch this manager has PROMOTED to active"
        # (divergence across shards = a barrier in flight) — setting it
        # at observe time would hide exactly the stall it exists to show
        metrics.shard_map_epoch.set(epoch, self.router.me)
        try:
            # publish the promote so the leader can serialize barriers
            # (no new epoch until every alive member runs ring `epoch`)
            self.store.patch_status(
                SHARD_MAP_KIND, self.namespace, SHARD_MAP_NAME,
                lambda s: s.setdefault("promoted", {}).__setitem__(
                    self.router.me, epoch
                ),
            )
        except (Conflict, NotFound):
            pass  # the heartbeat-cadence requeue retries via _observe_map
        delta = new_n - old_n
        metrics.shard_rebalances.inc(self.router.me, f"{delta:+d}")
        if started is not None:
            metrics.shard_rebalance_seconds.observe(
                max(0.0, now - started), self.router.me
            )
        self._update_parked_gauge()
        _log.info(
            "shard %s promoted map epoch %s (%d -> %d members)",
            self.router.me, epoch, old_n, new_n,
        )
        if self.recorder is not None:
            self.recorder.normal(
                m, "ShardRebalanced",
                f"epoch {epoch} active ({old_n} -> {new_n} members)",
            )
        if self.router.me not in self.router.members():
            if self._leaving:
                self._retire()
            # else: excluded without asking (a heartbeat raced the
            # leader's publish, or a partition healed) — keep
            # heartbeating; the leader re-adds us next duty cycle
        else:
            self._resync_owned()
            self._refresh_owned_gauge()

    def _confirm_promoted(self) -> None:
        """Idempotent catch-up for the post-promote ``status.promoted``
        write (a Conflict there must not wedge the leader's barrier
        serialization): re-patch whenever the bus record lags this
        member's active epoch."""
        if self._retired:
            return
        m = self.store.try_get_view(
            SHARD_MAP_KIND, self.namespace, SHARD_MAP_NAME
        )
        if m is None or map_epoch(m) != self.router.active_epoch:
            return
        promoted = m.status.get("promoted") or {}
        epoch = self.router.active_epoch
        if int(promoted.get(self.router.me) or 0) >= epoch:
            return
        try:
            self.store.patch_status(
                SHARD_MAP_KIND, self.namespace, SHARD_MAP_NAME,
                lambda s: s.setdefault("promoted", {}).__setitem__(
                    self.router.me, epoch
                ),
            )
        except (Conflict, NotFound):
            pass  # next tick retries

    def _draining(self) -> bool:
        """Any in-flight reconcile for a family this shard is losing?"""
        active, pending = self.router.rings()
        if pending is None:
            return False
        for controller, ns, name in self.manager.active_keys():
            if controller == SHARD_CONTROLLER:
                continue
            root = self.router.root_for(controller, ns, name)
            if root is None:
                continue
            if (active.owner(root) == self.router.me
                    and pending.owner(root) != self.router.me):
                return True
        return False

    def _retire(self) -> None:
        # stop and JOIN the renewal thread before the member delete —
        # a beat landing after it would resurrect the member as a
        # zombie until TTL expiry
        self._retired = True
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        try:
            self.store.delete(SHARD_MEMBER_KIND, self.namespace, self.router.me)
        except NotFound:
            pass
        self.stop()
        _log.info("shard %s retired (left the ring)", self.router.me)

    # -- resync ------------------------------------------------------------
    def _resync_owned(self) -> None:
        """Post-promote: enqueue every non-terminal run family this
        shard now owns — a run handed over mid-flight may produce no
        further events on its own."""
        for run in self.store.list_views(STORY_RUN_KIND):
            ns, rn = run.meta.namespace, run.meta.name
            if not self.router.owns_run(ns, rn):
                continue
            if is_nonterminal_phase(run.status.get("phase"), empty_is_active=True):
                self.manager.enqueue("storyrun", ns, rn)
        for sr in self.store.list_views(STEP_RUN_KIND):
            run = (sr.spec.get("storyRunRef") or {}).get("name")
            if not run or not self.router.owns_run(sr.meta.namespace, run):
                continue
            if is_nonterminal_phase(sr.status.get("phase"), empty_is_active=True):
                self.manager.enqueue("steprun", sr.meta.namespace, sr.meta.name)
        self._resync_definitions()
        for controller, kind in _AUX_CONTROLLER_KIND.items():
            for ns, name in self.store.list_keys(kind):
                if self.router.owns_root(f"{kind}:{ns}/{name}"):
                    self.manager.enqueue(controller, ns, name)

    def _resync_definitions(self) -> None:
        """Definition owners no longer receive other shards' run events
        (the mappers that would re-reconcile them fan out on run-owner
        shards and gate-drop there), so usage counters converge by
        periodic resync instead of per-event nudges."""
        for controller, kind in _DEF_CONTROLLER_KIND.items():
            for ns, name in self.store.list_keys(kind):
                if self.router.owns_root(f"{kind}:{ns}/{name}"):
                    self.manager.enqueue(controller, ns, name)

    def _refresh_owned_gauge(self) -> None:
        owned = sum(
            1
            for ns, name in self.store.list_keys(STORY_RUN_KIND)
            if self.router.owns_run(ns, name)
        )
        metrics.shard_owned_runs.set(owned, self.router.me)
