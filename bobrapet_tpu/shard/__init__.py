"""Sharded control plane: hash-ring run ownership across N managers.

The deliberate step PAST the reference's single-active-manager shape
(reference: internal/config/operator.go — one controller-runtime
process, leader-elected active/standby): N cooperating managers share
one coordination bus and own **disjoint hash-ring ranges of run keys**,
so watch fan-out, dispatcher queues, and reconcile work all partition.

Pieces (each its own module, composable without the others):

- :mod:`ring` — consistent hashing with stable virtual nodes
  (``utils/hashing.stable_uint64``; minimal key movement on membership
  change).
- :mod:`map` — the ShardMap bus resource: leader-published membership +
  epoch, admission-fenced against stale leaders
  (``utils/leader.py`` fencing tokens).
- :mod:`router` — per-manager ownership decisions: run-family root
  resolution for watch delivery, reconcile-key classification
  (own/park/drop) for the dispatcher gate, rebalance state.
- :mod:`coordinator` — the "shard" controller each manager runs:
  leader election + map publish, drain-and-ack barrier on membership
  change, parked-key release, handoff accounting.
- :mod:`detector` — test-support double-reconcile detector (no run may
  be processed by two shards).
- :mod:`harness` — N in-process Runtimes over one bus for tests/bench.
"""

from .coordinator import SHARD_CONTROLLER, ShardCoordinator
from .detector import DoubleReconcileDetector
from .harness import ShardedControlPlane
from .map import (
    SHARD_MAP_KIND,
    SHARD_MAP_NAME,
    SHARD_NAMESPACE,
    ShardMapPublisher,
    register_shard_admission,
)
from .ring import HashRing
from .router import ADMIT_DROP, ADMIT_OWN, ADMIT_PARK, ShardRouter

__all__ = [
    "ADMIT_DROP",
    "ADMIT_OWN",
    "ADMIT_PARK",
    "DoubleReconcileDetector",
    "HashRing",
    "SHARD_CONTROLLER",
    "SHARD_MAP_KIND",
    "SHARD_MAP_NAME",
    "SHARD_NAMESPACE",
    "ShardCoordinator",
    "ShardMapPublisher",
    "ShardRouter",
    "ShardedControlPlane",
    "register_shard_admission",
]
