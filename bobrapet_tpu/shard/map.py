"""ShardMap: the leader-published membership resource on the bus.

One resource (``bobrapet-system/shard-map``) carries the facts every
manager needs to agree on ownership: the member list, a monotonically
increasing **epoch** (one per membership change), the publisher's
**fence token** (``utils/leader.py`` — the lease epoch minted at the
leader's last acquisition), and the vnode count the rings are built
with. Status carries the rebalance barrier: ``acks[shard] = epoch``
written by each member once it has drained everything it is losing.

Fencing is enforced at ADMISSION, not by publisher discipline: a
paused-and-resumed stale leader that still believes it leads carries a
fence token older than the lease's current epoch, and the validator
rejects the write — the stale map loses at the bus, deterministically
(``register_shard_admission``). ``ShardMapPublisher.publish`` also
pre-checks ``validate_fence()`` (a fresh lease read), but that check is
advisory; the validator is the guarantee.
"""

from __future__ import annotations

import logging
from typing import Iterable, Optional, Sequence

from ..core.object import Resource, new_resource
from ..core.store import AdmissionDenied, Conflict, NotFound, ResourceStore
from ..utils.leader import LEASE_KIND
from .ring import DEFAULT_VNODES

_log = logging.getLogger(__name__)

SHARD_NAMESPACE = "bobrapet-system"
SHARD_MAP_KIND = "ShardMap"
SHARD_MAP_NAME = "shard-map"
SHARD_MEMBER_KIND = "ShardMember"
SHARD_LEASE_NAME = "shard-leader"


def register_shard_admission(
    store: ResourceStore,
    namespace: str = SHARD_NAMESPACE,
    lease_name: str = SHARD_LEASE_NAME,
) -> None:
    """Install the ShardMap spec validator (idempotent per store).

    Rules:
    - ``spec.fence`` must be >= the shard-leader lease's current epoch
      (a stale leader's token is strictly older — rejected);
    - ``spec.epoch`` must strictly increase on any spec change;
    - ``spec.members`` must be a non-empty list.
    """
    if getattr(store, "_shard_admission_registered", False):
        return
    store._shard_admission_registered = True  # noqa: SLF001 - own marker

    def validate(new: Resource, old: Optional[Resource]) -> None:
        spec = new.spec
        members = spec.get("members")
        if not members or not isinstance(members, list):
            raise AdmissionDenied("ShardMap spec.members must be a non-empty list")
        lease = store.try_get_view(LEASE_KIND, namespace, lease_name)
        if lease is not None:
            current = int(lease.spec.get("epoch") or 0)
            fence = int(spec.get("fence") or 0)
            if fence < current:
                raise AdmissionDenied(
                    f"ShardMap publish fenced out: token {fence} is older "
                    f"than the shard-leader lease epoch {current} (stale "
                    f"leader)"
                )
        if old is not None and spec != old.spec:
            if int(spec.get("epoch") or 0) <= int(old.spec.get("epoch") or 0):
                raise AdmissionDenied(
                    f"ShardMap epoch must increase on membership change "
                    f"(got {spec.get('epoch')} after {old.spec.get('epoch')})"
                )

    store.register_validator(SHARD_MAP_KIND, validate)


class ShardMapPublisher:
    """Leader-side publish of membership changes (fenced; see module
    docstring). One instance per coordinator; only the elected leader's
    calls survive admission."""

    def __init__(
        self,
        store: ResourceStore,
        elector,
        namespace: str = SHARD_NAMESPACE,
        name: str = SHARD_MAP_NAME,
        vnodes: int = DEFAULT_VNODES,
    ):
        self.store = store
        self.elector = elector
        self.namespace = namespace
        self.name = name
        self.vnodes = int(vnodes)

    def publish(self, members: Iterable[str]) -> Optional[Resource]:
        """Publish ``members`` as the new map (epoch+1). Returns the
        committed resource, or None when this publisher lost the fence
        race (stale leader) — never raises for staleness."""
        desired = sorted({str(m) for m in members})
        if not desired:
            return None
        # advisory pre-check: a fresh lease read, not the cached
        # is_leader flag — skips the doomed write in the common case
        if not self.elector.validate_fence():
            return None
        fence = int(self.elector.fence_token)

        def fill(spec: dict) -> None:
            spec["members"] = desired
            spec["epoch"] = int(spec.get("epoch") or 0) + 1
            spec["fence"] = fence
            spec["vnodes"] = self.vnodes
            spec["publisher"] = self.elector.identity

        existing = self.store.try_get(SHARD_MAP_KIND, self.namespace, self.name)
        try:
            if existing is None:
                spec: dict = {}
                fill(spec)
                return self.store.create(
                    new_resource(SHARD_MAP_KIND, self.name, self.namespace, spec)
                )

            def mut(r: Resource) -> None:
                if list(r.spec.get("members") or []) == desired:
                    return  # no-op write: mutate's patch-if-changed elides it
                fill(r.spec)

            return self.store.mutate(
                SHARD_MAP_KIND, self.namespace, self.name, mut
            )
        except AdmissionDenied as e:
            _log.warning("shard map publish fenced out: %s", e)
            return None
        except (Conflict, NotFound):
            return None


def map_members(resource: Optional[Resource]) -> list[str]:
    if resource is None:
        return []
    return [str(m) for m in (resource.spec.get("members") or [])]


def map_epoch(resource: Optional[Resource]) -> int:
    if resource is None:
        return 0
    return int(resource.spec.get("epoch") or 0)


def make_member(shard_id: str, renew_time: float,
                namespace: str = SHARD_NAMESPACE) -> Resource:
    return new_resource(
        SHARD_MEMBER_KIND, str(shard_id), namespace,
        {"renewTime": float(renew_time)},
    )
