"""Per-manager ownership decisions over the hash ring.

Three seams, one source of truth (the active/pending ring pair):

- **delivery filter** (:meth:`ShardRouter.wants`) — installed as the
  store's default watch filter, so every watch this manager's
  components register only sees events for run families it owns (plus
  the parent-interest edge for cross-shard ``executeStory`` children,
  and every non-family kind: definitions, config, leases, shard
  coordination — those broadcast).
- **reconcile gate** (:meth:`classify`) — consulted by the dispatcher
  before each reconcile: OWN (proceed), PARK (gaining this family in a
  pending map; requeue until the barrier clears), DROP (another
  shard's work — a mapper fan-out or a family this shard is losing).
- **rebalance state** — ``begin_rebalance`` installs a pending ring
  (keys deliver to BOTH old and new owner: the loser stops starting
  work, the gainer parks it), ``promote`` swaps it in once the barrier
  clears.

Ownership roots:

- run family — a StoryRun and every resource under it (StepRuns, Jobs,
  realtime workloads, bindings) root at ``namespace/run-name``; a
  sub-StoryRun roots at its OWN name (per-run sharding — that's what
  makes cross-shard ``executeStory`` handoff exist) while its events
  also deliver to the parent's shard so the waiting parent step
  observes completion.
- aux family — StoryTriggers and EffectClaims root at themselves
  (their created runs hash independently; creation through the shared
  store IS the handoff).
- definitions (Story/Engram/templates/Impulse/Transport) broadcast on
  the watch but reconcile on exactly one shard (hash of kind+key), so
  usage counters are written by one manager — the counter+annotation
  pair cannot be raced by two shards. Run events no longer reach the
  definition owner's mappers from other shards, so the coordinator
  resyncs owned definitions periodically.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..analysis.racedetect import guarded_state
from ..api.catalog import ENGRAM_TEMPLATE_KIND, IMPULSE_TEMPLATE_KIND
from ..api.engram import KIND as ENGRAM_KIND
from ..api.impulse import KIND as IMPULSE_KIND
from ..api.runs import (
    EFFECT_CLAIM_KIND,
    STEP_RUN_KIND,
    STORY_RUN_KIND,
    STORY_TRIGGER_KIND,
)
from ..api.story import KIND as STORY_KIND
from ..api.transport import TRANSPORT_BINDING_KIND, TRANSPORT_KIND
from ..core.object import Resource
from .ring import DEFAULT_VNODES, HashRing

#: gate verdicts
ADMIT_OWN = "own"
ADMIT_PARK = "park"
ADMIT_DROP = "drop"

#: run-family labels (controllers/step_executor.py stamps them)
LABEL_STORY_RUN = "bobrapet.io/story-run"
LABEL_STEP_RUN = "bobrapet.io/step-run"

#: child-workload kinds that carry the step-run label
_STEP_OWNED_KINDS = frozenset(
    {TRANSPORT_BINDING_KIND, "Deployment", "StatefulSet", "Service"}
)

#: controller registration name -> the definition kind it reconciles
#: (controllers/manager.py registration names; runtime.py wiring)
_DEF_CONTROLLER_KIND = {
    "story": STORY_KIND,
    "engram": ENGRAM_KIND,
    "engramtemplate": ENGRAM_TEMPLATE_KIND,
    "impulsetemplate": IMPULSE_TEMPLATE_KIND,
    "impulse": IMPULSE_KIND,
    "transport": TRANSPORT_KIND,
}

_AUX_CONTROLLER_KIND = {
    "storytrigger": STORY_TRIGGER_KIND,
    "effectclaim": EFFECT_CLAIM_KIND,
}

DEFINITION_KINDS = frozenset(_DEF_CONTROLLER_KIND.values())


@guarded_state("parked")
class ShardRouter:
    """One per manager process; thread-safe (ring swaps under a lock,
    reads take an immutable snapshot)."""

    def __init__(
        self,
        store,
        shard_id: str,
        shard_count: int = 1,
        vnodes: int = DEFAULT_VNODES,
    ):
        self.store = store
        self.me = str(shard_id)
        self.vnodes = int(vnodes)
        self._lock = threading.Lock()
        #: the (active, pending) pair lives in ONE tuple attribute so
        #: readers (wants/classify, which run unlocked on gate and
        #: drainer threads) snapshot both rings in a single atomic load
        #: — two separate attribute reads could tear against a
        #: concurrent promote() into (old active, pending=None), which
        #: classifies a family this shard just lost as OWN.
        #: Epoch 0 = the config-derived bootstrap ring (controllers.
        #: shard-count); published maps supersede it from epoch 1 on.
        self._rings: tuple[HashRing, Optional[HashRing]] = (
            HashRing(
                [str(i) for i in range(max(1, int(shard_count)))],
                vnodes=vnodes,
            ),
            None,
        )
        self._active_epoch = 0
        self._pending_epoch = 0
        self._rebalance_started: Optional[float] = None
        #: keys currently parked by the gate, for the gauge + tests
        self.parked: set[tuple[str, str, str]] = set()
        #: fired (outside the lock) after any ring mutation — the
        #: store-service client pushes the refreshed :meth:`filter_spec`
        #: to the server so the SERVER-side delivery filter tracks ring
        #: changes with the same immediacy the in-process drain-time
        #: evaluation gives already-bound subscriptions
        self.on_rings_changed: Optional[callable] = None

    # -- ring state --------------------------------------------------------
    @property
    def active_epoch(self) -> int:
        return self._active_epoch

    @property
    def pending_epoch(self) -> int:
        return self._pending_epoch

    @property
    def rebalancing(self) -> bool:
        return self._rings[1] is not None

    def rings(self) -> tuple[HashRing, Optional[HashRing]]:
        return self._rings

    def members(self) -> tuple[str, ...]:
        return self._rings[0].members

    def set_bootstrap_count(self, count: int) -> bool:
        """Adopt a live-reloaded ``controllers.shard-count`` — only
        while still on the bootstrap ring (epoch 0). Once a leader has
        published a map, dynamic membership is authoritative and the
        static count is just the expected fleet size."""
        with self._lock:
            active, pending = self._rings
            if self._active_epoch != 0 or pending is not None:
                return False
            members = [str(i) for i in range(max(1, int(count)))]
            if list(active.members) == members:
                return False
            self._rings = (HashRing(members, vnodes=self.vnodes), None)
        self._rings_changed()
        return True

    def begin_rebalance(self, members, epoch: int, started_at: float,
                        vnodes: Optional[int] = None) -> None:
        with self._lock:
            if epoch <= max(self._active_epoch, self._pending_epoch):
                return
            self._rings = (
                self._rings[0],
                HashRing(members, vnodes=vnodes or self.vnodes),
            )
            self._pending_epoch = int(epoch)
            if self._rebalance_started is None:
                self._rebalance_started = float(started_at)
        self._rings_changed()

    def promote(self) -> tuple[int, int, Optional[float]]:
        """Swap pending -> active at the barrier; returns
        (old member count, new member count, rebalance start time)."""
        with self._lock:
            active, pending = self._rings
            assert pending is not None
            old_n = len(active.members)
            self._rings = (pending, None)
            self._active_epoch = self._pending_epoch
            started = self._rebalance_started
            self._rebalance_started = None
            self.parked.clear()
        self._rings_changed()
        return old_n, len(pending.members), started

    def _rings_changed(self) -> None:
        """Notify the (optional) filter-push hook OUTSIDE the ring lock
        — the hook does socket I/O and must not nest under it."""
        hook = self.on_rings_changed
        if hook is not None:
            try:
                hook()
            except Exception:  # noqa: BLE001 - delivery heals at resync
                import logging

                logging.getLogger(__name__).exception(
                    "shard %s filter push failed", self.me
                )

    def filter_spec(self) -> dict:
        """The declarative, wire-serializable form of :meth:`wants`:
        rings are deterministic from (members, vnodes) so the store
        service rebuilds the SAME predicate against its own store with
        :func:`router_from_spec` and evaluates it server-side — each
        shard process only ever receives events for families it has an
        ownership interest in."""
        active, pending = self._rings  # one atomic load (see __init__)
        spec = {
            "me": self.me,
            "active": {"members": list(active.members),
                       "vnodes": active.vnodes},
        }
        if pending is not None:
            spec["pending"] = {"members": list(pending.members),
                               "vnodes": pending.vnodes}
        return spec

    # -- gate parking ------------------------------------------------------
    def park(self, key: tuple[str, str, str]) -> bool:
        """Record ``key`` as parked by the gate; True if newly parked.
        Parks are cleared wholesale by :meth:`promote` at the barrier,
        so membership changes and the clear serialize on one lock — the
        dispatcher gate threads must NOT touch ``parked`` directly."""
        with self._lock:
            if key in self.parked:
                return False
            self.parked.add(key)
            return True

    def unpark(self, key: tuple[str, str, str]) -> bool:
        """Drop a gate park; True if the key was actually parked."""
        with self._lock:
            if key not in self.parked:
                return False
            self.parked.discard(key)
            return True

    def parked_snapshot(self) -> tuple[tuple[str, str, str], ...]:
        """Stable copy for the gauge/tests (iteration must not race the
        gate threads' adds or promote()'s clear)."""
        with self._lock:
            return tuple(self.parked)

    # -- ownership ---------------------------------------------------------
    def owner_of(self, root: str) -> str:
        return self._rings[0].owner(root)

    def owns_root(self, root: str) -> bool:
        return self._rings[0].owner(root) == self.me

    def owns_run(self, namespace: str, run_name: str) -> bool:
        return self.owns_root(f"{namespace}/{run_name}")

    def owns_resource(self, resource: Resource) -> bool:
        """Does this shard own the run family ``resource`` belongs to?
        (Used by the DAG engine's shard-local global concurrency cap.)
        Non-family resources are 'owned' everywhere. Only the FIRST
        interest root is ownership — later entries are delivery edges
        (a sub-StoryRun's parent shard observes, it does not own)."""
        roots = self._interest_roots(resource)
        if not roots:
            return True
        return self._rings[0].owner(roots[0]) == self.me

    # -- delivery filter ---------------------------------------------------
    def wants(self, resource: Resource) -> bool:
        """The store's default watch filter for this manager: deliver
        run-family events only to shards with an ownership interest
        (owner under the active ring, owner under a pending ring, or —
        for sub-StoryRuns — the parent run's owner). Everything else
        broadcasts."""
        roots = self._interest_roots(resource)
        if not roots:
            return True
        active, pending = self._rings  # one atomic load (see __init__)
        for root in roots:
            if active.owner(root) == self.me:
                return True
            if pending is not None and pending.owner(root) == self.me:
                return True
        return False

    def _interest_roots(self, resource: Resource) -> list[str]:
        """Run-family roots this resource's events concern; [] means
        non-family (broadcast)."""
        kind = resource.kind
        ns = resource.meta.namespace
        if kind == STORY_RUN_KIND:
            roots = [f"{ns}/{resource.meta.name}"]
            parent = resource.meta.labels.get(LABEL_STORY_RUN)
            if parent:
                # cross-shard executeStory: the parent's shard must see
                # the child's phase changes to progress the waiting step
                roots.append(f"{ns}/{parent}")
            return roots
        if kind == STEP_RUN_KIND:
            run = (resource.spec.get("storyRunRef") or {}).get(
                "name"
            ) or resource.meta.labels.get(LABEL_STORY_RUN)
            return [f"{ns}/{run}"] if run else []
        if kind == "Job":
            run = resource.meta.labels.get(LABEL_STORY_RUN)
            if run:
                return [f"{ns}/{run}"]
            sr_name = (resource.spec.get("stepRunRef") or {}).get("name")
            return self._steprun_root(ns, sr_name)
        if kind in _STEP_OWNED_KINDS:
            run = resource.meta.labels.get(LABEL_STORY_RUN)
            if run:
                return [f"{ns}/{run}"]
            sr_name = resource.meta.labels.get(LABEL_STEP_RUN)
            return self._steprun_root(ns, sr_name)
        if kind == STORY_TRIGGER_KIND or kind == EFFECT_CLAIM_KIND:
            return [f"{kind}:{ns}/{resource.meta.name}"]
        return []

    def _steprun_root(self, ns: str, sr_name: Optional[str]) -> list[str]:
        if not sr_name:
            return []
        sr = self.store.try_get_view(STEP_RUN_KIND, ns, sr_name)
        if sr is None:
            return []  # parent gone: broadcast, gates still apply
        run = (sr.spec.get("storyRunRef") or {}).get("name")
        return [f"{ns}/{run}"] if run else []

    # -- reconcile gate ----------------------------------------------------
    def classify(self, controller: str, ns: str, name: str
                 ) -> tuple[str, Optional[str]]:
        """Gate verdict for a dispatched key: (OWN|PARK|DROP, root).

        Controllers outside the known families (the shard coordinator
        itself, cluster reconcilers, simulators) always run."""
        root = self.root_for(controller, ns, name)
        if root is None:
            return ADMIT_OWN, None
        active, pending = self._rings  # one atomic load (see __init__)
        own_now = active.owner(root) == self.me
        if pending is None:
            return (ADMIT_OWN if own_now else ADMIT_DROP), root
        own_next = pending.owner(root) == self.me
        if own_now and own_next:
            return ADMIT_OWN, root
        if own_next:
            # gaining: untouched until the old owner drains and the
            # barrier clears — the no-two-shards invariant lives here
            return ADMIT_PARK, root
        # losing (or never ours): the pending owner parks it
        return ADMIT_DROP, root

    def root_for(self, controller: str, ns: str, name: str) -> Optional[str]:
        """Ownership root for a (controller, key) dispatch; None for
        unsharded controllers."""
        if controller == "storyrun":
            return f"{ns}/{name}"
        if controller == "steprun":
            sr = self.store.try_get_view(STEP_RUN_KIND, ns, name)
            if sr is not None:
                run = (sr.spec.get("storyRunRef") or {}).get(
                    "name"
                ) or sr.meta.labels.get(LABEL_STORY_RUN)
                if run:
                    return f"{ns}/{run}"
            return f"{ns}/{name}"  # orphan StepRun: hash on itself
        kind = _AUX_CONTROLLER_KIND.get(controller)
        if kind is not None:
            return f"{kind}:{ns}/{name}"
        kind = _DEF_CONTROLLER_KIND.get(controller)
        if kind is not None:
            return f"{kind}:{ns}/{name}"
        return None


def router_from_spec(store, spec: dict) -> ShardRouter:
    """Rebuild a shard's delivery predicate from its
    :meth:`ShardRouter.filter_spec` against ``store`` (the store
    SERVICE's authoritative store — ``_steprun_root`` needs local
    lookups, which is exactly why the filter must be reconstructed
    server-side rather than shipped as a callable)."""
    active = spec["active"]
    r = ShardRouter(store, spec["me"], shard_count=1,
                    vnodes=int(active["vnodes"]))
    pending = spec.get("pending")
    r._rings = (  # noqa: SLF001 - deterministic reconstruction
        HashRing(active["members"], vnodes=int(active["vnodes"])),
        HashRing(pending["members"], vnodes=int(pending["vnodes"]))
        if pending else None,
    )
    return r
