"""Consistent hash ring over run keys.

Stable virtual-node hashing (``utils/hashing.stable_uint64`` — sha256,
never the process-seeded ``hash()``): every manager computes the exact
same ring from the same member list, across processes and restarts.
Virtual nodes smooth the per-member share (64 vnodes keeps the largest/
smallest member spread under ~1.4x at 4 members); consistent hashing
bounds movement on membership change to ~1/N of the keyspace, which is
what keeps a rebalance barrier short.
"""

from __future__ import annotations

import bisect
from typing import Iterable, Sequence

from ..utils.hashing import stable_uint64

DEFAULT_VNODES = 64


class HashRing:
    """Immutable once built; membership change = build a new ring."""

    __slots__ = ("_members", "_vnodes", "_points", "_owners")

    def __init__(self, members: Iterable[str], vnodes: int = DEFAULT_VNODES):
        self._members: tuple[str, ...] = tuple(sorted({str(m) for m in members}))
        if not self._members:
            raise ValueError("HashRing needs at least one member")
        self._vnodes = max(1, int(vnodes))
        points: list[tuple[int, str]] = []
        for member in self._members:
            for v in range(self._vnodes):
                points.append((stable_uint64(f"vnode:{member}:{v}"), member))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [m for _, m in points]

    @property
    def members(self) -> tuple[str, ...]:
        return self._members

    @property
    def vnodes(self) -> int:
        return self._vnodes

    def owner(self, key: str) -> str:
        """The member owning ``key`` (first vnode clockwise)."""
        if len(self._members) == 1:
            return self._members[0]
        i = bisect.bisect_right(self._points, stable_uint64(key))
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def owns(self, member: str, key: str) -> bool:
        return self.owner(key) == str(member)

    def moved_keys(self, other: "HashRing", keys: Sequence[str]) -> list[str]:
        """Keys whose owner differs between this ring and ``other`` —
        the drain set of a rebalance."""
        return [k for k in keys if self.owner(k) != other.owner(k)]

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, HashRing)
            and self._members == other._members
            and self._vnodes == other._vnodes
        )

    def __hash__(self) -> int:  # pragma: no cover - set membership only
        return hash((self._members, self._vnodes))

    def __repr__(self) -> str:  # pragma: no cover
        return f"HashRing(members={list(self._members)}, vnodes={self._vnodes})"
