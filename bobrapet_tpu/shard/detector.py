"""Double-reconcile detector: no run family on two shards at once.

Test/bench support (wired by the harness, importable anywhere): every
manager's dispatcher reports reconcile start/finish through the
``reconcile_observer`` hook (controllers/manager.py), the detector
resolves each key to its ownership root through that shard's router,
and a root in flight on two DIFFERENT shards simultaneously is recorded
as a violation. Same-shard overlap (the storyrun and steprun pools both
touching one family) is legal — keyed serialization is per controller —
so the ledger is a per-root multiset of shards, not a single slot.

This is the executable form of the rebalance contract: the loser
drains before acking, the gainer parks until the promote, therefore the
in-flight shard-sets never overlap across a membership change.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Violation:
    root: str
    shards: tuple[str, ...]
    controller: str
    key: tuple[str, str]


@dataclass
class _InFlight:
    #: shard id -> count of reconciles currently processing this root
    by_shard: dict[str, int] = field(default_factory=dict)


class DoubleReconcileDetector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._inflight: dict[str, _InFlight] = {}
        self.violations: list[Violation] = []
        #: reconciles observed per shard (proof both shards did work)
        self.processed: dict[str, int] = {}

    def install(self, runtime) -> None:
        """Attach to one manager; requires the runtime to be sharded
        (the router resolves ownership roots)."""
        router = runtime.shard_router
        if router is None:
            raise ValueError("detector requires a sharded Runtime")
        runtime.manager.reconcile_observer = _Observer(self, router)

    def assert_clean(self) -> None:
        assert not self.violations, (
            f"{len(self.violations)} double-reconcile violations; first: "
            f"{self.violations[0]}"
        )

    # -- observer callbacks ------------------------------------------------
    def _started(self, shard: str, root: Optional[str],
                 controller: str, ns: str, name: str) -> None:
        with self._lock:
            self.processed[shard] = self.processed.get(shard, 0) + 1
            if root is None:
                return
            entry = self._inflight.setdefault(root, _InFlight())
            entry.by_shard[shard] = entry.by_shard.get(shard, 0) + 1
            live = tuple(s for s, n in entry.by_shard.items() if n > 0)
            if len(live) > 1:
                self.violations.append(
                    Violation(root=root, shards=live,
                              controller=controller, key=(ns, name))
                )

    def _finished(self, shard: str, root: Optional[str]) -> None:
        if root is None:
            return
        with self._lock:
            entry = self._inflight.get(root)
            if entry is None:
                return
            n = entry.by_shard.get(shard, 0) - 1
            if n <= 0:
                entry.by_shard.pop(shard, None)
                if not entry.by_shard:
                    self._inflight.pop(root, None)
            else:
                entry.by_shard[shard] = n


class _Observer:
    """Per-manager adapter: resolves roots with THAT shard's router."""

    __slots__ = ("detector", "router", "_roots")

    def __init__(self, detector: DoubleReconcileDetector, router):
        self.detector = detector
        self.router = router
        #: root resolved at start, replayed at finish — the resource
        #: may be deleted mid-reconcile and the finish must balance
        self._roots: dict[tuple[str, str, str], Optional[str]] = {}

    def reconcile_started(self, controller: str, ns: str, name: str) -> None:
        # only run families carry the no-two-shards invariant; the
        # definition/aux controllers are single-owner by the gate alone
        root = None
        if controller in ("storyrun", "steprun"):
            root = self.router.root_for(controller, ns, name)
        self._roots[(controller, ns, name)] = root
        self.detector._started(self.router.me, root, controller, ns, name)

    def reconcile_finished(self, controller: str, ns: str, name: str) -> None:
        root = self._roots.pop((controller, ns, name), None)
        self.detector._finished(self.router.me, root)
