"""N real OS processes over one durable store service, for tests + bench.

``ProcessShardedControlPlane`` is the process-mode sibling of
:class:`.harness.ShardedControlPlane` (construct the latter with
``processes=True`` to get one of these): it spawns the store service as
its own process (``python -m bobrapet_tpu.store_service`` over a Unix
socket, journal + snapshots in a scratch data dir) and one shard
manager **process** per shard (``python -m
bobrapet_tpu.shard.procharness --child``). Each child builds a full
Runtime against a :class:`..store_service.client.StoreClient`, so the
whole PR-6 contract — fenced map publish, member TTL expiry,
drain/ack/promote barriers — runs across real process boundaries, and
``kill_shard`` is a real ``SIGKILL``: no crash() courtesy call, no
in-process cleanup, exactly the death the lease-TTL takeover paths
exist for. ``kill_store_service`` / ``restart_store_service`` extend
the same honesty to the bus itself (clients reconnect + resync;
recovery replays the journal).

What in-process shards could never show — CPU parallelism past one
GIL — is what this harness exists to measure; what it cannot use are
in-process conveniences: no shared detector, recorder or configure
callback. Control flows through bus resources instead: the parent
writes a ``ShardControl`` command to stop/leave a child, and a child
exiting gracefully publishes a ``ShardReport`` (reconcile counts,
per-process double-reconcile violations, ChipLedger imbalance) the
parent collects in :attr:`reports`. Cross-process exactly-once
retirement is asserted parent-side: a watch on StoryRuns counts
transitions into a terminal phase, which must be exactly one per run.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

from ..analysis.racedetect import guarded_state
from ..api.enums import Phase
from ..api.runs import STORY_RUN_KIND, make_storyrun
from ..core.object import new_resource
from ..core.store import Conflict, NotFound
from ..utils.naming import compose_unique
from .map import SHARD_MAP_KIND, SHARD_MAP_NAME, SHARD_NAMESPACE
from .ring import DEFAULT_VNODES

SHARD_CONTROL_KIND = "ShardControl"
SHARD_REPORT_KIND = "ShardReport"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_TERMINAL = (Phase.SUCCEEDED, Phase.FAILED)


def _child_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    path = env.get("PYTHONPATH")
    env["PYTHONPATH"] = _REPO_ROOT + (os.pathsep + path if path else "")
    return env


@guarded_state("_children", "_logs", "_run_phase_seen", "_shard_options",
               "_terminal_counts", "config_data", "reports")
class ProcessShardedControlPlane:
    """Mirror of ``ShardedControlPlane``'s surface over real processes.

    Differences forced by the process boundary:

    - ``configure`` (a callable) cannot cross the wire — pass
      ``config_data`` (dotted operator-config keys, e.g.
      ``{"scheduling.global-max-concurrent-steps": "2"}``) and the
      parent publishes the ConfigMap before any child boots;
    - ``workload`` is a ``"module:function"`` spec imported INSIDE each
      child to register engram entrypoints there (callables cannot be
      applied through the store; resources still apply from the parent);
    - there is no shared ``detector`` — each child runs its own and
      publishes the verdict in its ShardReport on graceful exit.
    """

    def __init__(
        self,
        shards: int = 2,
        executor_mode: str = "threaded",
        heartbeat_interval: float = 0.25,
        member_ttl: float = 3.0,
        lease_duration: float = 4.0,
        vnodes: int = DEFAULT_VNODES,
        workload: str = "tests.proc_workload:install",
        config_data: Optional[dict] = None,
        base_dir: Optional[str] = None,
        fsync_batch: Optional[int] = None,
        snapshot_every: Optional[int] = None,
    ):
        self._lock = threading.Lock()
        self.executor_mode = executor_mode
        self.workload = workload
        self.config_data = dict(config_data or {})
        self._bootstrap_count = max(1, int(shards))
        self._shard_options = {
            "heartbeat_interval": heartbeat_interval,
            "member_ttl": member_ttl,
            "lease_duration": lease_duration,
            "vnodes": vnodes,
        }
        self._fsync_batch = fsync_batch
        self._snapshot_every = snapshot_every
        self._dir = base_dir or tempfile.mkdtemp(prefix="bobra-proc-")
        #: socket paths cap at ~107 bytes; a mkdtemp under /tmp fits
        self.socket_path = os.path.join(self._dir, "store.sock")
        self.data_dir = os.path.join(self._dir, "store")
        self._service: Optional[subprocess.Popen] = None
        self.store = None  # parent StoreClient, built in start()
        self._children: dict[str, subprocess.Popen] = {}
        self._logs: dict[str, object] = {}
        #: sid -> ShardReport spec, collected at graceful child exit
        self.reports: dict[str, dict] = {}
        #: run name -> count of transitions INTO a terminal phase
        #: (exactly-once retirement, observed from outside every shard)
        self._terminal_counts: dict[str, int] = {}
        self._run_phase_seen: dict[str, Optional[str]] = {}
        self._next_id = 0
        self._started = False

    # -- store service -----------------------------------------------------
    def _spawn_service(self) -> None:
        cmd = [
            sys.executable, "-m", "bobrapet_tpu.store_service",
            "--socket", self.socket_path, "--data-dir", self.data_dir,
        ]
        if self._fsync_batch is not None:
            cmd += ["--fsync-batch", str(self._fsync_batch)]
        if self._snapshot_every is not None:
            cmd += ["--snapshot-every", str(self._snapshot_every)]
        log = self._open_log("store-service")
        proc = subprocess.Popen(
            cmd, env=_child_env(), stdout=log, stderr=subprocess.STDOUT,
        )
        with self._lock:
            self._service = proc
        deadline = time.monotonic() + 30.0
        while not os.path.exists(self.socket_path):
            if self._service.poll() is not None:
                raise RuntimeError(
                    f"store service died at startup (rc={self._service.returncode}); "
                    f"see {self._log_path('store-service')}"
                )
            if time.monotonic() > deadline:
                raise AssertionError("store service never bound its socket")
            time.sleep(0.02)

    def kill_store_service(self) -> None:
        """SIGKILL the bus itself: clients must survive by reconnecting
        after :meth:`restart_store_service` replays the journal."""
        svc = self._service
        assert svc is not None and svc.poll() is None, "service not running"
        svc.kill()
        svc.wait(timeout=10.0)

    def restart_store_service(self) -> None:
        """Respawn over the SAME data dir — recovery is journal replay,
        not amnesia. Parent + child clients redial and resync."""
        self._spawn_service()

    def dump_store(self) -> bytes:
        """Canonical state bytes via the live service (see
        ``DurableResourceStore.dump`` / ``dump_recovered`` — the
        byte-identity pair for crash-recovery asserts)."""
        return self.store.dump_remote()

    # -- membership --------------------------------------------------------
    def add_shard(self) -> str:
        sid = str(self._next_id)
        self._next_id += 1
        cmd = [
            sys.executable, "-m", "bobrapet_tpu.shard.procharness", "--child",
            "--socket", self.socket_path,
            "--shard-id", sid,
            "--bootstrap", str(self._bootstrap_count),
            "--executor-mode", self.executor_mode,
            "--heartbeat-interval", str(self._shard_options["heartbeat_interval"]),
            "--member-ttl", str(self._shard_options["member_ttl"]),
            "--lease-duration", str(self._shard_options["lease_duration"]),
            "--vnodes", str(self._shard_options["vnodes"]),
            "--workload", self.workload,
        ]
        log = self._open_log(f"shard-{sid}")
        proc = subprocess.Popen(
            cmd, env=_child_env(), stdout=log, stderr=subprocess.STDOUT,
        )
        with self._lock:
            self._children[sid] = proc
        return sid

    def leave_shard(self, sid: str, timeout: float = 60.0) -> None:
        """Graceful leave via the bus: the child drains, acks the
        removal barrier, retires, publishes its report and exits 0."""
        self._command(sid, "leave")
        self._await_child_exit(sid, timeout, expect_clean=True)

    def stop_shard(self, sid: str, timeout: float = 60.0) -> None:
        """Stop without leaving the ring (process shutdown, member TTL
        left to expire) — the restart-shaped exit."""
        self._command(sid, "stop")
        self._await_child_exit(sid, timeout, expect_clean=True)

    def kill_shard(self, sid: str) -> None:
        """A real ``kill -9``. Nothing in the child runs again — no
        crash() flag, no lease release, no report. The survivors must
        detect the stale heartbeat / outlive the lease TTL exactly as
        they would for a production manager OOM-kill."""
        with self._lock:
            proc = self._children.pop(sid)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10.0)

    def _command(self, sid: str, command: str) -> None:
        name = f"shard-{sid}"
        try:
            self.store.create(new_resource(
                SHARD_CONTROL_KIND, name, SHARD_NAMESPACE, {"command": command}
            ))
        except Exception:  # noqa: BLE001 - exists (or raced): mutate it
            self.store.mutate(
                SHARD_CONTROL_KIND, SHARD_NAMESPACE, name,
                lambda r: r.spec.__setitem__("command", command),
            )

    def _await_child_exit(self, sid: str, timeout: float,
                          expect_clean: bool) -> None:
        with self._lock:
            proc = self._children.pop(sid)
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise AssertionError(
                f"shard {sid} ignored its control command for {timeout}s; "
                f"see {self._log_path(f'shard-{sid}')}"
            ) from None
        if expect_clean and rc != 0:
            raise AssertionError(
                f"shard {sid} exited rc={rc}; see {self._log_path(f'shard-{sid}')}"
            )
        self._collect_report(sid)

    def _collect_report(self, sid: str) -> None:
        rep = self.store.try_get(SHARD_REPORT_KIND, SHARD_NAMESPACE, str(sid))
        if rep is not None:
            with self._lock:
                self.reports[sid] = dict(rep.spec)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ProcessShardedControlPlane":
        from ..config import OperatorConfigManager
        from ..config.operator import CONFIG_MAP_KIND
        from ..store_service.client import StoreClient
        from ..templating.engine import Evaluator, TemplateConfig
        from ..webhooks import register_webhooks

        self._spawn_service()
        self.store = StoreClient(self.socket_path)
        # the parent is an API client like any other: its creates must
        # pass the same defaulting/validation chain the shards run
        cfgman = OperatorConfigManager(self.store)
        register_webhooks(
            self.store, Evaluator(TemplateConfig()), cfgman, enabled=True
        )
        if self.config_data:
            # publish BEFORE any child boots: children read the
            # ConfigMap at Runtime construction, not only on reloads
            self.store.create(new_resource(
                CONFIG_MAP_KIND, "operator-config", SHARD_NAMESPACE,
                {"data": {k: str(v) for k, v in self.config_data.items()}},
            ))
        self.store.watch(self._on_run_event, kinds=[STORY_RUN_KIND])
        self._started = True
        for _ in range(self._bootstrap_count):
            self.add_shard()
        return self

    def stop(self, timeout: float = 60.0) -> None:
        """Graceful teardown: stop children (collecting reports), then
        the service. Always followed by :meth:`reap` in fixtures."""
        self._started = False
        with self._lock:
            sids = list(self._children)
        for sid in sids:
            try:
                self.stop_shard(sid, timeout=timeout)
            except Exception:
                if self._children_alive() or self._service_alive():
                    raise
        svc = self._service
        if svc is not None and svc.poll() is None:
            svc.terminate()
            try:
                svc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                svc.kill()
                svc.wait(timeout=10.0)

    def reap(self) -> None:
        """Finalizer: SIGKILL anything still alive, close the client
        and every log handle. Idempotent; never raises."""
        with self._lock:
            procs = list(self._children.values())
            self._children = {}
        svc = self._service
        if svc is not None:
            procs.append(svc)
        for proc in procs:
            try:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 - reaping is best-effort
                pass
        if self.store is not None:
            try:
                self.store.close()
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            logs, self._logs = dict(self._logs), {}
        for handle in logs.values():
            try:
                handle.close()
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self) -> "ProcessShardedControlPlane":
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _children_alive(self) -> bool:
        with self._lock:
            return any(p.poll() is None for p in self._children.values())

    def _service_alive(self) -> bool:
        return self._service is not None and self._service.poll() is None

    def logs(self, name: str) -> str:
        """Tail of one process log (``store-service`` / ``shard-<sid>``)
        for assertion forensics."""
        try:
            with open(self._log_path(name), "r", encoding="utf-8",
                      errors="replace") as fh:
                return fh.read()[-8000:]
        except OSError:
            return ""

    def _log_path(self, name: str) -> str:
        return os.path.join(self._dir, f"{name}.log")

    def _open_log(self, name: str):
        handle = open(self._log_path(name), "ab")
        with self._lock:
            self._logs[name] = handle
        return handle

    # -- exactly-once retirement (parent-side observer) --------------------
    def _on_run_event(self, ev) -> None:
        res = ev.resource
        name = f"{res.meta.namespace}/{res.meta.name}"
        phase = (res.status or {}).get("phase")
        with self._lock:
            prev = self._run_phase_seen.get(name)
            self._run_phase_seen[name] = phase
            if phase in _TERMINAL and prev not in _TERMINAL:
                self._terminal_counts[name] = self._terminal_counts.get(name, 0) + 1

    def terminal_transitions(self, run: str, namespace: str = "default") -> int:
        with self._lock:
            return self._terminal_counts.get(f"{namespace}/{run}", 0)

    def assert_exactly_once(self, runs, namespace: str = "default") -> None:
        """Every run retired exactly once, as observed from the bus.
        Two shards finishing one family would each drive a terminal
        transition; zero means the run was lost."""
        bad = {r: self.terminal_transitions(r, namespace)
               for r in runs if self.terminal_transitions(r, namespace) != 1}
        assert not bad, f"runs not retired exactly once: {bad}"

    def terminal_count_violations(self) -> dict:
        """Runs observed retiring MORE than once, over every run this
        plane ever watched (entries exist only once a run turns
        terminal, so in-flight runs are not false positives). The bench
        gates on this when it never learned individual run names."""
        with self._lock:
            return {r: c for r, c in self._terminal_counts.items() if c != 1}

    # -- convenience (mirrors ShardedControlPlane) -------------------------
    def apply(self, resource):
        existing = self.store.try_get(
            resource.kind, resource.meta.namespace, resource.meta.name
        )
        if existing is None:
            return self.store.create(resource)

        def sync(r) -> None:
            r.spec = dict(resource.spec)
            r.meta.labels.update(resource.meta.labels)
            r.meta.annotations.update(resource.meta.annotations)

        return self.store.mutate(
            resource.kind, resource.meta.namespace, resource.meta.name, sync
        )

    def run_story(self, story: str, inputs=None, name=None,
                  namespace: str = "default") -> str:
        run_name = name or compose_unique(
            story, "run", str(self.store._rv_counter))
        self.store.create(make_storyrun(run_name, story, inputs, namespace))
        return run_name

    def run_phase(self, run_name: str, namespace: str = "default"):
        run = self.store.try_get(STORY_RUN_KIND, namespace, run_name)
        return run.status.get("phase") if run is not None else None

    def members_settled(self, expected: set[str]) -> bool:
        """The published map lists exactly ``expected`` AND every member
        has acked the map's epoch (the barrier cleared) — the
        outside-observer form of the in-process router check."""
        m = self.store.try_get(SHARD_MAP_KIND, SHARD_NAMESPACE, SHARD_MAP_NAME)
        if m is None:
            return False
        members = {str(x) for x in (m.spec.get("members") or [])}
        if members != set(expected):
            return False
        epoch = int(m.spec.get("epoch") or 0)
        acks = (m.status or {}).get("acks") or {}
        return all(int(acks.get(s, 0)) >= epoch for s in members)

    def wait_members(self, expected: set[str], timeout: float = 60.0) -> None:
        def detail() -> str:
            m = self.store.try_get(
                SHARD_MAP_KIND, SHARD_NAMESPACE, SHARD_MAP_NAME)
            return (
                f"map never settled on {sorted(expected)}: "
                f"spec={m and m.spec} status={m and m.status}"
            )

        self.wait_until(lambda: self.members_settled(expected), timeout, detail)

    def steady_state_steps_per_sec(
        self,
        story: str,
        window: int,
        measure_s: float = 6.0,
        warmup_s: float = 2.5,
        namespace: str = "default",
        drain_timeout: float = 60.0,
    ) -> float:
        """Same closed-loop measurement as the in-process harness (keep
        ``window`` outstanding, count completions inside the timed
        window only) — over RPCs, so the parent's polling cost is part
        of the measured client-side reality."""
        outstanding: list[str] = []
        submitted = done_meas = 0
        warm_end = time.perf_counter() + warmup_s
        t_meas0 = None
        while True:
            now = time.perf_counter()
            if t_meas0 is None and now >= warm_end:
                t_meas0 = now
            if t_meas0 is not None and now - t_meas0 >= measure_s:
                break
            while len(outstanding) < window:
                outstanding.append(self.run_story(
                    story, inputs={"i": submitted}, namespace=namespace))
                submitted += 1
            still = []
            for r in outstanding:
                if self.run_phase(r, namespace) in _TERMINAL:
                    done_meas += t_meas0 is not None
                else:
                    still.append(r)
            outstanding = still
            time.sleep(0.02)
        wall = time.perf_counter() - t_meas0
        self.wait_runs(outstanding, timeout=drain_timeout, namespace=namespace)
        return done_meas / wall

    def wait_runs(self, runs, timeout: float = 60.0,
                  namespace: str = "default") -> None:
        remaining = set(runs)
        deadline = time.monotonic() + timeout
        while remaining:
            for r in list(remaining):
                if self.run_phase(r, namespace) in _TERMINAL:
                    remaining.discard(r)
            if not remaining:
                return
            if time.monotonic() > deadline:
                sample = [(r, self.run_phase(r, namespace))
                          for r in list(remaining)[:5]]
                raise AssertionError(
                    f"{len(remaining)} runs not terminal after {timeout}s; "
                    f"sample: {sample}"
                )
            time.sleep(0.1)

    @staticmethod
    def wait_until(cond, timeout: float, message="condition not met",
                   interval: float = 0.02) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            time.sleep(interval)
        raise AssertionError(message() if callable(message) else message)


# ---------------------------------------------------------------------------
# child entrypoint: one shard manager process
# ---------------------------------------------------------------------------

def _load_workload(spec: str) -> None:
    """Import ``module:function`` and call it — engram entrypoints must
    register in THIS interpreter; the executor runs here."""
    import importlib

    mod_name, _, fn_name = spec.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    fn()


def _publish_report(store, sid: str, detector, reason: str) -> None:
    from ..observability.analytics import LEDGER

    spec = {
        "shard": sid,
        "exit": reason,
        "processed": int(detector.processed.get(sid, 0)),
        "violations": [
            f"{v.root} on {list(v.shards)} ({v.controller} {v.key})"
            for v in detector.violations
        ],
        "ledgerUnbalanced": list(LEDGER.unbalanced()),
    }
    try:
        store.create(new_resource(SHARD_REPORT_KIND, sid, SHARD_NAMESPACE, spec))
    except Exception:  # noqa: BLE001 - restarted shard: replace the old report
        try:
            def mut(r):
                r.spec = spec

            store.mutate(SHARD_REPORT_KIND, SHARD_NAMESPACE, sid, mut)
        except (Conflict, NotFound):
            pass


def child_main(argv=None) -> int:
    import argparse
    import logging

    parser = argparse.ArgumentParser(
        prog="python -m bobrapet_tpu.shard.procharness")
    parser.add_argument("--child", action="store_true", required=True)
    parser.add_argument("--socket", required=True)
    parser.add_argument("--shard-id", required=True)
    parser.add_argument("--bootstrap", type=int, required=True)
    parser.add_argument("--executor-mode", default="threaded")
    parser.add_argument("--heartbeat-interval", type=float, default=0.25)
    parser.add_argument("--member-ttl", type=float, default=3.0)
    parser.add_argument("--lease-duration", type=float, default=4.0)
    parser.add_argument("--vnodes", type=int, default=DEFAULT_VNODES)
    parser.add_argument("--workload", default="")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format=(f"%(asctime)s shard-{args.shard_id} "
                "%(levelname)s %(name)s: %(message)s"),
    )

    from ..controllers.manager import Clock
    from ..core.events import EventRecorder
    from ..runtime import Runtime
    from ..store_service.client import StoreClient
    from .detector import DoubleReconcileDetector

    if args.workload:
        _load_workload(args.workload)

    sid = str(args.shard_id)
    store = StoreClient(args.socket)
    detector = DoubleReconcileDetector()
    rt = Runtime(
        store=store,
        clock=Clock(),
        shard_id=sid,
        shard_count=args.bootstrap,
        recorder=EventRecorder(),
        executor_mode=args.executor_mode,
        # chains are per-process in service mode: every client runs its
        # own admission (the in-process harness's first-runtime-only
        # rule is a shared-store artifact)
        enable_webhooks=True,
        shard_options={
            "heartbeat_interval": args.heartbeat_interval,
            "member_ttl": args.member_ttl,
            "lease_duration": args.lease_duration,
            "vnodes": args.vnodes,
        },
    )
    detector.install(rt)
    rt.start()

    command_box: list[str] = []
    got_command = threading.Event()
    control_name = f"shard-{sid}"

    def on_control(ev) -> None:
        if ev.resource.meta.name != control_name:
            return
        cmd = (ev.resource.spec or {}).get("command")
        if cmd in ("stop", "leave") and not command_box:
            command_box.append(cmd)
            got_command.set()

    store.watch(on_control, kinds=[SHARD_CONTROL_KIND])
    # a command written before the watch registered must still land
    pre = store.try_get(SHARD_CONTROL_KIND, SHARD_NAMESPACE, control_name)
    if pre is not None:
        cmd = (pre.spec or {}).get("command")
        if cmd in ("stop", "leave") and not command_box:
            command_box.append(cmd)
            got_command.set()

    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, lambda *_: (
            command_box.append("stop") if not command_box else None,
            got_command.set(),
        ))

    got_command.wait()
    command = command_box[0]
    if command == "leave":
        rt.shard_coordinator.request_leave()
        deadline = time.monotonic() + 60.0
        while not rt.shard_coordinator.retired:
            if time.monotonic() > deadline:
                _publish_report(store, sid, detector, "leave-timeout")
                rt.stop()
                return 3
            time.sleep(0.05)
    _publish_report(store, sid, detector, command)
    rt.stop()
    store.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(child_main())
