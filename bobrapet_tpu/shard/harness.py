"""N in-process managers over one coordination bus, for tests + bench.

``ShardedControlPlane`` assembles N full Runtimes that share ONE
ResourceStore (the bus), each with its own shard identity, router,
coordinator, dispatcher pools, placer and executor — the in-process
model of N manager replicas against a shared API server. Everything
the real deployment would exercise runs for real here: fenced map
publish, watch partitioning, the drain/ack/promote barrier, cross-shard
``executeStory`` handoff, graceful leave and crash detection. What it
deliberately does NOT model is GIL-free CPU parallelism — production
runs one process per shard; this harness measures coordination
correctness and latency-bound throughput (see docs/SCALING.md).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..api.enums import Phase
from ..core.events import EventRecorder
from ..core.store import ResourceStore
from ..controllers.manager import Clock
from .detector import DoubleReconcileDetector
from .ring import DEFAULT_VNODES


class ShardedControlPlane:
    def __new__(cls, *args, processes: bool = False, **kwargs):
        """``processes=True`` returns the process-mode harness instead:
        one OS process per shard plus a durable store-service process
        (``procharness.ProcessShardedControlPlane``, which takes
        ``config_data``/``workload`` in place of ``configure``). The
        returned object is not a ShardedControlPlane, so ``__init__``
        below never runs on it — kwargs pass through untouched."""
        if processes and cls is ShardedControlPlane:
            from .procharness import ProcessShardedControlPlane

            return ProcessShardedControlPlane(*args, **kwargs)
        return super().__new__(cls)

    def __init__(
        self,
        shards: int = 2,
        executor_mode: str = "threaded",
        heartbeat_interval: float = 0.25,
        member_ttl: float = 3.0,
        lease_duration: float = 4.0,
        vnodes: int = DEFAULT_VNODES,
        configure: Optional[Callable] = None,
        processes: bool = False,
    ):
        from ..runtime import Runtime  # late: runtime imports this package

        self._runtime_cls = Runtime
        self.store = ResourceStore()
        self.clock = Clock()  # real clock: shards run live, threaded
        self.recorder = EventRecorder()
        self.detector = DoubleReconcileDetector()
        self.executor_mode = executor_mode
        self._configure = configure
        self._bootstrap_count = max(1, int(shards))
        self._shard_options = {
            "heartbeat_interval": heartbeat_interval,
            "member_ttl": member_ttl,
            "lease_duration": lease_duration,
            "vnodes": vnodes,
        }
        self.runtimes: dict[str, "Runtime"] = {}
        self._next_id = 0
        self._started = False
        for _ in range(self._bootstrap_count):
            self.add_shard()

    # -- membership --------------------------------------------------------
    def add_shard(self) -> str:
        """Create a shard runtime. Before ``start()`` this builds the
        initial fleet; after, it is a live JOIN — the new member owns
        nothing until the leader publishes a map including it and the
        rebalance barrier clears."""
        sid = str(self._next_id)
        self._next_id += 1
        rt = self._runtime_cls(
            store=self.store,
            clock=self.clock,
            shard_id=sid,
            # every member bootstraps the SAME epoch-0 ring (the initial
            # fleet size): a joiner owns nothing under it, so rings
            # agree everywhere until a published map supersedes them
            shard_count=self._bootstrap_count,
            recorder=self.recorder,
            executor_mode=self.executor_mode,
            enable_webhooks=not self.runtimes,  # admission is per-store
            shard_options=dict(self._shard_options),
        )
        if self._configure is not None:
            self._configure(rt.config_manager.config)
        self.detector.install(rt)
        self.runtimes[sid] = rt
        if self._started:
            rt.start()
        return sid

    def leave_shard(self, sid: str, timeout: float = 60.0) -> None:
        """Graceful leave: drain, ack the removal barrier, retire."""
        rt = self.runtimes[sid]
        rt.shard_coordinator.request_leave()
        self.wait_until(
            lambda: rt.shard_coordinator.retired, timeout,
            f"shard {sid} did not retire",
        )
        rt.stop()
        del self.runtimes[sid]

    def kill_shard(self, sid: str) -> None:
        """Crash: no drain, no ack, NO graceful lease release — the
        leader detects the stale member heartbeat and republishes
        without it, and a crashed leader's lease must be outlived
        (TTL expiry + fencing), never handed over."""
        rt = self.runtimes.pop(sid)
        rt.shard_coordinator.crash()
        rt.stop()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._started = True
        for rt in self.runtimes.values():
            rt.start()

    def stop(self) -> None:
        self._started = False
        for rt in self.runtimes.values():
            rt.stop()

    def __enter__(self) -> "ShardedControlPlane":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- convenience -------------------------------------------------------
    @property
    def any(self):
        """Any live runtime (definitions/stories apply through the
        shared bus, so the entry shard does not matter)."""
        return next(iter(self.runtimes.values()))

    def apply(self, resource):
        return self.any.apply(resource)

    def run_story(self, story: str, inputs=None, name=None,
                  namespace: str = "default") -> str:
        return self.any.run_story(story, inputs=inputs, name=name,
                                  namespace=namespace)

    def run_phase(self, run_name: str, namespace: str = "default"):
        return self.any.run_phase(run_name, namespace)

    def members_settled(self, expected: set[str]) -> bool:
        """Every live router's ACTIVE ring matches ``expected`` and no
        rebalance is in flight."""
        for sid, rt in self.runtimes.items():
            router = rt.shard_router
            if set(router.members()) != expected or router.rebalancing:
                return False
        return True

    def wait_members(self, expected: set[str], timeout: float = 30.0) -> None:
        self.wait_until(
            lambda: self.members_settled(expected), timeout,
            f"rings never settled on {sorted(expected)}: "
            f"{ {sid: rt.shard_router.members() for sid, rt in self.runtimes.items()} }",
        )

    def steady_state_steps_per_sec(
        self,
        story: str,
        window: int,
        measure_s: float = 6.0,
        warmup_s: float = 2.5,
        namespace: str = "default",
        drain_timeout: float = 60.0,
    ) -> float:
        """Closed-loop steady-state throughput: keep ``window`` runs of
        ``story`` outstanding, count completions inside the timed
        window only (warmup fills the pipeline, the drain tail is
        excluded — fixed-N soaks under-read multi-shard scaling by the
        tail, where emptying shards idle). Drains every outstanding run
        before returning, so the detector ledger is settled."""
        outstanding: list[str] = []
        submitted = done_meas = 0
        warm_end = time.perf_counter() + warmup_s
        t_meas0 = None
        while True:
            now = time.perf_counter()
            if t_meas0 is None and now >= warm_end:
                t_meas0 = now
            if t_meas0 is not None and now - t_meas0 >= measure_s:
                break
            while len(outstanding) < window:
                outstanding.append(self.run_story(
                    story, inputs={"i": submitted}, namespace=namespace))
                submitted += 1
            still = []
            for r in outstanding:
                if self.run_phase(r, namespace) in (Phase.SUCCEEDED,
                                                    Phase.FAILED):
                    done_meas += t_meas0 is not None
                else:
                    still.append(r)
            outstanding = still
            time.sleep(0.02)
        wall = time.perf_counter() - t_meas0
        self.wait_runs(outstanding, timeout=drain_timeout,
                       namespace=namespace)
        return done_meas / wall

    def wait_runs(self, runs, timeout: float = 60.0,
                  namespace: str = "default") -> None:
        """Wait for every run to turn terminal. Polls INCREMENTALLY at
        a coarse interval — a tight loop re-reading the whole
        population from the main thread convoys the store lock against
        all N shards' workers (measured: it halves soak throughput)."""
        remaining = set(runs)
        deadline = time.monotonic() + timeout
        while remaining:
            for r in list(remaining):
                if self.run_phase(r, namespace) in (Phase.SUCCEEDED, Phase.FAILED):
                    remaining.discard(r)
            if not remaining:
                return
            if time.monotonic() > deadline:
                sample = [(r, self.run_phase(r, namespace))
                          for r in list(remaining)[:5]]
                raise AssertionError(
                    f"{len(remaining)} runs not terminal after {timeout}s; "
                    f"sample: {sample}"
                )
            time.sleep(0.1)

    @staticmethod
    def wait_until(cond: Callable[[], bool], timeout: float,
                   message="condition not met", interval: float = 0.02) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            time.sleep(interval)
        raise AssertionError(message() if callable(message) else message)
