"""Story admission: defaults + the full validation battery.

The counterpart of the reference's Story webhook
(reference: internal/webhook/v1alpha1/story_webhook.go:90 Default,
:164 ValidateCreate/Update — step shape, unique names, needs existence,
batch-only primitives rejected in realtime, primitive `with` shapes,
per-scope template static validation :832-848, `with` size caps,
executeStory reference cycles, policy timeout parsing).
"""

from __future__ import annotations

from typing import Any, Optional

from ..api.enums import BATCH_ONLY_PRIMITIVES, StepType, StoryPattern
from ..api.story import KIND as STORY_KIND, StorySpec, parse_story
from ..core.object import Resource
from ..core.store import ResourceStore
from ..templating.engine import (
    ROOT_INPUTS,
    ROOT_PACKET,
    ROOT_RUN,
    ROOT_STEPS,
    Evaluator,
    TemplateError,
)
from ..utils.duration import DurationError, parse_duration
from .validation import (
    FieldErrors,
    json_size,
    validate_name,
    validate_template_safety,
    walk_strings,
)

#: Scope roots per evaluation context
#: (reference: story_webhook.go:832-848 — batch runtime vs realtime
#: static vs realtime runtime vs output template).
SCOPE_BATCH_RUNTIME = frozenset({ROOT_INPUTS, ROOT_STEPS, ROOT_RUN})
SCOPE_REALTIME_STATIC = frozenset({ROOT_INPUTS, ROOT_RUN})
SCOPE_REALTIME_RUNTIME = frozenset({ROOT_INPUTS, ROOT_RUN, ROOT_PACKET})
SCOPE_OUTPUT = frozenset({ROOT_INPUTS, ROOT_STEPS, ROOT_RUN})

DEFAULT_MAX_WITH_BLOCK_SIZE = 256 * 1024  # reference: MaxStoryWithBlockSizeBytes

_VALID_ON_TIMEOUT = {"fail", "skip"}
# stop accepts StopMode aliases and terminal Phase names
# (reference: step_executor.go:1084-1101 + pkg/enums StopMode)
_VALID_STOP_PHASES = {
    "success", "failure", "cancel",
    "Succeeded", "Failed", "Finished", "Canceled",
}


class StoryWebhook:
    def __init__(self, store: ResourceStore, evaluator: Evaluator, config_manager=None):
        self.store = store
        self.evaluator = evaluator
        self.config_manager = config_manager

    # -- mutating admission ------------------------------------------------
    def default(self, resource: Resource) -> None:
        """(reference: story_webhook.go:90 Default)"""
        spec = resource.spec
        spec.setdefault("pattern", str(StoryPattern.BATCH))
        for step in spec.get("steps") or []:
            if isinstance(step, dict) and step.get("type") == str(StepType.WAIT):
                with_ = step.setdefault("with", {})
                if isinstance(with_, dict):
                    with_.setdefault("onTimeout", "fail")

    # -- validating admission ----------------------------------------------
    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(STORY_KIND, resource.meta.name)
        validate_name(errs, "metadata.name", resource.meta.name)
        try:
            spec = parse_story(resource)
        except Exception as e:  # noqa: BLE001 - malformed spec is a user error
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        realtime = spec.effective_pattern.is_realtime
        self._validate_steps(errs, resource, spec, spec.steps, "spec.steps", realtime)
        self._validate_steps(
            errs, resource, spec, spec.compensations, "spec.compensations", realtime
        )
        self._validate_steps(
            errs, resource, spec, spec.finally_, "spec.finally", realtime
        )
        self._validate_output(errs, spec)
        self._validate_policy(errs, spec)
        self._validate_streaming_layers(errs, spec)
        errs.raise_if_any()

    def _validate_streaming_layers(self, errs: FieldErrors, spec) -> None:
        """Coherence-validate the MERGED streaming settings each
        streaming step would bind with (transport defaults -> story
        declaration -> step runtime). Layers are merged before checking
        because an individually-incomplete layer (e.g. a step enabling
        credits whose window the transport supplies) can be coherent in
        combination — and vice versa: a step override can break an
        admitted transport config, which must be caught HERE, the layer
        the user is writing."""
        from ..api.catalog import CLUSTER_NAMESPACE
        from ..api.transport import TRANSPORT_KIND, parse_transport
        from ..transport.settings import merge_streaming_settings
        from .transport import validate_streaming_settings

        declared = {t.name or t.transport_ref: t for t in spec.transports}
        for i, step in enumerate(spec.steps):
            t = declared.get(step.transport) if step.transport else None
            step_streaming = (step.runtime or {}).get("streaming")
            if t is None and not step_streaming:
                continue
            transport_defaults = None
            if t is not None:
                tr = self.store.try_get(
                    TRANSPORT_KIND, CLUSTER_NAMESPACE, t.transport_ref or t.name
                )
                if tr is not None:
                    try:
                        transport_defaults = parse_transport(tr).streaming
                    except Exception:  # noqa: BLE001 - validated at its own admission
                        transport_defaults = None
            try:
                merged = merge_streaming_settings(
                    transport_defaults,
                    (t.streaming or t.settings) if t is not None else None,
                    step_streaming,
                )
            except Exception as e:  # noqa: BLE001 - malformed override
                errs.add(f"spec.steps[{i}].runtime.streaming", f"malformed: {e}")
                continue
            # errors point at the user-writable field, runtime.streaming
            validate_streaming_settings(
                merged, errs, f"spec.steps[{i}].runtime.streaming"
            )

    # -- step battery ------------------------------------------------------
    def _validate_steps(
        self,
        errs: FieldErrors,
        resource: Resource,
        spec: StorySpec,
        steps: list,
        path: str,
        realtime: bool,
        nested: bool = False,
    ) -> None:
        seen: set[str] = set()
        names = {s.name for s in steps}
        for i, step in enumerate(steps):
            p = f"{path}[{i}]"
            if not step.name:
                errs.add(p + ".name", "step name is required")
            elif step.name in seen:
                # (reference: CEL-validated uniqueness, story_types.go:88)
                errs.add(p + ".name", f"duplicate step name {step.name!r}")
            seen.add(step.name)

            # exactly one of ref / type (reference: story_types.go:88 CEL)
            if bool(step.ref) == bool(step.type):
                errs.add(p, "exactly one of `ref` (engram) or `type` (primitive) must be set")
            elif step.type is not None and not isinstance(step.type, StepType):
                # forward-compat parsing keeps unknown enum strings
                # verbatim (specbase.py) — admission must still reject
                # them, mirroring the schema's enum (parity suite)
                errs.add(
                    p + ".type",
                    f"unknown step type {step.type!r} (one of "
                    f"{sorted(t.value for t in StepType)})",
                )

            for dep in step.needs:
                if dep == step.name:
                    errs.add(p + ".needs", "step cannot depend on itself")
                elif dep not in names:
                    errs.add(p + ".needs", f"unknown step {dep!r}")

            if realtime and step.type in BATCH_ONLY_PRIMITIVES:
                # (reference: batch-only primitives rejected in realtime)
                errs.add(p + ".type", f"primitive {step.type} is batch-only")

            self._validate_primitive_with(errs, resource, spec, step, p, realtime, nested)
            self._validate_step_templates(errs, step, p, realtime)

            if step.execution is not None and step.execution.retry is not None:
                # same bounds the Engram webhook applies (and the
                # schema mirrors on RetryPolicy): a step-level override
                # must not smuggle invalid retry math past admission
                from .engram import _validate_retry

                _validate_retry(errs, step.execution.retry,
                                p + ".execution.retry")

            with_size = json_size(step.with_) if step.with_ else 0
            if with_size > self._max_with_size():
                errs.add(
                    p + ".with",
                    f"size {with_size} exceeds cap {self._max_with_size()}",
                )

        # needs cycle detection over this step list
        self._detect_needs_cycle(errs, steps, path)

    def _validate_primitive_with(
        self, errs, resource, spec, step, p, realtime, nested
    ) -> None:
        """Primitive `with` shapes (reference SURVEY §2.2 primitive table:
        dag.go:1549,1569,1608, step_executor.go:1084-1215,741-747)."""
        w = step.with_ or {}
        t = step.type
        if t is StepType.SLEEP:
            if not w.get("duration"):
                errs.add(p + ".with.duration", "sleep requires `duration`")
            else:
                self._check_duration(errs, p + ".with.duration", w["duration"])
        elif t is StepType.WAIT:
            if not w.get("until"):
                errs.add(p + ".with.until", "wait requires `until` template")
            self._check_duration(errs, p + ".with.timeout", w.get("timeout"))
            self._check_duration(errs, p + ".with.pollInterval", w.get("pollInterval"))
            if w.get("onTimeout") not in (None, *_VALID_ON_TIMEOUT):
                errs.add(p + ".with.onTimeout", "must be `fail` or `skip`")
        elif t is StepType.GATE:
            self._check_duration(errs, p + ".with.timeout", w.get("timeout"))
            self._check_duration(errs, p + ".with.pollInterval", w.get("pollInterval"))
            if w.get("onTimeout") not in (None, *_VALID_ON_TIMEOUT):
                errs.add(p + ".with.onTimeout", "must be `fail` or `skip`")
        elif t is StepType.STOP:
            if w.get("phase") not in (None, *_VALID_STOP_PHASES):
                errs.add(p + ".with.phase", f"must be one of {sorted(_VALID_STOP_PHASES)}")
        elif t is StepType.EXECUTE_STORY:
            ref = w.get("storyRef")
            if not (isinstance(ref, dict) and ref.get("name")):
                errs.add(p + ".with.storyRef", "executeStory requires `storyRef.name`")
            else:
                self._check_execute_story_cycle(errs, resource, ref, p)
        elif t is StepType.PARALLEL:
            branches = w.get("steps")
            replicated = w.get("replicas") is not None or isinstance(
                w.get("step"), dict
            )
            if replicated and isinstance(branches, list) and branches:
                errs.add(
                    p + ".with",
                    "parallel takes either `steps` or `replicas`+`step`, "
                    "not both",
                )
            elif replicated and nested:
                # same rule as the explicit spelling — a replicated
                # fan-out nested inside another parallel would only
                # fail at execution time otherwise
                errs.add(p + ".with",
                         "parallel branches cannot nest another parallel")
            elif replicated:
                try:
                    n = int(w.get("replicas") or 0)
                except (TypeError, ValueError):
                    n = 0
                if n < 1:
                    errs.add(p + ".with.replicas",
                             "replicas must be an integer >= 1")
                if not isinstance(w.get("step"), dict):
                    errs.add(p + ".with.step",
                             "replicas fan-out requires a `step` template")
                pools = w.get("pools")
                if pools is not None and not (
                    isinstance(pools, list)
                    and pools
                    and all(isinstance(x, str) and x for x in pools)
                ):
                    errs.add(p + ".with.pools",
                             "must be a non-empty list of pool names")
                if n >= 1 and isinstance(w.get("step"), dict):
                    try:
                        from ..api.story import expand_parallel_branches

                        parsed = expand_parallel_branches(step)
                    except Exception as e:  # noqa: BLE001
                        errs.add(p + ".with.step", f"malformed template: {e}")
                    else:
                        self._validate_steps(
                            errs, resource, spec, parsed[:1], p + ".with.step",
                            realtime, nested=True,
                        )
            elif not isinstance(branches, list) or not branches:
                errs.add(
                    p + ".with.steps",
                    "parallel requires a non-empty `steps` list (or "
                    "`replicas`+`step` for a spanning fan-out)",
                )
            elif nested:
                errs.add(p + ".with.steps", "parallel branches cannot nest another parallel")
            else:
                try:
                    from ..api.story import Step

                    parsed = [Step.from_dict(b) for b in branches]
                except Exception as e:  # noqa: BLE001
                    errs.add(p + ".with.steps", f"malformed branch: {e}")
                else:
                    self._validate_steps(
                        errs, resource, spec, parsed, p + ".with.steps",
                        realtime, nested=True,
                    )
        elif t is StepType.CONDITION:
            # no `with` machinery (reference: step_executor.go:168-170)
            pass

    def _validate_step_templates(self, errs, step, p, realtime) -> None:
        """Per-scope static validation
        (reference: story_webhook.go:832-848)."""
        if realtime:
            config_scope = SCOPE_REALTIME_STATIC if not step.ref else SCOPE_REALTIME_RUNTIME
        else:
            config_scope = SCOPE_BATCH_RUNTIME
        if step.if_:
            self._check_template(errs, p + ".if", step.if_, config_scope)
        for tpath, text in walk_strings(step.with_ or {}, p + ".with"):
            self._check_template(errs, tpath, text, config_scope)
        if step.idempotency_key_template:
            self._check_template(
                errs, p + ".idempotencyKeyTemplate",
                step.idempotency_key_template, SCOPE_BATCH_RUNTIME,
            )
        if step.post_execution and step.post_execution.condition:
            # postExecution sees the step's own output
            self._check_template(
                errs, p + ".postExecution.condition",
                step.post_execution.condition,
                SCOPE_BATCH_RUNTIME | {"output"},
            )

    def _validate_output(self, errs, spec: StorySpec) -> None:
        for tpath, text in walk_strings(spec.output or {}, "spec.output"):
            self._check_template(errs, tpath, text, SCOPE_OUTPUT)

    def _validate_policy(self, errs, spec: StorySpec) -> None:
        pol = spec.policy
        if pol is None:
            return
        if pol.timeouts is not None:
            self._check_duration(errs, "spec.policy.timeouts.story", pol.timeouts.story)
            self._check_duration(errs, "spec.policy.timeouts.step", pol.timeouts.step)
            self._check_duration(
                errs, "spec.policy.timeouts.gracefulShutdownTimeout",
                pol.timeouts.graceful_shutdown_timeout,
            )
        if pol.concurrency is not None and pol.concurrency < 1:
            errs.add("spec.policy.concurrency", "must be >= 1")

    # -- helpers -----------------------------------------------------------
    def _check_template(self, errs, path, text, roots) -> None:
        if "{{" not in text:
            return
        if not validate_template_safety(errs, path, text):
            return
        try:
            self.evaluator.validate(text, allowed_roots=roots)
        except TemplateError as e:
            errs.add(path, str(e))

    def _check_duration(self, errs, path, value) -> None:
        if value in (None, ""):
            return
        try:
            parse_duration(value)
        except DurationError as e:
            errs.add(path, str(e))

    def _check_execute_story_cycle(self, errs, resource, ref: dict, p) -> None:
        """Reject direct and transitive executeStory cycles reachable
        through stories that already exist
        (reference: executeStory reference cycle validation)."""
        start = (ref.get("namespace") or resource.meta.namespace, ref.get("name"))
        if start == (resource.meta.namespace, resource.meta.name):
            errs.add(p + ".with.storyRef", "executeStory must not reference its own story")
            return
        seen = set()
        frontier = [start]
        while frontier:
            ns, name = frontier.pop()
            if (ns, name) in seen:
                continue
            seen.add((ns, name))
            target = self.store.try_get(STORY_KIND, ns, name)
            if target is None:
                continue
            for child in _execute_story_refs(target):
                cns = child.get("namespace") or ns
                cname = child.get("name")
                if (cns, cname) == (resource.meta.namespace, resource.meta.name):
                    errs.add(
                        p + ".with.storyRef",
                        f"executeStory cycle via {ns}/{name}",
                    )
                    return
                frontier.append((cns, cname))

    def _detect_needs_cycle(self, errs, steps, path) -> None:
        graph = {s.name: [d for d in s.needs] for s in steps}
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}

        def visit(n) -> bool:
            color[n] = GRAY
            for d in graph.get(n, []):
                if color.get(d, BLACK) == GRAY:
                    return True
                if color.get(d) == WHITE and visit(d):
                    return True
            color[n] = BLACK
            return False

        for n in graph:
            if color[n] == WHITE and visit(n):
                errs.add(path, f"dependency cycle involving step {n!r}")
                return

    def _max_with_size(self) -> int:
        """(reference: MaxStoryWithBlockSizeBytes, controller_config.go:80)"""
        if self.config_manager is not None:
            return self.config_manager.config.max_story_with_block_size_bytes
        return DEFAULT_MAX_WITH_BLOCK_SIZE


def _execute_story_refs(story: Resource) -> list[dict[str, Any]]:
    """Every executeStory target in the story — main/compensation/finally
    lists AND parallel branches (a cycle through any of them recurses at
    runtime just the same)."""
    out: list[dict[str, Any]] = []

    def walk(steps) -> None:
        for step in steps or []:
            if not isinstance(step, dict):
                continue
            if step.get("type") == str(StepType.EXECUTE_STORY):
                ref = (step.get("with") or {}).get("storyRef")
                if isinstance(ref, dict):
                    out.append(ref)
            elif step.get("type") == str(StepType.PARALLEL):
                walk((step.get("with") or {}).get("steps"))

    walk(story.spec.get("steps"))
    walk(story.spec.get("compensations"))
    walk(story.spec.get("finally"))
    return out
