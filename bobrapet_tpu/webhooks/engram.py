"""Engram + Impulse admission.

The counterpart of the reference's Engram/Impulse webhooks
(reference: internal/webhook/v1alpha1/{engram,impulse}_webhook.go —
templateRef existence + mode support, secret-schema conformance,
retry defaults retry_defaults.go, cross-namespace reference policy
reference_validation.go).
"""

from __future__ import annotations

from typing import Optional

from ..api.catalog import (
    CLUSTER_NAMESPACE,
    ENGRAM_TEMPLATE_KIND,
    IMPULSE_TEMPLATE_KIND,
    parse_engram_template,
    parse_impulse_template,
)
from ..api.engram import KIND as ENGRAM_KIND, parse_engram
from ..api.impulse import KIND as IMPULSE_KIND, parse_impulse
from ..api.story import KIND as STORY_KIND
from ..core.object import Resource
from ..core.store import ResourceStore
from ..utils.duration import DurationError, parse_duration
from .policy import check_cross_namespace
from .validation import FieldErrors

#: Retry defaults injected when an Engram declares retries without knobs
#: (reference: retry_defaults.go).
DEFAULT_RETRY = {"maxRetries": 3, "delay": "5s", "backoff": "exponential"}


def _validate_secrets(errs: FieldErrors, declared: dict, schema, path: str) -> None:
    """Secret-schema conformance: required secrets present, no unknown
    names when a schema is declared."""
    by_name = {s.name: s for s in schema}
    for s in schema:
        if s.required and s.name not in declared:
            errs.add(f"{path}.{s.name}", "required secret is missing")
    if by_name:
        for name in declared:
            if name not in by_name:
                errs.add(f"{path}.{name}", "not declared in template secretSchema")


def _validate_retry(errs: FieldErrors, retry, path: str) -> None:
    if retry is None:
        return
    if retry.max_retries is not None and retry.max_retries < 0:
        errs.add(path + ".maxRetries", "must be >= 0")
    for field in ("delay", "max_delay"):
        val = getattr(retry, field, None)
        if val:
            try:
                parse_duration(val)
            except DurationError as e:
                errs.add(f"{path}.{field}", str(e))
    if retry.jitter is not None and not (0 <= retry.jitter <= 100):
        errs.add(path + ".jitter", "must be a percentage 0-100")


class EngramWebhook:
    def __init__(self, store: ResourceStore, config_manager=None):
        self.store = store
        self.config_manager = config_manager

    def default(self, resource: Resource) -> None:
        exec_ = resource.spec.get("execution")
        if isinstance(exec_, dict) and exec_.get("retry") == {}:
            exec_["retry"] = dict(DEFAULT_RETRY)

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(ENGRAM_KIND, resource.meta.name)
        try:
            spec = parse_engram(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if spec.template_ref is None or not spec.template_ref.name:
            errs.add("spec.templateRef", "templateRef.name is required")
            errs.raise_if_any()
            return
        template = self.store.try_get(
            ENGRAM_TEMPLATE_KIND, CLUSTER_NAMESPACE, spec.template_ref.name
        )
        if template is None:
            errs.add(
                "spec.templateRef",
                f"EngramTemplate {spec.template_ref.name!r} not found",
            )
            errs.raise_if_any()
            return
        tspec = parse_engram_template(template)
        if spec.mode is not None and not tspec.supports_mode(spec.mode):
            errs.add(
                "spec.mode",
                f"mode {spec.mode} not in template supportedModes "
                f"{[str(m) for m in tspec.supported_modes]}",
            )
        _validate_secrets(errs, spec.secrets, tspec.secret_schema, "spec.secrets")
        if spec.execution is not None:
            _validate_retry(errs, spec.execution.retry, "spec.execution.retry")
        errs.raise_if_any()


class ImpulseWebhook:
    def __init__(self, store: ResourceStore, config_manager=None):
        self.store = store
        self.config_manager = config_manager

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(IMPULSE_KIND, resource.meta.name)
        try:
            spec = parse_impulse(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if spec.template_ref is None or not spec.template_ref.name:
            errs.add("spec.templateRef", "templateRef.name is required")
        else:
            template = self.store.try_get(
                IMPULSE_TEMPLATE_KIND, CLUSTER_NAMESPACE, spec.template_ref.name
            )
            if template is None:
                errs.add(
                    "spec.templateRef",
                    f"ImpulseTemplate {spec.template_ref.name!r} not found",
                )
            else:
                tspec = parse_impulse_template(template)
                _validate_secrets(
                    errs, spec.secrets, tspec.secret_schema, "spec.secrets"
                )

        if spec.story_ref is None or not spec.story_ref.name:
            errs.add("spec.storyRef", "storyRef.name is required")
        else:
            ns = spec.story_ref.namespace or resource.meta.namespace
            check_cross_namespace(
                errs, self.store, self.config_manager,
                from_kind=IMPULSE_KIND, from_namespace=resource.meta.namespace,
                to_kind=STORY_KIND, to_namespace=ns, to_name=spec.story_ref.name,
                path="spec.storyRef",
            )

        if spec.throttle is not None:
            for field, key in (
                ("max_in_flight", "maxInFlight"),
                ("rate_per_second", "ratePerSecond"),
                ("burst", "burst"),
            ):
                val = getattr(spec.throttle, field, None)
                if val is not None and val < 1:
                    errs.add(f"spec.throttle.{key}", "must be >= 1")
        errs.raise_if_any()
