"""StoryTrigger + EffectClaim admission.

The counterpart of the reference's trigger/claim webhooks
(reference: internal/webhook/runs/v1alpha1 storytrigger/effectclaim
validators — identity requirements, name-derivation rules, lease shape).
"""

from __future__ import annotations

import re
from typing import Optional

from ..api.runs import (
    EFFECT_CLAIM_KIND,
    STORY_TRIGGER_KIND,
    parse_effectclaim,
    parse_storytrigger,
)
from ..core.object import Resource
from ..core.store import ResourceStore
from .validation import FieldErrors

_VALID_MODES = {"none", "key", "keyAndInputHash"}
_HASH_RE = re.compile(r"^[a-f0-9]{64}$")


class StoryTriggerWebhook:
    def __init__(self, store: ResourceStore, config_manager=None):
        self.store = store
        self.config_manager = config_manager

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(STORY_TRIGGER_KIND, resource.meta.name)
        try:
            spec = parse_storytrigger(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if spec.story_ref is None or not spec.story_ref.name:
            errs.add("spec.storyRef", "storyRef.name is required")

        ident = spec.identity
        if ident is None:
            errs.add("spec.identity", "identity is required")
        else:
            mode = ident.mode or "none"
            if mode not in _VALID_MODES:
                errs.add("spec.identity.mode", f"must be one of {sorted(_VALID_MODES)}")
            if mode in ("key", "keyAndInputHash") and not ident.key:
                errs.add("spec.identity.key", f"required when mode={mode}")
            if mode == "keyAndInputHash":
                if not ident.input_hash:
                    errs.add("spec.identity.inputHash", "required when mode=keyAndInputHash")
                elif not _HASH_RE.match(ident.input_hash):
                    errs.add("spec.identity.inputHash", "must be a sha256 hex digest")
            if mode == "none" and not ident.submission_id:
                errs.add(
                    "spec.identity.submissionId",
                    "required when mode=none (no other dedupe identity exists)",
                )

        # identity is immutable after creation — dedupe decisions would be
        # unsound otherwise (reference: name-derivation rules)
        if old is not None:
            if (old.spec.get("identity") or {}) != (resource.spec.get("identity") or {}):
                errs.add("spec.identity", "immutable after creation")
            if (old.spec.get("storyRef") or {}) != (resource.spec.get("storyRef") or {}):
                errs.add("spec.storyRef", "immutable after creation")

        errs.raise_if_any()


class EffectClaimWebhook:
    def __init__(self, store: ResourceStore, config_manager=None):
        self.store = store
        self.config_manager = config_manager

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(EFFECT_CLAIM_KIND, resource.meta.name)
        try:
            spec = parse_effectclaim(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if not spec.effect_id:
            errs.add("spec.effectId", "effectId is required")
        if not (isinstance(spec.step_run_ref, dict) and spec.step_run_ref.get("name")):
            errs.add("spec.stepRunRef", "stepRunRef.name is required")
        if not spec.holder_identity:
            errs.add("spec.holderIdentity", "holderIdentity is required")
        if spec.lease_duration_seconds is not None and spec.lease_duration_seconds < 1:
            errs.add("spec.leaseDurationSeconds", "must be >= 1")

        if old is not None:
            if old.spec.get("effectId") != resource.spec.get("effectId"):
                errs.add("spec.effectId", "immutable after creation")
            if (old.spec.get("stepRunRef") or {}) != (resource.spec.get("stepRunRef") or {}):
                errs.add("spec.stepRunRef", "immutable after creation")

        errs.raise_if_any()
