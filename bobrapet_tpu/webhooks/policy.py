"""Cross-namespace reference policy enforcement.

The counterpart of the reference's ValidateCrossNamespaceReference
(reference: internal/webhook/v1alpha1/validate_helpers.go:81-126):
``referenceCrossNamespacePolicy`` = deny (default) rejects any
cross-namespace reference; ``grant`` consults ReferenceGrants in the
target namespace (pkg/refs/reference_grant.go:26); ``allow`` permits
everything. Used by webhooks and controllers alike.
"""

from __future__ import annotations

from ..api.policy import reference_granted
from ..core.store import ResourceStore
from .validation import FieldErrors

POLICY_DENY = "deny"
POLICY_GRANT = "grant"
POLICY_ALLOW = "allow"


def cross_namespace_policy(config_manager) -> str:
    cfg = config_manager.config if config_manager else None
    return getattr(cfg, "reference_cross_namespace_policy", POLICY_DENY) or POLICY_DENY


def cross_namespace_allowed(
    store: ResourceStore,
    config_manager,
    from_kind: str,
    from_namespace: str,
    to_kind: str,
    to_namespace: str,
    to_name: str,
) -> bool:
    if from_namespace == to_namespace:
        return True
    policy = cross_namespace_policy(config_manager)
    if policy == POLICY_ALLOW:
        return True
    if policy == POLICY_GRANT:
        return reference_granted(
            store, from_kind, from_namespace, to_kind, to_namespace, to_name
        )
    return False


def check_cross_namespace(
    errs: FieldErrors,
    store: ResourceStore,
    config_manager,
    from_kind: str,
    from_namespace: str,
    to_kind: str,
    to_namespace: str,
    to_name: str,
    path: str,
) -> None:
    if not cross_namespace_allowed(
        store, config_manager, from_kind, from_namespace,
        to_kind, to_namespace, to_name,
    ):
        errs.add(
            path,
            f"cross-namespace reference {from_namespace} -> "
            f"{to_namespace}/{to_name} denied by policy "
            f"{cross_namespace_policy(config_manager)!r}",
        )
