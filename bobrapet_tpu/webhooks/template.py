"""EngramTemplate / ImpulseTemplate admission.

The counterpart of the reference's catalog validation (performed by the
catalog controllers + CRD schema in the reference; here the same checks
run at admission so bad templates never land in the catalog —
reference: internal/controller/catalog/template_helpers.go).
"""

from __future__ import annotations

from typing import Optional

from ..api.catalog import (
    ENGRAM_TEMPLATE_KIND,
    IMPULSE_TEMPLATE_KIND,
    parse_engram_template,
    parse_impulse_template,
)
from ..api.enums import SecretMountType
from ..core.object import Resource
from ..core.store import ResourceStore
from .validation import FieldErrors

_VALID_MOUNT_TYPES = {str(m) for m in SecretMountType}


def _validate_template(errs: FieldErrors, spec) -> None:
    if not spec.image and not spec.entrypoint:
        errs.add("spec", "one of `image` or `entrypoint` is required")
    seen = set()
    for i, secret in enumerate(spec.secret_schema):
        p = f"spec.secretSchema[{i}]"
        if not secret.name:
            errs.add(p + ".name", "secret name is required")
        elif secret.name in seen:
            errs.add(p + ".name", f"duplicate secret {secret.name!r}")
        seen.add(secret.name)
        if secret.mount_type is not None and str(secret.mount_type) not in _VALID_MOUNT_TYPES:
            errs.add(p + ".mountType", f"must be one of {sorted(_VALID_MOUNT_TYPES)}")
        if secret.mount_type is not None and str(secret.mount_type) in ("file", "both"):
            if not secret.mount_path:
                errs.add(p + ".mountPath", "required for file mounts")
    if spec.config_schema is not None and not isinstance(spec.config_schema, dict):
        errs.add("spec.configSchema", "must be a JSON schema object")


class EngramTemplateWebhook:
    def __init__(self, store: ResourceStore):
        self.store = store

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(ENGRAM_TEMPLATE_KIND, resource.meta.name)
        try:
            spec = parse_engram_template(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return
        _validate_template(errs, spec)
        if spec.input_schema is not None and not isinstance(spec.input_schema, dict):
            errs.add("spec.inputSchema", "must be a JSON schema object")
        if spec.output_schema is not None and not isinstance(spec.output_schema, dict):
            errs.add("spec.outputSchema", "must be a JSON schema object")
        errs.raise_if_any()


class ImpulseTemplateWebhook:
    def __init__(self, store: ResourceStore):
        self.store = store

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(IMPULSE_TEMPLATE_KIND, resource.meta.name)
        try:
            spec = parse_impulse_template(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return
        _validate_template(errs, spec)
        errs.raise_if_any()
