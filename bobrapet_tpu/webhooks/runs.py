"""StoryRun / StepRun admission.

The counterpart of the reference's runs webhooks
(reference: internal/webhook/runs/v1alpha1/storyrun_webhook.go —
storyRef required, inputs shape/size caps, JSON-schema validation against
Story.inputsSchema, storage-ref spoofing rejection :389, cancelRequested
transition rules :175-191, observedGeneration monotonicity; and
steprun_webhook.go:163-588 — field checks, size caps, downstream target
shape, StructuredError contract, observedGeneration monotonic).
"""

from __future__ import annotations

from typing import Any, Optional

from ..api.enums import ExitClass
from ..api.errors import ErrorType
from ..api.runs import (
    STEP_RUN_KIND,
    STORY_RUN_KIND,
    parse_steprun,
    parse_storyrun,
)
from ..api.story import KIND as STORY_KIND, parse_story
from ..core.object import Resource
from ..core.store import ResourceStore
from .policy import check_cross_namespace
from .validation import FieldErrors, find_storage_refs, json_size, validate_name

#: Size caps (reference: inputs shape/size caps; ~1MiB etcd-object
#: headroom — oversized payloads must go through storage offload).
DEFAULT_MAX_INPUTS_BYTES = 1 * 1024 * 1024
DEFAULT_MAX_OUTPUT_BYTES = 1 * 1024 * 1024
DEFAULT_MAX_OBJECT_BYTES = int(1.5 * 1024 * 1024)

_VALID_ERROR_TYPES = set(ErrorType.ALL)
_VALID_EXIT_CLASSES = {str(c) for c in ExitClass}


def _schema_validate(value: Any, schema: dict[str, Any], path: str) -> list[str]:
    """Minimal JSON-schema subset validation (type/required/properties/
    enum/items) — the same subset the StepRun controller enforces."""
    errors: list[str] = []
    t = schema.get("type")
    if t:
        py = {
            "object": dict, "array": list, "string": str,
            "number": (int, float), "integer": int, "boolean": bool,
        }.get(t)
        if py is not None and value is not None and not isinstance(value, py):
            errors.append(f"{path}: expected {t}")
            return errors
        # bool is an int subclass in Python; JSON schema says it is not
        if t in ("number", "integer") and isinstance(value, bool):
            errors.append(f"{path}: expected {t}")
    if "enum" in schema and value not in schema["enum"]:
        errors.append(f"{path}: not in enum {schema['enum']}")
    if isinstance(value, dict):
        for req in schema.get("required", []):
            if req not in value:
                errors.append(f"{path}.{req}: required property missing")
        props = schema.get("properties", {})
        for k, sub in props.items():
            if k in value:
                errors.extend(_schema_validate(value[k], sub, f"{path}.{k}"))
    if isinstance(value, list) and "items" in schema:
        for i, item in enumerate(value):
            errors.extend(_schema_validate(item, schema["items"], f"{path}[{i}]"))
    return errors


def _check_storage_refs(
    errs: FieldErrors, value: Any, namespace: str, path: str
) -> None:
    """Storage-ref spoofing rejection (reference: storyrun_webhook.go:389
    + pkg/storage validateStorageRef:518): refs must stay inside the
    resource's own namespace scope of the canonical offload key scheme
    (``runs/<namespace>/...``, StorageManager.step_key) so a run can
    never be pointed at another namespace's offloaded payloads."""
    for rpath, ref in find_storage_refs(value, path):
        key = ref.get("key") or ""
        if not key.startswith(f"runs/{namespace}/"):
            errs.add(
                rpath,
                f"storageRef key {key!r} outside namespace scope runs/{namespace}/",
            )


class StoryRunWebhook:
    def __init__(self, store: ResourceStore, config_manager=None):
        self.store = store
        self.config_manager = config_manager

    # -- spec admission ----------------------------------------------------
    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(STORY_RUN_KIND, resource.meta.name)
        validate_name(errs, "metadata.name", resource.meta.name)
        try:
            spec = parse_storyrun(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if spec.story_ref is None or not spec.story_ref.name:
            errs.add("spec.storyRef", "storyRef.name is required")
            errs.raise_if_any()
            return

        story_ns = spec.story_ref.namespace or resource.meta.namespace
        check_cross_namespace(
            errs, self.store, self.config_manager,
            from_kind=STORY_RUN_KIND, from_namespace=resource.meta.namespace,
            to_kind=STORY_KIND, to_namespace=story_ns, to_name=spec.story_ref.name,
            path="spec.storyRef",
        )

        inputs = spec.inputs
        if inputs is not None:
            if not isinstance(inputs, dict):
                errs.add("spec.inputs", "must be an object")
            else:
                size = json_size(inputs)
                if size > DEFAULT_MAX_INPUTS_BYTES:
                    errs.add(
                        "spec.inputs",
                        f"size {size} exceeds {DEFAULT_MAX_INPUTS_BYTES} "
                        "(offload through storage instead)",
                    )
                _check_storage_refs(
                    errs, inputs, resource.meta.namespace, "spec.inputs"
                )
                # schema validation on create only: the Story may evolve
                # while the run exists (reference: create-time check)
                if old is None:
                    story = self.store.try_get(
                        STORY_KIND, story_ns, spec.story_ref.name
                    )
                    if story is not None:
                        sspec = parse_story(story)
                        if sspec.inputs_schema:
                            for msg in _schema_validate(
                                inputs, sspec.inputs_schema, "spec.inputs"
                            ):
                                errs.add("spec.inputs", msg)

        # cancelRequested transition rules (reference: :175-191) — a
        # cancellation cannot be withdrawn
        if old is not None:
            was = bool(old.spec.get("cancelRequested"))
            now = bool(spec.cancel_requested)
            if was and not now:
                errs.add("spec.cancelRequested", "cannot be withdrawn once set")

        errs.raise_if_any()

    # -- status admission --------------------------------------------------
    def validate_status(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(STORY_RUN_KIND, resource.meta.name)
        _validate_observed_generation(errs, resource, old)
        errs.raise_if_any()


class StepRunWebhook:
    def __init__(self, store: ResourceStore, config_manager=None):
        self.store = store
        self.config_manager = config_manager

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(STEP_RUN_KIND, resource.meta.name)
        validate_name(errs, "metadata.name", resource.meta.name)
        try:
            spec = parse_steprun(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if spec.story_run_ref is None or not spec.story_run_ref.name:
            errs.add("spec.storyRunRef", "storyRunRef.name is required")
        else:
            # DNS-1123 shape, mirroring the schema's ObjectRef pattern
            # (parity suite): a ref that can never name a real object
            # must fail at admission, not at reconcile
            validate_name(errs, "spec.storyRunRef.name",
                          spec.story_run_ref.name)
        if spec.engram_ref is not None and spec.engram_ref.name:
            validate_name(errs, "spec.engramRef.name", spec.engram_ref.name)
        if spec.engram_ref is None or not spec.engram_ref.name:
            errs.add("spec.engramRef", "engramRef.name is required")

        if spec.input is not None:
            size = json_size(spec.input)
            if size > DEFAULT_MAX_INPUTS_BYTES:
                errs.add(
                    "spec.input",
                    f"size {size} exceeds {DEFAULT_MAX_INPUTS_BYTES}",
                )
            _check_storage_refs(
                errs, spec.input, resource.meta.namespace, "spec.input"
            )

        for i, tgt in enumerate(spec.downstream_targets):
            p = f"spec.downstreamTargets[{i}]"
            has_grpc = tgt.grpc is not None
            has_term = bool(tgt.terminate)
            if has_grpc == has_term:
                errs.add(p, "exactly one of `grpc` or `terminate` must be set")
            elif has_grpc:
                if not tgt.grpc.host:
                    errs.add(p + ".grpc.host", "host is required")
                if not (0 < tgt.grpc.port < 65536):
                    errs.add(p + ".grpc.port", "port must be 1-65535")

        total = json_size(resource.spec)
        if total > DEFAULT_MAX_OBJECT_BYTES:
            errs.add("spec", f"total object size {total} exceeds cap")

        errs.raise_if_any()

    def validate_status(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(STEP_RUN_KIND, resource.meta.name)
        _validate_observed_generation(errs, resource, old)

        output = resource.status.get("output")
        if output is not None:
            size = json_size(output)
            if size > DEFAULT_MAX_OUTPUT_BYTES:
                errs.add(
                    "status.output",
                    f"size {size} exceeds {DEFAULT_MAX_OUTPUT_BYTES} "
                    "(SDK must offload large outputs)",
                )

        err = resource.status.get("error")
        if err is not None:
            _validate_structured_error(errs, err)

        errs.raise_if_any()


def _validate_observed_generation(
    errs: FieldErrors, resource: Resource, old: Optional[Resource]
) -> None:
    """(reference: steprun_webhook.go:529, storyrun observedGeneration
    monotonicity) — status can never report a generation from the future
    or regress one already observed."""
    new_gen = resource.status.get("observedGeneration")
    if new_gen is None:
        return
    if not isinstance(new_gen, int) or new_gen < 0:
        errs.add("status.observedGeneration", "must be a non-negative integer")
        return
    if new_gen > resource.meta.generation:
        errs.add(
            "status.observedGeneration",
            f"{new_gen} is ahead of metadata.generation {resource.meta.generation}",
        )
    if old is not None:
        old_gen = old.status.get("observedGeneration")
        if isinstance(old_gen, int) and new_gen < old_gen:
            errs.add(
                "status.observedGeneration",
                f"cannot regress from {old_gen} to {new_gen}",
            )


def _validate_structured_error(errs: FieldErrors, err: Any) -> None:
    """StructuredError v1 contract
    (reference: api/runs/v1alpha1/structured_error_types.go:53)."""
    if not isinstance(err, dict):
        errs.add("status.error", "must be a StructuredError object")
        return
    etype = err.get("type")
    if etype is not None and str(etype) not in _VALID_ERROR_TYPES:
        errs.add("status.error.type", f"unknown error type {etype!r}")
    eclass = err.get("exitClass")
    if eclass is not None and str(eclass) not in _VALID_EXIT_CLASSES:
        errs.add("status.error.exitClass", f"unknown exit class {eclass!r}")
    if "message" in err and not isinstance(err["message"], str):
        errs.add("status.error.message", "must be a string")
    retryable = err.get("retryable")
    if retryable is not None and not isinstance(retryable, bool):
        errs.add("status.error.retryable", "must be a boolean")
