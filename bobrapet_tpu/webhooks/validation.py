"""Shared admission-validation helpers.

The counterpart of the reference's field-error aggregator
(reference: pkg/validation/aggregator.go) and template safety pre-checks
(reference: pkg/templatesafety/templatesafety.go — size/charset limits
applied before any expression is parsed).
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

from ..core.store import AdmissionDenied

# Template-safety limits (reference: templatesafety.ValidateTemplateString)
MAX_TEMPLATE_LENGTH = 8 * 1024
_CONTROL_CHARS = re.compile(r"[\x00-\x08\x0b\x0c\x0e-\x1f]")

# DNS-1123-subdomain-ish name shape shared by reference resource names.
NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9.-]{0,251}[a-z0-9])?$")


class FieldErrors:
    """Accumulates field errors; one AdmissionDenied with all of them
    (reference: pkg/validation aggregator — webhooks report every
    problem in one response, not just the first)."""

    def __init__(self, kind: str, name: str):
        self.kind = kind
        self.name = name
        self.errors: list[str] = []

    def add(self, path: str, message: str) -> None:
        self.errors.append(f"{path}: {message}")

    def require(self, condition: Any, path: str, message: str) -> None:
        if not condition:
            self.add(path, message)

    @property
    def ok(self) -> bool:
        return not self.errors

    def raise_if_any(self) -> None:
        if self.errors:
            raise AdmissionDenied(
                f"{self.kind} {self.name!r} is invalid: " + "; ".join(self.errors)
            )


def validate_template_safety(errs: FieldErrors, path: str, text: str) -> bool:
    """Cheap pre-checks before expression parsing; returns False when the
    string must not be handed to the evaluator."""
    if len(text) > MAX_TEMPLATE_LENGTH:
        errs.add(path, f"template exceeds {MAX_TEMPLATE_LENGTH} bytes")
        return False
    if _CONTROL_CHARS.search(text):
        errs.add(path, "template contains control characters")
        return False
    return True


def json_size(value: Any) -> int:
    """Canonical serialized size used for all object-size caps."""
    try:
        return len(json.dumps(value, separators=(",", ":"), sort_keys=True))
    except (TypeError, ValueError):
        return 0


def validate_name(errs: FieldErrors, path: str, name: Optional[str]) -> None:
    if not name:
        errs.add(path, "name is required")
    elif not NAME_RE.match(name):
        errs.add(path, f"invalid name {name!r} (must be DNS-1123 subdomain)")


def walk_strings(value: Any, path: str = ""):
    """Yield (path, string) pairs for every string in a JSON-like value."""
    if isinstance(value, str):
        yield path, value
    elif isinstance(value, dict):
        for k, v in value.items():
            yield from walk_strings(v, f"{path}.{k}" if path else str(k))
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            yield from walk_strings(v, f"{path}[{i}]")


def find_storage_refs(value: Any, path: str = ""):
    """Yield (path, refDict) for every storageRef marker in a value.

    Must mirror the runtime's ``is_storage_ref`` exactly
    (templating/engine.py:81-88 — any dict with a dict-valued
    ``storageRef`` key counts): anything hydrate would treat as a ref,
    admission must inspect (reference: offloaded_refs.go:23-207)."""
    if isinstance(value, dict):
        ref = value.get("storageRef")
        if isinstance(ref, dict):
            yield path, ref
            return
        for k, v in value.items():
            yield from find_storage_refs(v, f"{path}.{k}" if path else str(k))
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            yield from find_storage_refs(v, f"{path}[{i}]")
