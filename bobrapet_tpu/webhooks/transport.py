"""Transport / TransportBinding admission (schema-only, no config dep —
reference: internal/webhook/transport/v1alpha1/transport_webhook.go:378,
validation via pkg/transport/validation).
"""

from __future__ import annotations

import re
from typing import Optional

from ..api.transport import (
    DRIVER_GRPC,
    DRIVER_ICI,
    TRANSPORT_BINDING_KIND,
    TRANSPORT_KIND,
    parse_transport,
    parse_transport_binding,
)
from ..core.object import Resource
from ..core.store import ResourceStore
from .validation import FieldErrors

_VALID_DRIVERS = {DRIVER_GRPC, DRIVER_ICI, "webrtc"}
_VALID_DROP_POLICIES = {"dropOldest", "dropNewest", "block"}
_VALID_DELIVERY = {"atMostOnce", "atLeastOnce"}
_VALID_ORDERING = {"none", "perKey", "total"}
_VALID_ROUTING_MODES = {"auto", "hub", "p2p"}
_VALID_FAN_IN = {"merge", "zip", "quorum"}
_VALID_FLOW_MODES = {"none", "credits"}
_VALID_REPLAY_MODES = {"none", "fromCheckpoint", "full"}
_VALID_PARTITION_MODES = {"none", "keyHash", "roundRobin"}
_VALID_FAN_OUT = {"all", "first", "roundRobin"}
_VALID_RULE_ACTIONS = {"route", "drop", "duplicate"}
_VALID_LIFECYCLE = {"drain", "cutover"}
# both vocabularies: the reference's off|metadata|payload
# (sampleRate orthogonal) and the in-tree none|sample|full
_VALID_RECORDING = {"none", "off", "metadata", "payload",
                    "sample", "full"}


def validate_streaming_settings(st, errs: FieldErrors, path: str) -> None:
    """Enforcement-grade coherence validation of the streaming policy
    language: combinations the data plane cannot honor are REJECTED at
    admission rather than silently ignored (reference semantics:
    transport_settings_types.go:21-528 + pkg/transport/validation)."""
    if st.backpressure and st.backpressure.buffer:
        _validate_buffer(st.backpressure.buffer, errs, f"{path}.backpressure.buffer")
    fc = st.flow_control
    if fc is not None:
        if fc.mode not in (None, *_VALID_FLOW_MODES):
            errs.add(f"{path}.flowControl.mode",
                     f"must be one of {sorted(_VALID_FLOW_MODES)}")
        if fc.mode == "credits":
            credits = fc.initial_credits
            # the data plane grants message-granularity credits; a
            # bytes-only window would be admitted but never replenished
            if credits is None or (credits.messages or 0) < 1:
                errs.add(
                    f"{path}.flowControl.initialCredits.messages",
                    "mode=credits requires initialCredits.messages >= 1 "
                    "(bytes may only supplement the message window)",
                )
            for holder, nm in ((credits, "initialCredits"), (fc.ack_every, "ackEvery")):
                if holder is None:
                    continue
                for field, camel in (("messages", "messages"), ("bytes", "bytes")):
                    v = getattr(holder, field)
                    if v is not None and v < 1:
                        errs.add(f"{path}.flowControl.{nm}.{camel}", "must be >= 1")
        elif fc.mode in (None, "none"):
            # credit knobs without credit mode are inert — reject
            for field, v in (
                ("initialCredits", fc.initial_credits),
                ("ackEvery", fc.ack_every),
                ("pauseThreshold", fc.pause_threshold),
                ("resumeThreshold", fc.resume_threshold),
            ):
                if v is not None:
                    errs.add(f"{path}.flowControl.{field}",
                             "only meaningful with flowControl.mode=credits")
        pause, resume = fc.pause_threshold, fc.resume_threshold
        for nm, th in (("pauseThreshold", pause), ("resumeThreshold", resume)):
            if th is not None and th.buffer_pct is not None and not (
                0 < th.buffer_pct <= 100
            ):
                errs.add(f"{path}.flowControl.{nm}.bufferPct", "must be in (0, 100]")
        if (
            pause is not None and resume is not None
            and pause.buffer_pct is not None and resume.buffer_pct is not None
            and resume.buffer_pct >= pause.buffer_pct
        ):
            errs.add(f"{path}.flowControl.resumeThreshold.bufferPct",
                     "must be below pauseThreshold.bufferPct (hysteresis)")
    d = st.delivery
    if d is not None:
        if d.semantics not in (None, *_VALID_DELIVERY):
            errs.add(f"{path}.delivery.semantics",
                     f"must be one of {sorted(_VALID_DELIVERY)}")
        if d.ordering not in (None, *_VALID_ORDERING):
            errs.add(f"{path}.delivery.ordering",
                     f"must be one of {sorted(_VALID_ORDERING)}")
        if d.semantics == "atLeastOnce" and (
            fc is None or fc.mode != "credits" or fc.ack_every is None
        ):
            errs.add(
                f"{path}.delivery.semantics",
                "atLeastOnce requires flowControl.mode=credits with ackEvery "
                "(redelivery rides the ack protocol)",
            )
        r = d.replay
        if r is not None:
            if r.mode not in (None, *_VALID_REPLAY_MODES):
                errs.add(f"{path}.delivery.replay.mode",
                         f"must be one of {sorted(_VALID_REPLAY_MODES)}")
            # fromCheckpoint is ENFORCED since round 4: the hub
            # persists per-consumerId cumulative-ack positions in its
            # record store (every checkpointInterval + at detach) and
            # reattaching consumers resume after them automatically
            if r.mode == "fromCheckpoint" and (
                fc is None or fc.mode != "credits" or fc.ack_every is None
            ):
                errs.add(f"{path}.delivery.replay.mode",
                         "fromCheckpoint needs flowControl.mode=credits "
                         "with ackEvery (checkpoint positions come from "
                         "the ack protocol)")
            if r.mode in ("full", "fromCheckpoint") and not r.retention_seconds:
                errs.add(f"{path}.delivery.replay.retentionSeconds",
                         f"required for replay.mode={r.mode}")
            if r.mode in (None, "none") and (
                r.retention_seconds or r.checkpoint_interval
            ):
                errs.add(f"{path}.delivery.replay",
                         "retention/checkpoint only meaningful with replay enabled")
            if r.mode == "full" and r.checkpoint_interval:
                # inert knob: intervals pace CHECKPOINT persistence,
                # which only mode=fromCheckpoint performs
                errs.add(f"{path}.delivery.replay.checkpointInterval",
                         "only meaningful with replay.mode=fromCheckpoint")
        if (
            d.ordering == "total"
            and st.partitioning is not None
            and st.partitioning.mode in ("keyHash", "roundRobin")
        ):
            errs.add(f"{path}.delivery.ordering",
                     "ordering=total cannot be honored across partitions")
    p = st.partitioning
    if p is not None:
        if p.mode not in (None, *_VALID_PARTITION_MODES):
            errs.add(f"{path}.partitioning.mode",
                     f"must be one of {sorted(_VALID_PARTITION_MODES)}")
        if p.mode == "keyHash" and not p.key:
            errs.add(f"{path}.partitioning.key", "required for mode=keyHash")
        if p.partitions is not None and p.partitions < 1:
            errs.add(f"{path}.partitioning.partitions", "must be >= 1")
        if p.mode == "roundRobin" and p.sticky:
            errs.add(f"{path}.partitioning.sticky",
                     "sticky assignment contradicts roundRobin")
        if p.mode in (None, "none") and p.partitions is not None and p.partitions > 1:
            # partitions without a routing mode (absent OR an explicit
            # "none") would silently deliver on one stream
            errs.add(f"{path}.partitioning.mode",
                     "partitions > 1 requires mode=keyHash or roundRobin")
        # keyHash/roundRobin are ENFORCED since round 4: the client
        # splits the logical stream into N hub streams with a consumer-
        # side fan-in merge (dataplane/partition.py) — per-partition
        # ordering and key stickiness hold end to end
    ro = st.routing
    if ro is not None:
        if ro.mode not in (None, *_VALID_ROUTING_MODES):
            errs.add(f"{path}.routing.mode",
                     f"must be one of {sorted(_VALID_ROUTING_MODES)}")
        if ro.fan_out not in (None, *_VALID_FAN_OUT):
            errs.add(f"{path}.routing.fanOut",
                     f"must be one of {sorted(_VALID_FAN_OUT)}")
        if ro.max_downstreams is not None and ro.max_downstreams < 1:
            errs.add(f"{path}.routing.maxDownstreams", "must be >= 1")
        for i, rule in enumerate(ro.rules):
            if rule.action not in (None, *_VALID_RULE_ACTIONS):
                errs.add(f"{path}.routing.rules[{i}].action",
                         f"must be one of {sorted(_VALID_RULE_ACTIONS)}")
            if rule.action in ("route", "duplicate") and (
                rule.target is None or not rule.target.steps
            ):
                errs.add(f"{path}.routing.rules[{i}].target.steps",
                         f"required for action={rule.action}")
            if not rule.when:
                errs.add(f"{path}.routing.rules[{i}].when",
                         "routing rule requires a condition")
    fi = st.fan_in
    if fi is not None:
        if fi.mode not in (None, *_VALID_FAN_IN):
            errs.add(f"{path}.fanIn.mode",
                     f"must be one of {sorted(_VALID_FAN_IN)}")
        if fi.mode == "quorum" and not fi.quorum:
            errs.add(f"{path}.fanIn.quorum", "required for mode=quorum")
        if fi.quorum is not None and fi.quorum < 1:
            errs.add(f"{path}.fanIn.quorum", "must be >= 1")
        if fi.mode != "quorum" and fi.quorum:
            errs.add(f"{path}.fanIn.quorum",
                     "only meaningful with fanIn.mode=quorum")
        if fi.buffer is not None:
            _validate_buffer(fi.buffer, errs, f"{path}.fanIn.buffer")
    lc = st.lifecycle
    if lc is not None:
        if lc.strategy not in (None, *_VALID_LIFECYCLE):
            errs.add(f"{path}.lifecycle.strategy",
                     f"must be one of {sorted(_VALID_LIFECYCLE)}")
        if lc.drain_timeout_seconds is not None and lc.drain_timeout_seconds < 0:
            errs.add(f"{path}.lifecycle.drainTimeoutSeconds", "must be >= 0")
        if lc.strategy == "cutover" and lc.drain_timeout_seconds:
            errs.add(f"{path}.lifecycle.drainTimeoutSeconds",
                     "only meaningful with strategy=drain")
    rec = st.recording
    if rec is not None:
        if rec.mode not in (None, *_VALID_RECORDING):
            errs.add(f"{path}.recording.mode",
                     f"must be one of {sorted(_VALID_RECORDING)}")
        if rec.mode == "sample" and rec.sample_rate is None:
            errs.add(f"{path}.recording.sampleRate",
                     "mode=sample requires a sampleRate")
        elif rec.sample_rate is not None and not (0 < rec.sample_rate <= 100):
            errs.add(f"{path}.recording.sampleRate",
                     "must be in (0, 100]")
        if rec.mode == "full" and rec.sample_rate is not None:
            # legacy full means 100% by definition; a stray rate would
            # silently change a durable audit artifact's coverage
            errs.add(f"{path}.recording.sampleRate",
                     "mode=full records everything; use mode=payload "
                     "for orthogonal sampling")
        if rec.mode == "metadata" and rec.redact_fields:
            errs.add(f"{path}.recording.redactFields",
                     "metadata mode records no payload to redact")
        if rec.mode in (None, "none", "off") and (
            rec.sample_rate or rec.retention_seconds or rec.redact_fields
        ):
            errs.add(f"{path}.recording",
                     "recording knobs only meaningful with mode != none")
        # full/sample recording is ENFORCED since round 4: hubs carry a
        # StreamRecorder that tees (optionally sampled/redacted) data
        # frames into the blob store with retention
        # (dataplane/recording.py)
    ob = st.observability
    if ob is not None and ob.watermark is not None:
        # watermarks are ENFORCED since round 4: producers stamp event
        # time (client-side extraction per timestampSource), both hub
        # engines track min-over-producers and push watermark frames
        wm = ob.watermark
        if wm.timestamp_source is not None and not re.fullmatch(
            r"[A-Za-z_][A-Za-z0-9_]*(\.[A-Za-z_][A-Za-z0-9_]*)*",
            wm.timestamp_source,
        ):
            errs.add(f"{path}.observability.watermark.timestampSource",
                     "must be a dotted field path into the JSON payload "
                     "(e.g. metadata.event_time_ms)")
        if wm.timestamp_source and not wm.enabled:
            errs.add(f"{path}.observability.watermark.timestampSource",
                     "only meaningful with watermark.enabled")
    for i, lane in enumerate(st.lanes):
        for field in ("max_messages", "max_bytes"):
            v = getattr(lane, field)
            if v is not None and v < 1:
                camel = "maxMessages" if field == "max_messages" else "maxBytes"
                errs.add(f"{path}.lanes[{i}].{camel}", "must be >= 1")


def _validate_buffer(buf, errs: FieldErrors, path: str) -> None:
    if buf.drop_policy not in (None, *_VALID_DROP_POLICIES):
        errs.add(f"{path}.dropPolicy",
                 f"must be one of {sorted(_VALID_DROP_POLICIES)}")
    for field, camel in (
        ("max_messages", "maxMessages"),
        ("max_bytes", "maxBytes"),
        ("max_age_seconds", "maxAgeSeconds"),
    ):
        v = getattr(buf, field)
        if v is not None and v < 1:
            errs.add(f"{path}.{camel}", "must be >= 1")


class TransportWebhook:
    def __init__(self, store: ResourceStore):
        self.store = store

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(TRANSPORT_KIND, resource.meta.name)
        try:
            spec = parse_transport(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if not spec.provider:
            errs.add("spec.provider", "provider is required")
        if spec.driver not in _VALID_DRIVERS:
            errs.add("spec.driver", f"must be one of {sorted(_VALID_DRIVERS)}")
        if spec.driver == DRIVER_ICI and not spec.mesh_topology:
            errs.add("spec.meshTopology", "required for driver=ici")
        for i, codec in enumerate(spec.supported_audio):
            if not codec.name:
                errs.add(f"spec.supportedAudio[{i}].name", "codec name is required")
        for i, codec in enumerate(spec.supported_video):
            if not codec.name:
                errs.add(f"spec.supportedVideo[{i}].name", "codec name is required")

        st = spec.streaming
        if st is not None:
            validate_streaming_settings(st, errs, "spec.streaming")
            seen_lanes = set()
            for i, lane in enumerate(st.lanes):
                if not lane.name:
                    errs.add(f"spec.streaming.lanes[{i}].name", "lane name is required")
                elif lane.name in seen_lanes:
                    errs.add(f"spec.streaming.lanes[{i}].name", f"duplicate lane {lane.name!r}")
                seen_lanes.add(lane.name)

        errs.raise_if_any()


class TransportBindingWebhook:
    def __init__(self, store: ResourceStore):
        self.store = store

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(TRANSPORT_BINDING_KIND, resource.meta.name)
        try:
            spec = parse_transport_binding(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if not spec.transport_ref:
            errs.add("spec.transportRef", "transportRef is required")
        if spec.story_run_ref is None or not spec.story_run_ref.name:
            errs.add("spec.storyRunRef", "storyRunRef.name is required")
        if not spec.step_name:
            errs.add("spec.stepName", "stepName is required")
        if spec.driver not in _VALID_DRIVERS:
            errs.add("spec.driver", f"must be one of {sorted(_VALID_DRIVERS)}")
        for kind in ("audio", "video", "binary"):
            mb = getattr(spec, kind)
            if mb is not None and mb.direction not in (None, "send", "receive", "both"):
                errs.add(f"spec.{kind}.direction", "must be send|receive|both")
        # NOTE: spec.rawSettings is deliberately NOT coherence-validated
        # here — it is controller-written merge output (transport ->
        # story -> step), and a per-field deep merge of individually
        # coherent layers can be locally incoherent (e.g. a step
        # override mode=none retains upper-layer credit knobs). User
        # input is validated at its own admission point.

        errs.raise_if_any()
