"""Transport / TransportBinding admission (schema-only, no config dep —
reference: internal/webhook/transport/v1alpha1/transport_webhook.go:378,
validation via pkg/transport/validation).
"""

from __future__ import annotations

from typing import Optional

from ..api.transport import (
    DRIVER_GRPC,
    DRIVER_ICI,
    TRANSPORT_BINDING_KIND,
    TRANSPORT_KIND,
    parse_transport,
    parse_transport_binding,
)
from ..core.object import Resource
from ..core.store import ResourceStore
from .validation import FieldErrors

_VALID_DRIVERS = {DRIVER_GRPC, DRIVER_ICI, "webrtc"}
_VALID_DROP_POLICIES = {"dropOldest", "dropNewest", "block"}
_VALID_DELIVERY = {"atMostOnce", "atLeastOnce"}
_VALID_ORDERING = {"none", "perKey", "total"}
_VALID_ROUTING_MODES = {"auto", "hub", "p2p"}
_VALID_FAN_IN = {"merge", "zip", "quorum"}


class TransportWebhook:
    def __init__(self, store: ResourceStore):
        self.store = store

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(TRANSPORT_KIND, resource.meta.name)
        try:
            spec = parse_transport(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if not spec.provider:
            errs.add("spec.provider", "provider is required")
        if spec.driver not in _VALID_DRIVERS:
            errs.add("spec.driver", f"must be one of {sorted(_VALID_DRIVERS)}")
        if spec.driver == DRIVER_ICI and not spec.mesh_topology:
            errs.add("spec.meshTopology", "required for driver=ici")
        for i, codec in enumerate(spec.supported_audio):
            if not codec.name:
                errs.add(f"spec.supportedAudio[{i}].name", "codec name is required")
        for i, codec in enumerate(spec.supported_video):
            if not codec.name:
                errs.add(f"spec.supportedVideo[{i}].name", "codec name is required")

        st = spec.streaming
        if st is not None:
            if st.backpressure and st.backpressure.buffer:
                buf = st.backpressure.buffer
                if buf.drop_policy not in (None, *_VALID_DROP_POLICIES):
                    errs.add(
                        "spec.streaming.backpressure.buffer.dropPolicy",
                        f"must be one of {sorted(_VALID_DROP_POLICIES)}",
                    )
            if st.delivery:
                if st.delivery.semantics not in (None, *_VALID_DELIVERY):
                    errs.add(
                        "spec.streaming.delivery.semantics",
                        f"must be one of {sorted(_VALID_DELIVERY)}",
                    )
                if st.delivery.ordering not in (None, *_VALID_ORDERING):
                    errs.add(
                        "spec.streaming.delivery.ordering",
                        f"must be one of {sorted(_VALID_ORDERING)}",
                    )
            if st.routing:
                if st.routing.mode not in (None, *_VALID_ROUTING_MODES):
                    errs.add(
                        "spec.streaming.routing.mode",
                        f"must be one of {sorted(_VALID_ROUTING_MODES)}",
                    )
                if st.routing.max_downstreams is not None and st.routing.max_downstreams < 1:
                    errs.add("spec.streaming.routing.maxDownstreams", "must be >= 1")
            if st.fan_in:
                if st.fan_in.mode not in (None, *_VALID_FAN_IN):
                    errs.add(
                        "spec.streaming.fanIn.mode",
                        f"must be one of {sorted(_VALID_FAN_IN)}",
                    )
                if st.fan_in.mode == "quorum" and not st.fan_in.quorum:
                    errs.add("spec.streaming.fanIn.quorum", "required for mode=quorum")
            seen_lanes = set()
            for i, lane in enumerate(st.lanes):
                if not lane.name:
                    errs.add(f"spec.streaming.lanes[{i}].name", "lane name is required")
                elif lane.name in seen_lanes:
                    errs.add(f"spec.streaming.lanes[{i}].name", f"duplicate lane {lane.name!r}")
                seen_lanes.add(lane.name)

        errs.raise_if_any()


class TransportBindingWebhook:
    def __init__(self, store: ResourceStore):
        self.store = store

    def validate(self, resource: Resource, old: Optional[Resource]) -> None:
        errs = FieldErrors(TRANSPORT_BINDING_KIND, resource.meta.name)
        try:
            spec = parse_transport_binding(resource)
        except Exception as e:  # noqa: BLE001
            errs.add("spec", f"malformed: {e}")
            errs.raise_if_any()
            return

        if not spec.transport_ref:
            errs.add("spec.transportRef", "transportRef is required")
        if spec.story_run_ref is None or not spec.story_run_ref.name:
            errs.add("spec.storyRunRef", "storyRunRef.name is required")
        if not spec.step_name:
            errs.add("spec.stepName", "stepName is required")
        if spec.driver not in _VALID_DRIVERS:
            errs.add("spec.driver", f"must be one of {sorted(_VALID_DRIVERS)}")
        for kind in ("audio", "video", "binary"):
            mb = getattr(spec, kind)
            if mb is not None and mb.direction not in (None, "send", "receive", "both"):
                errs.add(f"spec.{kind}.direction", "must be send|receive|both")

        errs.raise_if_any()
