"""Admission layer: defaulters + validators for every resource kind.

The counterpart of the reference's 9 webhooks (SURVEY §2.3; registered
at cmd/main.go:832-911). Here they register as ResourceStore admission
hooks — the exact seam where the reference's webhooks sit between the
API server and storage. ``ENABLE_WEBHOOKS=false`` has the same no-op
escape hatch (reference: cmd/main.go:364-394) via ``enabled=False``.
"""

from __future__ import annotations

from ..api.catalog import ENGRAM_TEMPLATE_KIND, IMPULSE_TEMPLATE_KIND
from ..api.engram import KIND as ENGRAM_KIND
from ..api.impulse import KIND as IMPULSE_KIND
from ..api.runs import (
    EFFECT_CLAIM_KIND,
    STEP_RUN_KIND,
    STORY_RUN_KIND,
    STORY_TRIGGER_KIND,
)
from ..api.story import KIND as STORY_KIND
from ..api.transport import TRANSPORT_BINDING_KIND, TRANSPORT_KIND
from ..core.store import ResourceStore
from ..templating.engine import Evaluator
from .engram import EngramWebhook, ImpulseWebhook
from .runs import StepRunWebhook, StoryRunWebhook
from .story import StoryWebhook
from .template import EngramTemplateWebhook, ImpulseTemplateWebhook
from .trigger import EffectClaimWebhook, StoryTriggerWebhook
from .transport import TransportBindingWebhook, TransportWebhook

__all__ = [
    "register_webhooks",
    "StoryWebhook",
    "EngramWebhook",
    "ImpulseWebhook",
    "StoryRunWebhook",
    "StepRunWebhook",
    "StoryTriggerWebhook",
    "EffectClaimWebhook",
    "TransportWebhook",
    "TransportBindingWebhook",
    "EngramTemplateWebhook",
    "ImpulseTemplateWebhook",
]


def register_webhooks(
    store: ResourceStore,
    evaluator: Evaluator,
    config_manager=None,
    enabled: bool = True,
) -> None:
    """Wire every webhook into the store's admission chain
    (reference: setupWebhooksIfEnabled cmd/main.go:802-924; each
    config-dependent webhook holds the live config manager :796-800)."""
    if not enabled:
        return

    story = StoryWebhook(store, evaluator, config_manager)
    store.register_defaulter(STORY_KIND, story.default)
    store.register_validator(STORY_KIND, story.validate)

    engram = EngramWebhook(store, config_manager)
    store.register_defaulter(ENGRAM_KIND, engram.default)
    store.register_validator(ENGRAM_KIND, engram.validate)

    impulse = ImpulseWebhook(store, config_manager)
    store.register_validator(IMPULSE_KIND, impulse.validate)

    storyrun = StoryRunWebhook(store, config_manager)
    store.register_validator(STORY_RUN_KIND, storyrun.validate)
    store.register_status_validator(STORY_RUN_KIND, storyrun.validate_status)

    steprun = StepRunWebhook(store, config_manager)
    store.register_validator(STEP_RUN_KIND, steprun.validate)
    store.register_status_validator(STEP_RUN_KIND, steprun.validate_status)

    trigger = StoryTriggerWebhook(store, config_manager)
    store.register_validator(STORY_TRIGGER_KIND, trigger.validate)

    claim = EffectClaimWebhook(store, config_manager)
    store.register_validator(EFFECT_CLAIM_KIND, claim.validate)

    transport = TransportWebhook(store)
    store.register_validator(TRANSPORT_KIND, transport.validate)

    binding = TransportBindingWebhook(store)
    store.register_validator(TRANSPORT_BINDING_KIND, binding.validate)

    etpl = EngramTemplateWebhook(store)
    store.register_validator(ENGRAM_TEMPLATE_KIND, etpl.validate)

    itpl = ImpulseTemplateWebhook(store)
    store.register_validator(IMPULSE_TEMPLATE_KIND, itpl.validate)
