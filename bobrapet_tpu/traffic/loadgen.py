"""Closed-loop multi-tenant load generation with seeded arrivals.

"Millions of users" becomes a claim the repo simulates and measures:
each :class:`TenantProfile` models a population of synthetic users in
**closed loop** — a user submits, waits for its completion, thinks
(exponential think time = Poisson arrivals per user at steady state),
then submits again, so in-flight work per user is bounded by
construction and offered load backs off when the system saturates,
exactly like real interactive traffic (an open-loop generator would
just grow an unbounded queue and measure nothing but itself).

Arrivals are **deterministic given the seed**: every user owns a
``random.Random`` seeded from (seed, tenant, user index), so the
sequence of prompt lengths/contents, output budgets and think times
replays identically run to run. What the target does with them (the
interleaving) is the system under test.

:class:`TrafficPhase` shapes the mix over time: a ``rate`` multiplier
scales every user's arrival rate for the phase's duration (burst = big
multiplier, diurnal trough = fractional), and ``rate_end`` turns the
phase into a linear ramp. Phases advance on wall-clock; when the last
phase ends the generator stops submitting and drains.

The ``target`` is anything with the engine/router serve surface
(``submit``/``step``/``finished``) — a bare :class:`ServingEngine`, a
:class:`ServingRouter` fronting a pool, it does not matter.
``tick_hooks`` run once per drive-loop iteration (the autoscaler's
``tick`` rides here in the harness). The report carries per-tenant
achieved TTFT/TPOT percentiles, goodput and SLO breach counts — the
numbers the bench gates and the fairness test asserts on.
"""

from __future__ import annotations

import dataclasses
import random
import time as _walltime
from typing import Any, Callable, Optional, Sequence

from ..analysis.racedetect import guarded_state
from ..observability.metrics import metrics


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (None on empty input) — one definition
    shared by the report, the bench and the tests."""
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return vs[idx]


@dataclasses.dataclass
class TenantProfile:
    """One tenant's synthetic population + request-shape distributions."""

    tenant: str
    #: closed-loop concurrency: simultaneous in-flight requests <= users
    users: int = 1
    #: mean think time between a user's completion and its next submit
    #: (exponential draw; 0 = back-to-back)
    think_time_s: float = 0.0
    #: uniform [lo, hi] prompt length draw
    prompt_len: tuple[int, int] = (8, 24)
    #: uniform [lo, hi] new-token budget draw
    new_tokens: tuple[int, int] = (8, 16)
    temperature: float = 0.0
    #: token id universe for generated prompts
    vocab: int = 256
    #: tokens of tenant-shared system prompt prepended to every request
    #: (drawn once per tenant from the seed; exercises prefix caching)
    shared_prefix_len: int = 0
    #: total requests this tenant may submit (0 = unbounded; phases or
    #: the wall deadline terminate instead)
    max_requests: int = 0


@dataclasses.dataclass
class TrafficPhase:
    """A named window of arrival-rate modulation."""

    name: str
    duration_s: float
    #: arrival-rate multiplier (divides think time): 10 = burst, 0.1 =
    #: trough, 1 = the profile's base rate
    rate: float = 1.0
    #: when set, the multiplier ramps linearly rate -> rate_end across
    #: the phase (diurnal shoulders)
    rate_end: Optional[float] = None

    def multiplier(self, into_phase_s: float) -> float:
        if self.rate_end is None or self.duration_s <= 0:
            return self.rate
        frac = min(1.0, max(0.0, into_phase_s / self.duration_s))
        return self.rate + (self.rate_end - self.rate) * frac


@dataclasses.dataclass
class TrafficReport:
    """What the run achieved, per tenant and overall."""

    wall_s: float
    submitted: int
    completed: int
    #: rids submitted but never retired (MUST be 0 — the e2e test and
    #: the chaos soak assert on it)
    lost: int
    per_tenant: dict[str, dict[str, Any]]
    phase_log: list[dict[str, Any]]

    def tenant(self, name: str) -> dict[str, Any]:
        return self.per_tenant[name]


class _User:
    __slots__ = ("profile", "rng", "prefix", "inflight_rid", "next_at",
                 "submitted")

    def __init__(self, profile: TenantProfile, seed: int, idx: int,
                 prefix: list[int]):
        self.profile = profile
        self.rng = random.Random(f"{seed}:{profile.tenant}:{idx}")
        self.prefix = prefix
        self.inflight_rid: Optional[int] = None
        self.next_at = 0.0
        self.submitted = 0


@guarded_state("_inflight", "_users", "phases", "profiles", "tick_hooks")
class ClosedLoopLoadGen:
    """See module docstring."""

    def __init__(
        self,
        target: Any,
        profiles: Sequence[TenantProfile],
        phases: Optional[Sequence[TrafficPhase]] = None,
        seed: int = 0,
        tick_hooks: Sequence[Callable[[float], Any]] = (),
    ):
        if not profiles:
            raise ValueError("loadgen needs at least one TenantProfile")
        names = [p.tenant for p in profiles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant profiles: {sorted(names)}")
        self.target = target
        self.profiles = list(profiles)
        self.phases = list(phases or [])
        self.seed = int(seed)
        self.tick_hooks = list(tick_hooks)
        self._users: list[_User] = []
        for p in self.profiles:
            prefix_rng = random.Random(f"{seed}:{p.tenant}:prefix")
            prefix = [prefix_rng.randrange(p.vocab)
                      for _ in range(p.shared_prefix_len)]
            for i in range(p.users):
                self._users.append(_User(p, self.seed, i, prefix))
        #: rid -> submitting user, for completion attribution
        self._inflight: dict[int, _User] = {}

    # -- drive --------------------------------------------------------------

    def run(self, max_duration_s: float = 60.0,
            max_steps: int = 1_000_000) -> TrafficReport:
        """Drive the target until every phase has elapsed (or every
        bounded tenant exhausted its budget) and in-flight work
        drained; hard stops at ``max_duration_s`` wall seconds either
        way (the closed loop cannot hang on a wedged target — lost
        rids then show up in the report, loudly)."""
        t0 = _walltime.perf_counter()
        deadline = t0 + max_duration_s
        phase_total = sum(ph.duration_s for ph in self.phases)
        harvested = len(self.target.finished)
        results: dict[str, list[Any]] = {p.tenant: [] for p in self.profiles}
        phase_log: list[dict[str, Any]] = []
        last_phase = None
        submitted = 0
        steps = 0
        while steps < max_steps:
            now = _walltime.perf_counter()
            elapsed = now - t0
            if now >= deadline:
                break
            phase = self._phase_at(elapsed)
            if phase is not last_phase and phase is not None:
                phase_log.append({"phase": phase.name,
                                  "at_s": round(elapsed, 3)})
                last_phase = phase
            submitting = (
                phase is not None
                or (not self.phases and self._budget_left())
            )
            mult = phase.multiplier(
                elapsed - self._phase_start(phase)) if phase else 1.0
            if submitting:
                submitted += self._submit_ready(now)
            self.target.step()
            steps += 1
            harvested = self._harvest(harvested, results, now, mult)
            for hook in self.tick_hooks:
                hook(now)
            if not submitting and not self._inflight:
                break
            if (not self.phases and not self._budget_left()
                    and not self._inflight):
                break
            if self.phases and elapsed > phase_total and not self._inflight:
                break
        wall = _walltime.perf_counter() - t0
        completed = sum(len(v) for v in results.values())
        return TrafficReport(
            wall_s=wall,
            submitted=submitted,
            completed=completed,
            lost=len(self._inflight),
            per_tenant={
                t: self._stats(rs, wall) for t, rs in results.items()
            },
            phase_log=phase_log,
        )

    # -- internals ----------------------------------------------------------

    def _phase_at(self, elapsed: float) -> Optional[TrafficPhase]:
        acc = 0.0
        for ph in self.phases:
            if elapsed < acc + ph.duration_s:
                return ph
            acc += ph.duration_s
        return None

    def _phase_start(self, phase: TrafficPhase) -> float:
        acc = 0.0
        for ph in self.phases:
            if ph is phase:
                return acc
            acc += ph.duration_s
        return acc

    def _budget_left(self) -> bool:
        return any(
            u.profile.max_requests == 0
            or u.submitted < -(-u.profile.max_requests // u.profile.users)
            for u in self._users
        )

    def _submit_ready(self, now: float) -> int:
        n = 0
        for u in self._users:
            if u.inflight_rid is not None or now < u.next_at:
                continue
            p = u.profile
            if p.max_requests and u.submitted >= -(-p.max_requests // p.users):
                continue
            prompt = u.prefix + [
                u.rng.randrange(p.vocab)
                for _ in range(u.rng.randint(*p.prompt_len))
            ]
            rid = self.target.submit(
                prompt,
                max_new_tokens=u.rng.randint(*p.new_tokens),
                temperature=p.temperature,
                tenant=p.tenant,
            )
            u.inflight_rid = rid
            u.submitted += 1
            self._inflight[rid] = u
            metrics.traffic_loadgen_requests.inc(p.tenant)
            n += 1
        return n

    def _harvest(self, harvested: int, results: dict[str, list],
                 now: float, mult: float = 1.0) -> int:
        fin = self.target.finished
        while harvested < len(fin):
            req = fin[harvested]
            harvested += 1
            u = self._inflight.pop(req.rid, None)
            if u is None:
                continue  # not ours (shared target)
            results[u.profile.tenant].append(req)
            p = u.profile
            # the ACTIVE phase's rate multiplier scales this user's
            # arrival rate by dividing its think time: burst = near
            # back-to-back, trough = long idle gaps. Applied at draw
            # time, so a phase change reshapes arrivals within one
            # request of taking effect.
            think = (
                u.rng.expovariate(1.0 / p.think_time_s) / max(1e-9, mult)
                if p.think_time_s > 0 else 0.0
            )
            u.next_at = now + think
            u.inflight_rid = None
        return harvested

    @staticmethod
    def _stats(reqs: list[Any], wall: float) -> dict[str, Any]:
        ttfts = [r.ttft_seconds for r in reqs if r.ttft_seconds is not None]
        tpots = [r.tpot_seconds for r in reqs if r.tpot_seconds is not None]
        tokens = sum(len(r.output) for r in reqs)
        return {
            "completed": len(reqs),
            "tokens": tokens,
            "goodput_tok_s": round(tokens / wall, 2) if wall > 0 else 0.0,
            "ttft_p50_s": percentile(ttfts, 0.50),
            "ttft_p95_s": percentile(ttfts, 0.95),
            "tpot_p50_s": percentile(tpots, 0.50),
            "tpot_p95_s": percentile(tpots, 0.95),
            "preemptions": sum(r.preemptions for r in reqs),
        }
