"""SLO-driven serving autoscaler: close the loop the sensors built.

PR 8 built the sensor plane (per-tenant TTFT/TPOT/queue-wait histograms
and SLO burn counters vs live ``telemetry.slo.*`` thresholds) and PR 11
exposed per-pool router queue depth/wait as "the independent autoscaler
signals" — this module is the loop that was missing:

- **decide** (pure function): per-pool scale decision from
  :class:`PoolSignals` under an :class:`AutoscalePolicy` — prefill
  pools scale UP on queue-wait (their backlog is ingest-bound), decode
  pools on TPOT burn rate (their pain is cadence), both on raw queue
  depth per replica; scale DOWN only when the pool is calm below the
  *lower* hysteresis thresholds with an empty queue. Per-direction
  cooldowns and min/max replica clamps. One replica per decision —
  drains are slow, and a measured step beats an oscillating jump.
- :class:`EngineReplicaSet`: the actuator. Scale-up places a slice
  grant through the PR-5 placement fast path (``SlicePlacer.place``)
  and registers a factory-built engine with the router; scale-down
  picks the newest autoscaler-added replica and retires it through the
  router's explicit drain contract (stop routing -> in-flight
  retirement -> remove + release the grant — prefix/KV state re-adopts
  from the PR-10 SSD tier exactly as preemption resume does). A
  preempted replica is *evicted* (its unfinished requests requeue onto
  the router with their clocks carried) and its grant released — a
  drain in progress on that replica is cleared, never stranded.
- :class:`Autoscaler`: the loop. Gathers signals (router queues +
  windowed deltas of the live SLO burn counters), decides, acts,
  flight-records every decision and counts it into
  ``bobrapet_traffic_autoscale_total{pool,direction,reason}`` plus the
  desired/actual/draining replica gauges. ``/debug/traffic`` serves
  :func:`traffic_debug_payload` — every live autoscaler's status and
  recent decision ring.

Live tuning: the ``traffic.*`` operator keys retune live autoscalers
through :func:`apply_tuning` (wired from
``Runtime._apply_traffic_tuning`` on every config reload).

Threading: an autoscaler is single-threaded by the same contract as
the router it steers — the serve/bench loop calls ``tick()``; nothing
here spawns threads or takes locks.
"""

from __future__ import annotations

import dataclasses
import logging
import time as _walltime
import weakref
from collections import deque
from typing import Any, Callable, Optional

from ..analysis.racedetect import guarded_state
from ..observability.metrics import metrics
from ..observability.timeline import FLIGHT

_log = logging.getLogger(__name__)

#: flight-recorder identity autoscaler decisions land under when the
#: caller wires no run of its own (kept stable so /debug/runs/
#: bobrapet-system/traffic-autoscaler always shows the decision ring)
DEFAULT_FLIGHT = ("bobrapet-system", "traffic-autoscaler")

#: autoscalers this process is currently running — live-reload targets
#: for the ``traffic.*`` operator knobs (same pattern as the engine
#: weakset in serving/engram.py)
_LIVE_AUTOSCALERS: "weakref.WeakSet[Autoscaler]" = weakref.WeakSet()


def apply_tuning(tcfg: Any) -> None:
    """Apply the operator's ``traffic.*`` knobs to every live
    autoscaler (forwarded from ``Runtime._apply_traffic_tuning``)."""
    for scaler in list(_LIVE_AUTOSCALERS):
        try:
            scaler.apply_tuning(tcfg)
        except ValueError as e:
            _log.warning("traffic.* reload skipped an autoscaler: %s", e)


def traffic_debug_payload() -> dict[str, Any]:
    """The /debug/traffic response body: every live autoscaler's
    status + recent decisions."""
    return {"autoscalers": [s.status() for s in list(_LIVE_AUTOSCALERS)]}


# ---------------------------------------------------------------------------
# pure decision core
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PoolSignals:
    """One pool's observed state at decision time."""

    #: requests queued in the router ahead of engine admission
    queue_depth: int = 0
    #: p95 router-queue wait over the last window (seconds)
    queue_wait_p95_s: float = 0.0
    #: fraction of requests breaching the pool's SLO over the last
    #: window (prefill pools judge ttft, decode pools tpot); 0 when the
    #: window saw no completed observations
    burn_rate: float = 0.0
    #: serving replicas currently routable (draining excluded)
    replicas: int = 1
    #: replicas mid-drain (shrinking but still retiring work)
    draining: int = 0


@dataclasses.dataclass
class AutoscalePolicy:
    """Scale thresholds + clamps (the ``traffic.*`` operator keys).

    Hysteresis is the up/down threshold GAP: a pool between
    ``scale_down_burn`` and ``scale_up_burn`` (or between the two
    queue-wait bounds) holds — without the gap a pool hovering at one
    threshold would flap a replica up and down every window."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: decode pools scale up past this SLO burn fraction
    scale_up_burn: float = 0.30
    #: ...and down only below this one (must be < scale_up_burn)
    scale_down_burn: float = 0.05
    #: prefill pools scale up past this p95 router-queue wait
    scale_up_queue_wait_s: float = 0.50
    #: ...and down only below this one (must be < the up bound)
    scale_down_queue_wait_s: float = 0.05
    #: either pool scales up when its backlog exceeds this many queued
    #: requests per routable replica (depth is the leading indicator —
    #: burn only moves after requests already suffered)
    queue_depth_per_replica: int = 8
    scale_up_cooldown_s: float = 5.0
    scale_down_cooldown_s: float = 30.0

    def validate(self) -> list[str]:
        errs = []
        if self.min_replicas < 1:
            errs.append("traffic.min-replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            errs.append("traffic.max-replicas must be >= traffic.min-replicas")
        for name, v in (("traffic.scale-up-burn", self.scale_up_burn),
                        ("traffic.scale-down-burn", self.scale_down_burn)):
            if not (0.0 <= v <= 1.0):
                errs.append(f"{name} must be in [0, 1]")
        if self.scale_down_burn >= self.scale_up_burn:
            errs.append(
                "traffic.scale-down-burn must be < traffic.scale-up-burn "
                "(the gap IS the hysteresis)"
            )
        if self.scale_up_queue_wait_s <= 0:
            errs.append("traffic.scale-up-queue-wait must be > 0")
        if not (0 <= self.scale_down_queue_wait_s < self.scale_up_queue_wait_s):
            errs.append(
                "traffic.scale-down-queue-wait must be in "
                "[0, traffic.scale-up-queue-wait)"
            )
        if self.queue_depth_per_replica < 1:
            errs.append("traffic.queue-depth-per-replica must be >= 1")
        if self.scale_up_cooldown_s < 0 or self.scale_down_cooldown_s < 0:
            errs.append("traffic.*-cooldown must be >= 0")
        return errs

    @classmethod
    def from_config(cls, tcfg: Any) -> "AutoscalePolicy":
        """Policy from the operator's TrafficConfig dataclass."""
        return cls(
            min_replicas=int(tcfg.min_replicas),
            max_replicas=int(tcfg.max_replicas),
            scale_up_burn=float(tcfg.scale_up_burn),
            scale_down_burn=float(tcfg.scale_down_burn),
            scale_up_queue_wait_s=float(tcfg.scale_up_queue_wait_seconds),
            scale_down_queue_wait_s=float(tcfg.scale_down_queue_wait_seconds),
            queue_depth_per_replica=int(tcfg.queue_depth_per_replica),
            scale_up_cooldown_s=float(tcfg.scale_up_cooldown_seconds),
            scale_down_cooldown_s=float(tcfg.scale_down_cooldown_seconds),
        )


@dataclasses.dataclass(frozen=True)
class Decision:
    pool: str
    direction: str  # "up" | "down" | "hold"
    reason: str
    #: replica target the decision implies (= replicas for "hold")
    desired: int
    signals: PoolSignals

    @property
    def scaled(self) -> bool:
        return self.direction != "hold"


def decide(
    pool: str,
    sig: PoolSignals,
    policy: AutoscalePolicy,
    now: float,
    last_up_at: Optional[float] = None,
    last_down_at: Optional[float] = None,
) -> Decision:
    """The pure scale decision — no engines, no clocks of its own.

    ``pool`` picks the signal family ("prefill" scales on queue wait,
    anything else on burn rate — the PR-11 split: prefill pressure is
    arrival-shaped and shows up as queue wait long before burn, decode
    pressure is cadence-shaped and queue wait stays flat while TPOT
    burns). Queue depth per replica is a shared leading indicator.
    Cooldown windows apply per direction; a scale-up landing inside the
    *down* cooldown is allowed (load spikes must not wait out a
    scale-down's settle window), and vice versa."""

    def hold(reason: str) -> Decision:
        return Decision(pool, "hold", reason, sig.replicas, sig)

    prefill = pool == "prefill"
    hot_signal = (
        sig.queue_wait_p95_s > policy.scale_up_queue_wait_s
        if prefill
        else sig.burn_rate > policy.scale_up_burn
    )
    hot_reason = "queue-wait" if prefill else "tpot-burn"
    depth_hot = (
        sig.queue_depth > policy.queue_depth_per_replica * max(1, sig.replicas)
    )
    calm = (
        sig.queue_depth == 0
        and (
            sig.queue_wait_p95_s <= policy.scale_down_queue_wait_s
            if prefill
            else sig.burn_rate <= policy.scale_down_burn
        )
    )
    # total footprint includes draining replicas: their chips are still
    # held, so "room to grow" must count them or a slow drain plus a
    # burst double-books the max (the chaos soak's double-count trap)
    footprint = sig.replicas + sig.draining
    if hot_signal or depth_hot:
        reason = hot_reason if hot_signal else "queue-depth"
        if footprint >= policy.max_replicas:
            return hold(f"{reason} hot but at max-replicas")
        if last_up_at is not None and now - last_up_at < policy.scale_up_cooldown_s:
            return hold(f"{reason} hot but in scale-up cooldown")
        return Decision(pool, "up", reason, sig.replicas + 1, sig)
    if calm and sig.replicas > policy.min_replicas:
        if sig.draining > 0:
            # one drain at a time: a second victim before the first
            # finishes retiring turns "calm" into a self-inflicted
            # backlog (and makes capacity accounting ambiguous)
            return hold("calm but a drain is already in flight")
        if (
            last_down_at is not None
            and now - last_down_at < policy.scale_down_cooldown_s
        ):
            return hold("calm but in scale-down cooldown")
        if last_up_at is not None and now - last_up_at < policy.scale_down_cooldown_s:
            # a replica we JUST added must prove itself across a full
            # settle window before it can be judged idle
            return hold("calm but settling after a scale-up")
        return Decision(pool, "down", "calm", sig.replicas - 1, sig)
    return hold("within hysteresis band")


# ---------------------------------------------------------------------------
# signal gathering (windowed deltas over the live metrics plane)
# ---------------------------------------------------------------------------


class MetricsSignalReader:
    """Per-pool :class:`PoolSignals` from the router + windowed deltas
    of the PR-8/PR-11 sensor metrics.

    Burn rate = breach / (ok + breach) of ``bobrapet_serving_slo_total``
    (ttft for prefill pools, tpot for decode) since the previous read;
    queue-wait p95 comes from the bucket deltas of
    ``bobrapet_serving_pool_queue_wait_seconds``. Both windows are
    "since last tick" — the autoscaler's interval IS the window."""

    def __init__(self, router: Any):
        self.router = router
        self._last_slo: dict[tuple, float] = {}
        self._last_wait: dict[str, tuple] = {}
        # prime the baselines NOW: the first window must cover "since
        # the autoscaler started", not the process's whole metric
        # history (a long-lived engine's past breaches are not load)
        for slo in ("ttft", "tpot"):
            self._burn(slo)
        for pool in ("prefill", "decode"):
            self._wait_p95(pool)

    def read(self, pool: str, replicas: int, draining: int) -> PoolSignals:
        return PoolSignals(
            queue_depth=int(self.router.queue_depths().get(pool, 0)),
            queue_wait_p95_s=self._wait_p95(pool),
            burn_rate=self._burn("ttft" if pool == "prefill" else "tpot"),
            replicas=replicas,
            draining=draining,
        )

    def _burn(self, slo: str) -> float:
        ok = breach = 0.0
        for labels, value in metrics.serving_slo.snapshot().items():
            ld = dict(labels)
            if ld.get("slo") != slo:
                continue
            key = labels
            delta = value - self._last_slo.get(key, 0.0)
            self._last_slo[key] = value
            if ld.get("outcome") == "breach":
                breach += delta
            else:
                ok += delta
        total = ok + breach
        return (breach / total) if total > 0 else 0.0

    def _wait_p95(self, pool: str) -> float:
        bounds, counts, total = metrics.serving_pool_wait.bucket_snapshot(pool)
        prev_counts, prev_total = self._last_wait.get(
            pool, ([0] * len(counts), 0)
        )
        self._last_wait[pool] = (counts, total)
        window_total = total - prev_total
        if window_total <= 0:
            return 0.0
        target = 0.95 * window_total
        cum = 0
        for bound, c, pc in zip(bounds, counts, prev_counts):
            cum += c - pc
            if cum >= target:
                return float(bound)
        return float(bounds[-1]) if bounds else 0.0


# ---------------------------------------------------------------------------
# the actuator: replicas behind a router
# ---------------------------------------------------------------------------


class EngineReplicaSet:
    """Replica lifecycle for ONE pool behind a :class:`ServingRouter`.

    ``factory()`` builds a ready engine in the pool's role (the caller
    owns model/params/paging choices); ``placer``/``queue``/``tpu``
    optionally charge each replica a slice grant through the placement
    fast path — scale-up that loses the NoCapacity race simply reports
    failure and the autoscaler re-tries next window. Only replicas this
    set added are eligible drain victims (the operator's static engines
    are not the autoscaler's to retire)."""

    def __init__(
        self,
        pool: str,
        router: Any,
        factory: Callable[[], Any],
        placer: Any = None,
        queue: Optional[str] = None,
        tpu: Any = None,
        flight: tuple[str, str] = DEFAULT_FLIGHT,
    ):
        if pool not in ("prefill", "decode"):
            raise ValueError(f"pool must be prefill|decode, got {pool!r}")
        self.pool = pool
        self.router = router
        self.factory = factory
        self.placer = placer
        self.queue = queue
        self.tpu = tpu
        self.flight = flight
        self._counter = 0
        #: engine name -> slice grant dict (None when unplaced)
        self.grants: dict[str, Optional[dict]] = {}
        #: engine name -> drain start (monotonic)
        self._draining: dict[str, float] = {}
        #: most recent drained-out engines (newest last, bounded) — a
        #: factory may hand them back out as WARM spares instead of
        #: paying a fresh compile on the next scale-up
        self.retired: deque = deque(maxlen=4)

    # -- observation --------------------------------------------------------

    def _members(self) -> list[str]:
        roles = ("prefill",) if self.pool == "prefill" else ("decode", "unified")
        return [
            name
            for name, eng in self.router.engines.items()
            if eng.role in roles
        ]

    def actual(self) -> int:
        return sum(
            1 for n in self._members() if n not in self._draining
        )

    def draining(self) -> int:
        return len(self._draining)

    # -- scale-up (placement fast path) -------------------------------------

    def scale_up(self, now: float, reason: str) -> Optional[str]:
        """Place + build + register one replica; returns its name, or
        None when placement lost the capacity race."""
        grant = None
        if self.placer is not None and self.tpu is not None:
            from ..parallel.placement import NoCapacity

            try:
                placed = self.placer.place(self.tpu, queue=self.queue)
            except NoCapacity as e:
                self._record("scale-up blocked: no capacity",
                             outcome="no-capacity", reason=reason)
                _log.info("autoscale %s: placement blocked: %s", self.pool, e)
                return None
            grant = placed.to_dict() if placed is not None else None
        self._counter += 1
        name = f"{self.pool}-as{self._counter}"
        try:
            engine = self.factory()
        except BaseException:
            # the grant belongs to nobody — hand it back or the pool
            # leaks chips on every failed engine build
            if grant is not None and self.placer is not None:
                self.placer.release(grant)
            raise
        self.router.add_engine(name, engine)
        self.grants[name] = grant
        self._record(
            f"replica {name} up"
            + (f" on slice {grant.get('sliceId')}" if grant else ""),
            outcome="up", engine=name, reason=reason,
        )
        return name

    # -- scale-down (drain contract) ----------------------------------------

    def begin_drain(self, now: float, reason: str) -> Optional[str]:
        """Pick the newest autoscaler-added routable replica and stop
        routing to it; returns its name (None when no eligible
        victim)."""
        eligible = [
            n for n in self._members()
            if n in self.grants and n not in self._draining
        ]
        if not eligible:
            return None
        victim = eligible[-1]  # newest first: oldest replicas are the
        # warmed baseline the operator sized deliberately
        self.router.drain(victim)
        self._draining[victim] = now
        self._record(f"replica {victim} draining", outcome="drain-begin",
                     engine=victim, reason=reason)
        return victim

    def poll_drains(self, now: float) -> list[str]:
        """Retire every drain that reached empty: remove from the
        router, release the grant. Returns the names retired."""
        done = []
        for name in list(self._draining):
            status = self.router.drain_status(name)
            if status is None or status.empty:
                started = self._draining.pop(name)
                if status is not None:
                    self.retired.append(self.router.remove_engine(name))
                self._release(name)
                metrics.traffic_drain_seconds.observe(
                    max(0.0, now - started), self.pool
                )
                self._record(f"replica {name} drained + released",
                             outcome="down", engine=name)
                done.append(name)
        return done

    # -- preemption (chaos) -------------------------------------------------

    def preempt(self, name: str) -> int:
        """A replica's slice was reclaimed: evict it (unfinished
        requests requeue onto the router, clocks carried), release the
        grant, and clear any drain in progress on it — the drain is
        finished by force, never stranded. Returns requeued count."""
        requeued = self.router.evict_engine(name)
        self._draining.pop(name, None)
        self._release(name)
        metrics.traffic_evictions.inc(self.pool)
        self._record(
            f"replica {name} preempted: {requeued} request(s) requeued",
            outcome="preempted", engine=name, requeued=requeued,
        )
        return requeued

    # -- internals ----------------------------------------------------------

    def _release(self, name: str) -> None:
        grant = self.grants.pop(name, None)
        if grant is not None and self.placer is not None:
            self.placer.release(grant)

    def _record(self, message: str, **attrs: Any) -> None:
        ns, run = self.flight
        FLIGHT.record(ns, run, "autoscale", message=message,
                      pool=self.pool, **attrs)


# ---------------------------------------------------------------------------
# the loop
# ---------------------------------------------------------------------------


@guarded_state("_last_down", "_last_up", "decisions", "pools")
class Autoscaler:
    """Tick-driven control loop over one router's replica sets.

    ``pools`` maps pool name -> :class:`EngineReplicaSet`. ``tick()``
    is cheap enough to call from the serve loop every iteration — it
    self-gates on ``interval`` seconds between decision passes (drains
    in flight are polled every call so retirement is prompt)."""

    def __init__(
        self,
        pools: dict[str, EngineReplicaSet],
        policy: Optional[AutoscalePolicy] = None,
        signals: Optional[Any] = None,
        interval_s: float = 1.0,
        enabled: bool = True,
        flight: tuple[str, str] = DEFAULT_FLIGHT,
    ):
        if not pools:
            raise ValueError("Autoscaler needs at least one replica set")
        self.pools = dict(pools)
        self.policy = policy or AutoscalePolicy()
        errs = self.policy.validate()
        if errs:
            raise ValueError("; ".join(errs))
        if signals is None:
            routers = {id(rs.router): rs.router for rs in self.pools.values()}
            if len(routers) > 1:
                # the default reader polls ONE router's queue depths —
                # silently reading router A for a pool behind router B
                # would hold that pool forever; multi-router setups
                # must bring their own signal source
                raise ValueError(
                    "replica sets span multiple routers: pass an "
                    "explicit `signals` reader (the default "
                    "MetricsSignalReader reads one router's queues)"
                )
            signals = MetricsSignalReader(next(iter(routers.values())))
        self.signals = signals
        self.interval_s = float(interval_s)
        self.enabled = bool(enabled)
        self.flight = flight
        self._last_pass: Optional[float] = None
        self._last_up: dict[str, float] = {}
        self._last_down: dict[str, float] = {}
        self.decisions: deque = deque(maxlen=64)
        _LIVE_AUTOSCALERS.add(self)

    # -- live tuning --------------------------------------------------------

    def apply_tuning(self, tcfg: Any) -> None:
        """Live ``traffic.*`` reload: swap the policy (validated — an
        invalid combination keeps the prior policy), interval and the
        enabled flag."""
        policy = AutoscalePolicy.from_config(tcfg)
        errs = policy.validate()
        if errs:
            raise ValueError("; ".join(errs))
        self.policy = policy
        self.interval_s = float(tcfg.autoscale_interval_seconds)
        self.enabled = bool(tcfg.autoscale_enabled)

    # -- the loop body ------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> list[Decision]:
        now = _walltime.monotonic() if now is None else now
        for rs in self.pools.values():
            rs.poll_drains(now)
        self._set_gauges(desired=None)
        if not self.enabled:
            return []
        if self._last_pass is not None and now - self._last_pass < self.interval_s:
            return []
        self._last_pass = now
        out: list[Decision] = []
        for pool, rs in self.pools.items():
            sig = self.signals.read(pool, rs.actual(), rs.draining())
            d = decide(pool, sig, self.policy, now,
                       self._last_up.get(pool), self._last_down.get(pool))
            acted = False
            if d.direction == "up":
                acted = rs.scale_up(now, d.reason) is not None
                if acted:
                    self._last_up[pool] = now
                else:
                    d = Decision(pool, "hold",
                                 f"{d.reason} hot but placement blocked",
                                 sig.replicas, sig)
            elif d.direction == "down":
                acted = rs.begin_drain(now, d.reason) is not None
                if acted:
                    self._last_down[pool] = now
                else:
                    d = Decision(pool, "hold",
                                 f"{d.reason} but no drainable replica",
                                 sig.replicas, sig)
            if d.scaled and acted:
                metrics.traffic_autoscale.inc(pool, d.direction, d.reason)
                ns, run = self.flight
                FLIGHT.record(
                    ns, run, "autoscale",
                    message=f"{pool}: scale {d.direction} ({d.reason}) "
                            f"-> {d.desired} replicas",
                    pool=pool, direction=d.direction, reason=d.reason,
                    desired=d.desired, queueDepth=sig.queue_depth,
                    burnRate=round(sig.burn_rate, 4),
                    queueWaitP95=round(sig.queue_wait_p95_s, 4),
                )
            # consecutive identical holds collapse into one ring entry
            # (a long idle window must not wash the actual scale
            # decisions out of the bounded ring)
            last = next(
                (e for e in reversed(self.decisions) if e["pool"] == pool),
                None,
            )
            if (d.direction != "hold" or last is None
                    or (last["direction"], last["reason"])
                    != (d.direction, d.reason)):
                self.decisions.append({
                    "at": now, "pool": pool, "direction": d.direction,
                    "reason": d.reason, "desired": d.desired,
                    "queueDepth": sig.queue_depth,
                    "burnRate": round(sig.burn_rate, 4),
                    "queueWaitP95": round(sig.queue_wait_p95_s, 4),
                })
            self._set_gauges(desired=(pool, d.desired))
            out.append(d)
        return out

    def _set_gauges(self, desired: Optional[tuple[str, int]]) -> None:
        for pool, rs in self.pools.items():
            metrics.traffic_replicas.set(float(rs.actual()), pool, "actual")
            metrics.traffic_replicas.set(float(rs.draining()), pool, "draining")
            if desired is not None and desired[0] == pool:
                metrics.traffic_replicas.set(float(desired[1]), pool, "desired")

    # -- introspection ------------------------------------------------------

    def status(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "intervalSeconds": self.interval_s,
            "policy": dataclasses.asdict(self.policy),
            "pools": {
                pool: {
                    "actual": rs.actual(),
                    "draining": rs.draining(),
                    "members": sorted(rs._members()),
                    "grants": {
                        n: (g or {}).get("sliceId")
                        for n, g in rs.grants.items()
                    },
                }
                for pool, rs in self.pools.items()
            },
            "decisions": list(self.decisions),
        }
