"""Production traffic harness (ROADMAP 3): closed-loop load generation,
SLO-driven autoscaling, weighted-fair tenant admission.

Three cooperating pieces, wired through the existing config / metrics /
flight-recorder planes:

- :mod:`.fairness` — a weighted deficit round-robin queue the serving
  engine and router swap in for their FIFO pending queues when the
  operator configures ``serving.tenant-weights``: one tenant's burst
  can no longer starve another tenant's TTFT (starvation is impossible
  by construction — every backlogged tenant accrues deficit every
  round).
- :mod:`.autoscaler` — a control loop scaling serving replicas per pool
  off SLO burn rate + router queue signals (prefill pools scale on
  queue wait, decode pools on TPOT burn — the PR-11 signal split),
  with hysteresis and per-direction cooldowns; scale-up goes through
  the placement fast path, scale-down through the router's explicit
  ``drain()`` contract.
- :mod:`.loadgen` — a deterministic seeded closed-loop load generator
  replaying bursty/diurnal multi-tenant mixes against engines/routers,
  recording per-tenant achieved TTFT/TPOT/goodput.

The package is deliberately jax-free at import time: the autoscaler and
load generator drive whatever engine/router objects the caller built,
so a pure control-plane process can import (and live-retune) them
without pulling in the serving stack.
"""

from .autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalePolicy,
    Decision,
    EngineReplicaSet,
    PoolSignals,
    decide,
    traffic_debug_payload,
)
from .fairness import WeightedFairQueue, parse_tenant_weights  # noqa: F401
from .loadgen import (  # noqa: F401
    ClosedLoopLoadGen,
    TenantProfile,
    TrafficPhase,
    TrafficReport,
)
