"""Weighted-fair tenant admission: a start-time fair queuing scheduler.

The failure this closes (ROADMAP 3): the serving engine and router
admit from plain FIFO deques, so a tenant that floods 10x fills the
queue and every other tenant's TTFT inherits the flood's full backlog.
The PR-8 per-tenant SLO histograms make that failure *visible*; this
queue makes it *impossible*:

:class:`WeightedFairQueue` keeps one FIFO per tenant and selects the
next admission by **start-time fair queuing** (SFQ, Goyal et al.): each
pop stamps its tenant a virtual *finish* tag advanced by
``cost / weight`` (cost = prompt tokens + new-token budget, so a
long-prompt flood cannot buy extra turns by sending fewer, bigger
requests), and the backlogged tenant with the smallest tag is served
next. A tenant's tag only advances when it is actually served, so a
victim tenant's next request is always within one request of the head
of service no matter how deep any other tenant's backlog is —
starvation is impossible by construction, service is weight-
proportional in the long run, and (unlike deficit round-robin) the
interleaving is per-request, not per-quantum: exactly what TTFT
fairness needs. The virtual clock rides the served tenant's start tag,
and an idle tenant re-entering is clamped to it — idle time banks no
credit.

With **no weights configured** the queue degrades to exact global FIFO
(arrival order across tenants) — byte-compatible with the deque it
replaces, which is what lets the engine/router swap implementations on
a live ``serving.tenant-weights`` reload without disturbing queued
work. Unlisted tenants weigh ``1.0``; the ``*`` key overrides that
default.

The class is deque-compatible for every operation the engine and
router actually perform on their pending queues (``append``,
``appendleft``, ``popleft``, ``extend``, ``clear``, ``len``, truth,
iteration in arrival order, and ``[0]`` peeking the CURRENT head —
stable until a pop/appendleft changes it, which the engine's
head-of-line admission loop relies on).
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Callable, Iterable, Optional

from ..analysis.racedetect import guarded_state


def parse_tenant_weights(raw: str) -> dict[str, float]:
    """Parse the ``serving.tenant-weights`` operator value:
    ``"alice:4,bob:1"`` -> ``{"alice": 4.0, "bob": 1.0}``. Empty string
    = no weights (FIFO). Raises ``ValueError`` on malformed entries or
    non-positive weights — the config layer validates with this exact
    function, so an invalid ConfigMap never half-applies."""
    out: dict[str, float] = {}
    if not raw or not str(raw).strip():
        return out
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        tenant, sep, w = part.rpartition(":")
        if not sep or not tenant.strip():
            raise ValueError(
                f"tenant-weights entry {part!r} is not <tenant>:<weight>"
            )
        weight = float(w)
        if weight <= 0:
            raise ValueError(
                f"tenant-weights entry {part!r}: weight must be > 0"
            )
        out[tenant.strip()] = weight
    return out


def _default_cost(item: Any) -> float:
    """Admission cost of a queued request: prompt tokens + new-token
    budget (works for both the engine's ``Request`` and the router's
    ``_Queued``; anything else costs 1)."""
    prompt = getattr(item, "prompt", None)
    if prompt is None:
        return 1.0
    return max(
        1.0,
        float(len(prompt) + int(getattr(item, "max_new_tokens", 0) or 0)),
    )


@guarded_state("_queues", "_vfinish", "_weights")
class WeightedFairQueue:
    """See module docstring. Single-threaded by the same contract as
    the engine/router that owns it."""

    def __init__(
        self,
        weights: Optional[dict[str, float]] = None,
        cost: Optional[Callable[[Any], float]] = None,
        items: Iterable[Any] = (),
    ):
        self._weights = dict(weights or {})
        self._default_weight = float(self._weights.pop("*", 1.0))
        self._cost = cost or _default_cost
        #: tenant -> deque[(seq, item)] — seq is the global arrival
        #: stamp that makes no-weights mode exact FIFO
        self._queues: dict[str, deque] = {}
        #: tenant -> virtual finish tag of its last served request
        self._vfinish: dict[str, float] = {}
        #: virtual clock = start tag of the request last served
        self._vclock = 0.0
        self._seq = itertools.count()
        self._len = 0
        #: cached head tenant — stable across repeated [0] peeks while
        #: the engine retries a stalled head-of-line admission
        self._head_tenant: Optional[str] = None
        self.extend(items)

    # -- deque-compatible surface ------------------------------------------

    def append(self, item: Any) -> None:
        self._push(item, front=False)

    def appendleft(self, item: Any) -> None:
        """Requeue to the FRONT of the item's tenant queue and make it
        the head choice: the engine's preemption/chunked-prefill paths
        appendleft a request and expect the very next ``[0]``/
        ``popleft`` to see it again."""
        self._push(item, front=True)
        self._head_tenant = self._tenant(item)

    def extend(self, items: Iterable[Any]) -> None:
        for item in items:
            self._push(item, front=False)

    def clear(self) -> None:
        self._queues.clear()
        self._vfinish.clear()
        self._vclock = 0.0
        self._len = 0
        self._head_tenant = None

    def popleft(self) -> Any:
        if not self._len:
            raise IndexError("pop from an empty WeightedFairQueue")
        tenant = self._select()
        q = self._queues[tenant]
        _seq, item = q.popleft()
        self._len -= 1
        # SFQ tag update: start = max(vclock, tenant's last finish);
        # finish = start + cost/weight; the clock rides the start tag
        start = max(self._vclock, self._vfinish.get(tenant, 0.0))
        self._vfinish[tenant] = start + self._cost(item) / self._weight(tenant)
        self._vclock = start
        if not q:
            del self._queues[tenant]
            if len(self._vfinish) > 4096:
                # idle-tenant tags at/below the clock carry no state
                # (re-entry clamps to the clock anyway) — prune so a
                # churn of one-shot tenants cannot grow this forever
                self._vfinish = {
                    t: v for t, v in self._vfinish.items()
                    if t in self._queues or v > self._vclock
                }
        self._head_tenant = None
        return item

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def __iter__(self):
        """Arrival order across tenants (what ``pending`` displays and
        drain bookkeeping iterate; NOT the service order)."""
        merged = sorted(
            (entry for q in self._queues.values() for entry in q),
            key=lambda e: e[0],
        )
        return (item for _seq, item in merged)

    def __getitem__(self, idx: int) -> Any:
        if idx == 0:
            if not self._len:
                raise IndexError("empty WeightedFairQueue")
            return self._queues[self._select()][0][1]
        return list(self)[idx]

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Per-tenant backlog + virtual tags (the /debug/traffic
        payload and the fairness tests read this)."""
        return {
            "tenants": {
                t: {
                    "queued": len(q),
                    "vfinish": round(self._vfinish.get(t, 0.0), 3),
                    "weight": self._weight(t),
                }
                for t, q in self._queues.items()
            },
            "vclock": round(self._vclock, 3),
            "fair": self._fair,
        }

    # -- internals ----------------------------------------------------------

    @property
    def _fair(self) -> bool:
        return bool(self._weights) or self._default_weight != 1.0

    @staticmethod
    def _tenant(item: Any) -> str:
        return str(getattr(item, "tenant", "") or "")

    def _weight(self, tenant: str) -> float:
        return float(self._weights.get(tenant, self._default_weight))

    def _push(self, item: Any, front: bool) -> None:
        tenant = self._tenant(item)
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            # an idle tenant re-enters AT the virtual clock: its stale
            # (lower) tag would otherwise bank idle time as burst credit
            self._vfinish[tenant] = max(
                self._vfinish.get(tenant, 0.0), self._vclock
            )
        if front:
            # re-queued work keeps (a fresh low) arrival precedence:
            # negative stamps sort ahead of everything that arrived
            # after the original admission attempt
            seq = (q[0][0] - 1) if q else -next(self._seq) - 1
            q.appendleft((seq, item))
        else:
            q.append((next(self._seq), item))
        self._len += 1

    def _select(self) -> str:
        """Tenant whose head is served next (cached until a pop or an
        appendleft invalidates it).

        FIFO mode (no weights configured): globally oldest arrival.
        Fair mode: smallest start tag ``max(vclock, vfinish[t])``, ties
        broken by oldest head arrival — a backlogged tenant's tag only
        moves when it is served, so every backlogged tenant reaches the
        minimum within one request of each other tenant (bounded wait,
        no starvation, no quantum batching)."""
        if self._head_tenant is not None and self._head_tenant in self._queues:
            return self._head_tenant
        if not self._fair:
            tenant = min(self._queues, key=lambda t: self._queues[t][0][0])
        else:
            tenant = min(
                self._queues,
                key=lambda t: (
                    max(self._vclock, self._vfinish.get(t, 0.0)),
                    self._queues[t][0][0],
                ),
            )
        self._head_tenant = tenant
        return tenant
