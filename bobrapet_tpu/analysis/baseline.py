"""Baseline / suppression file for bobralint findings.

The first full-repo run of a new checker surfaces a backlog; the
baseline freezes the AUDITED part of that backlog so CI fails on any
*new* violation while the frozen entries are paid down over time. Every
entry carries a mandatory, human-written justification — an empty or
placeholder justification fails the load, so "suppress and forget"
cannot merge.

Format (checked in at the repo root as ``bobralint-baseline.json``)::

    {
      "version": 1,
      "suppressions": [
        {
          "fingerprint": "0f3a9c21be77",
          "checker": "lock-blocking-io",
          "path": "bobrapet_tpu/core/store.py",
          "scope": "ResourceStore._update",
          "message": "...as reported...",
          "justification": "why this one is intentional"
        }
      ]
    }

Fingerprints are line-number-free (see core.Finding), so entries
survive unrelated edits; an entry whose code is actually fixed becomes
*stale* and is reported so the file shrinks instead of rotting.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, Sequence

from .core import Finding

BASELINE_NAME = "bobralint-baseline.json"

#: justifications that mean "nobody looked" — rejected at load time
_PLACEHOLDERS = {"", "todo", "tbd", "fixme", "temporary", "suppress"}


class BaselineError(Exception):
    pass


@dataclasses.dataclass
class Suppression:
    fingerprint: str
    checker: str
    path: str
    scope: str
    message: str
    justification: str


@dataclasses.dataclass
class Baseline:
    suppressions: list[Suppression] = dataclasses.field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            raise BaselineError(f"cannot read baseline {path}: {e}") from e
        if data.get("version") != 1:
            raise BaselineError(f"{path}: unsupported baseline version {data.get('version')!r}")
        out = cls()
        seen: set[str] = set()
        for i, raw in enumerate(data.get("suppressions") or []):
            fp = str(raw.get("fingerprint") or "")
            just = str(raw.get("justification") or "").strip()
            if not fp:
                raise BaselineError(f"{path}: suppression #{i} missing fingerprint")
            if just.lower() in _PLACEHOLDERS or len(just) < 10:
                raise BaselineError(
                    f"{path}: suppression {fp} ({raw.get('checker')}, "
                    f"{raw.get('path')}) needs a real justification — got "
                    f"{just!r}. Explain WHY the finding is intentional."
                )
            if fp in seen:
                raise BaselineError(f"{path}: duplicate suppression {fp}")
            seen.add(fp)
            out.suppressions.append(
                Suppression(
                    fingerprint=fp,
                    checker=str(raw.get("checker") or ""),
                    path=str(raw.get("path") or ""),
                    scope=str(raw.get("scope") or ""),
                    message=str(raw.get("message") or ""),
                    justification=just,
                )
            )
        return out

    def fingerprints(self) -> set[str]:
        return {s.fingerprint for s in self.suppressions}

    def partition(
        self, findings: Sequence[Finding]
    ) -> tuple[list[Finding], list[Finding], list[Suppression]]:
        """-> (new findings, suppressed findings, stale suppressions)."""
        known = self.fingerprints()
        new = [f for f in findings if f.fingerprint not in known]
        suppressed = [f for f in findings if f.fingerprint in known]
        live = {f.fingerprint for f in findings}
        stale = [s for s in self.suppressions if s.fingerprint not in live]
        return new, suppressed, stale

    @staticmethod
    def render(findings: Iterable[Finding], justification: str) -> str:
        """Serialize findings as a baseline document (--write-baseline).
        The justification is deliberately a placeholder the LOADER
        rejects: each entry must be hand-audited before CI passes.
        Findings sharing a fingerprint (same invariant broken the same
        way in one scope) collapse to one entry."""
        entries: dict[str, dict] = {}
        for f in findings:
            entries.setdefault(
                f.fingerprint,
                {
                    "fingerprint": f.fingerprint,
                    "checker": f.checker,
                    "path": f.path,
                    "scope": f.scope,
                    "message": f.message,
                    "justification": justification,
                },
            )
        doc = {"version": 1, "suppressions": list(entries.values())}
        return json.dumps(doc, indent=2, sort_keys=False) + "\n"
