"""bobralint: repo-native static analysis + runtime concurrency sanitizer.

The reference operator leans on ``go vet``, controller-runtime's linters
and the race detector to keep its concurrency invariants honest; this
package is the Python port's equivalent, specialized to the invariants
THIS codebase already relies on (rather than generic style rules):

- **lock-blocking-io** — no store traffic / sleeps / filesystem /
  network calls inside ``with <lock>:`` blocks (the advisor's recorder
  finding, generalized across every lock-held region);
- **cow-discipline** — objects obtained from ``get_view`` /
  ``list_views`` / ``cached_parse`` / watch events are shared
  copy-on-write views and must never be mutated in place (the PR 1
  contract);
- **config-key-drift** — dotted config-key literals must be registered
  in ``config/operator.py``, registered keys must set real dataclass
  fields and be consumed somewhere, and keys documented in docs/ must
  exist;
- **metrics-drift** — emitted metric families must be registered in
  ``observability/metrics.py`` and carry the ``bobrapet_*`` /
  ``bobravoz_*`` prefix;
- **enum-literal-drift** — bare string literals that shadow
  phase/exit-class/decision vocabulary must come from ``api/enums.py``;
- **shared-state-discipline** — container fields declared by
  ``@guarded_state`` are only mutated under ``with self._lock:`` (or
  from methods proven lock-context-only by a least fixed point over
  the in-class call graph), and the declarations match the mutated
  fields both ways. Its discovery pass IS the runtime race
  sanitizer's instrumentation registry.

Static findings are gated by a checked-in baseline
(``bobralint-baseline.json``) whose every entry carries a mandatory
justification — CI fails on any NEW violation, never on the audited
backlog. Run ``python -m bobrapet_tpu.analysis`` or ``make analyze``.

The runtime prong has two sanitizers armed during the
concurrency/chaos suites: :mod:`.lockorder` instruments
``threading.Lock`` / ``RLock``, records the lock-acquisition-order
graph and fails on acquisition-order cycles (potential deadlocks);
:mod:`.racedetect` ("bobrarace") swaps the ``@guarded_state`` container
fields for tracked wrappers and fails on conflicting access pairs
unordered by happens-before with no common lock — hybrid
lockset/vector-clock detection with seeded deterministic replay
(:mod:`.schedules`), gated by ``bobrarace-baseline.json``. Run
``make race``.

Everything here is stdlib-only so the analyzer runs in the lint CI job
without the compute-plane dependencies installed.
"""

from .baseline import Baseline, BaselineError
from .core import Finding, ProjectFile, load_project, run_checkers

__all__ = [
    "Baseline",
    "BaselineError",
    "Finding",
    "ProjectFile",
    "load_project",
    "run_checkers",
]
