"""Seeded replay schedules for the data-race sanitizer.

A race the churn soak catches 1-run-in-10 is useless for debugging
until it reproduces on demand. These schedules turn every tracked
access (racedetect's instrumentation sites) into a *seeded* decision
point, at two strength levels:

- :class:`JitterSchedule` — seeded perturbation: at each site, a
  shared seeded RNG decides pass / GIL-yield / microsleep. Safe under
  arbitrary blocking (threads never wait on the schedule), so it wraps
  real workloads — the shard churn soak arms it and prints the seed on
  failure. The DECISION SEQUENCE is exactly reproducible from the
  seed; which thread consumes which decision still depends on arrival
  order, so this is statistical reproducibility: same seed, same
  perturbation shape, dramatically better repro odds than bare timing.
- :class:`SerialSchedule` — strict cooperative serialization for
  self-contained repro cases (the known-bad corpus in
  tests/test_racedetect.py): participant threads are registered up
  front, every participant blocks at each instrumented access until
  ALL live participants are blocked, then the seeded RNG picks who
  runs one step. The resulting ``trace`` (thread, site) sequence is
  bit-identical across runs with the same seed, independent of OS
  scheduling — deterministic replay, with the caveat that participant
  bodies must not block on each other outside instrumented state (a
  token holder stuck on an application lock would stall the round;
  stalls time out, are counted in ``stalls``, and degrade to free
  running rather than deadlocking).

Both schedules synchronize internally with raw ``_thread.allocate_lock``
primitives and busy gates: their own machinery must be invisible to the
detector (no patched-lock lockset noise) and, critically, must create
NO happens-before edges between the threads being scheduled — a
serializer built on ``threading.Condition`` would order every access
pair it interleaves and the sanitizer would see nothing but clean
handoffs.
"""

from __future__ import annotations

import _thread
import random
import threading
import time
from typing import Callable, Optional


class JitterSchedule:
    """Seeded perturbation at instrumentation sites.

    ``p_sleep``/``p_yield`` partition the unit interval: a draw below
    ``p_sleep`` sleeps ``sleep_s`` (forces a real reschedule), below
    ``p_sleep + p_yield`` sleeps 0 (drops the GIL), else passes
    through. Defaults keep the soak within ~1.3x wall-clock."""

    def __init__(self, seed: int, *, p_sleep: float = 0.02,
                 p_yield: float = 0.08, sleep_s: float = 0.0005):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._mu = _thread.allocate_lock()
        self.p_sleep = p_sleep
        self.p_yield = p_yield
        self.sleep_s = sleep_s
        self.decisions = 0

    def on_access(self, site: str) -> None:
        with self._mu:
            draw = self._rng.random()
            self.decisions += 1
        if draw < self.p_sleep:
            time.sleep(self.sleep_s)
        elif draw < self.p_sleep + self.p_yield:
            time.sleep(0)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"JitterSchedule(seed={self.seed}, decisions={self.decisions})"


class SerialSchedule:
    """Deterministic round-based cooperative scheduler.

    Usage::

        sched = SerialSchedule(seed=7)
        t1 = sched.spawn(writer, name="w")
        t2 = sched.spawn(reader, name="r")
        with sanitize_races(schedule=sched, include_tests=True) as det:
            t1.start(); t2.start(); sched.run()
        assert sched.trace == <same-seed trace>

    ``spawn`` registers the participant BEFORE its thread starts, so no
    participant can slip past the first barrier while another is still
    being scheduled by the OS; ``run`` releases the first step and joins
    all participants."""

    def __init__(self, seed: int, *, step_timeout: float = 5.0,
                 max_steps: int = 100_000):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._mu = _thread.allocate_lock()
        self.step_timeout = float(step_timeout)
        self.max_steps = int(max_steps)
        #: participant name -> pre-acquired gate the dispatcher releases
        self._gates: dict[str, object] = {}
        self._live: set[str] = set()
        self._arrived: dict[str, str] = {}  # name -> site waiting at
        self._idents: dict[int, str] = {}
        self._threads: list[threading.Thread] = []
        self._released = False
        #: (participant, site) per granted step — the replay artifact:
        #: identical across runs with the same seed
        self.trace: list[tuple[str, str]] = []
        self.stalls = 0

    # -- participant management -------------------------------------------

    def spawn(self, fn: Callable[[], None], name: str) -> threading.Thread:
        if name in self._gates:
            raise ValueError(f"duplicate participant {name!r}")
        gate = _thread.allocate_lock()
        gate.acquire()
        with self._mu:
            self._gates[name] = gate
            self._live.add(name)

        def body():
            ident = threading.get_ident()
            with self._mu:
                self._idents[ident] = name
            self._checkpoint(name, "start")
            try:
                fn()
            finally:
                with self._mu:
                    self._live.discard(name)
                    self._arrived.pop(name, None)
                    self._idents.pop(ident, None)
                    self._dispatch_locked()

        t = threading.Thread(target=body, name=f"serial-{name}", daemon=True)
        self._threads.append(t)
        return t

    def run(self, timeout: Optional[float] = None) -> None:
        """Open the schedule (threads must already be started) and join
        every participant."""
        with self._mu:
            self._released = True
            self._dispatch_locked()
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))

    # -- detector hook -----------------------------------------------------

    def on_access(self, site: str) -> None:
        with self._mu:
            name = self._idents.get(threading.get_ident())
        if name is not None:
            self._checkpoint(name, site)

    # -- internals ---------------------------------------------------------

    def _checkpoint(self, name: str, site: str) -> None:
        with self._mu:
            if len(self.trace) >= self.max_steps:
                return  # runaway guard: degrade to free running
            self._arrived[name] = site
            gate = self._gates[name]
            self._dispatch_locked()
        if not gate.acquire(timeout=self.step_timeout):
            # a participant is blocked outside the schedule (application
            # lock, IO): don't deadlock the repro — run free and record
            # the stall so the test can notice determinism was lost
            with self._mu:
                self._arrived.pop(name, None)
                self.stalls += 1

    def _dispatch_locked(self) -> None:
        """Grant one step when every live participant is parked at a
        checkpoint. Called with ``_mu`` held."""
        if not self._released or not self._arrived:
            return
        if set(self._arrived) != self._live or not self._live:
            return
        name = self._rng.choice(sorted(self._arrived))
        site = self._arrived.pop(name)
        self.trace.append((name, site))
        self._gates[name].release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"SerialSchedule(seed={self.seed}, steps={len(self.trace)}, "
                f"stalls={self.stalls})")
