"""bobrarace: a test-time data-race sanitizer for the control plane.

The repo's cross-shard correctness bugs (the PR-6 starved-heartbeat
double-reconcile, the stale-scope lost-work race fixed in PR 12, the
PR-11 churn flake) were all found by *probabilistic* churn soaks. The
reference operator leans on Go's ``-race`` detector for this class;
this module is the Python-process-model equivalent, layered on the
PR-4 lock-order sanitizer:

- **what is watched** — the hot shared containers declared via the
  :func:`guarded_state` class decorator (store indexes and watch
  registries, dispatcher active/dirty sets and worker deques, shard
  membership/parked roots, serving and traffic queues) are swapped for
  ``TrackedDict``/``TrackedList``/``TrackedSet``/``TrackedDeque``
  wrappers **at test time only**: the decorator records the field list
  in :data:`GUARDED_REGISTRY` and returns the class untouched, so
  production builds carry zero overhead; :func:`sanitize_races`
  patches the registered ``__init__``\\ s for the session the same way
  lockorder patches ``threading.Lock``. Ad-hoc containers born inside
  methods opt in with :func:`track`.
- **how a race is decided** — each access grabs the thread's lockset
  from the lockorder monitor (allocation-site lock classes + instance
  ids) and its vector clock (:mod:`.hb`); clocks gain edges from
  ``Thread.start``/``join``, ``Future.set_result``→``result``,
  ``Condition.notify``→``wait``, ``Event.set``→``wait``/``is_set``,
  ``queue.Queue.put``→``get``, ``ThreadPoolExecutor.submit``→run, and
  (in ``mode="hb"``) lock release→acquire. The default ``"hybrid"``
  mode keeps mutex reasoning in the Eraser lockset clause instead —
  see :mod:`.hb` for why that makes detection far less
  timing-dependent than pure FastTrack.
- **how a race is reported** — both access stacks, both locksets, and
  the variable's lockset history, with a line-number-free fingerprint
  (variable + the two access sites' file:function + op pair) gated by
  ``bobrarace-baseline.json`` at the repo root: same contract as
  bobralint (mandatory justifications, stale-entry reporting).
- **replay** — a seeded schedule from :mod:`.schedules` can be armed
  per detector (:meth:`RaceDetector.scoped_schedule`) to inject
  deterministic yield points at every instrumented access, so a churn
  flake reproduces from its seed.

Overhead (measured on tests/test_scale_soak.py's 1k-run soak shape,
BOBRA_SOAK=1, interleaved best-of-2 per PR-13 profiler style, soak GC
posture): sanitizer-on runs at **0.092x** the sanitizer-off steps/s
(28.8 vs 314.4 steps/s on the measurement box, ~10.9x slowdown; the
second trial pair repeated within 3%). Every store access crosses a
tracked wrapper on that soak, so this is the worst case — the armed
concurrency/chaos suites are wait-dominated and absorb it (fleet
chaos: 87s armed), which is exactly why the autouse fixtures scope
arming to those five modules and tier-1 at large runs untracked.
Rerun with ``python bench_race_overhead.py`` after touching the
wrapper hot path.

Static companion: the ``shared-state-discipline`` bobralint checker
walks lock-owning classes for container mutations outside ``with
self._lock`` and cross-checks every ``@guarded_state`` field list
against the containers it discovers, so the runtime instrumentation
and the static view cannot drift (tests/test_racedetect.py asserts
registry == discovery).
"""

from __future__ import annotations

import _thread
import contextlib
import dataclasses
import functools
import hashlib
import os
import queue as queue_mod
import sys
import threading
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterator, Optional

from . import lockorder
from .baseline import Baseline
from .hb import VarState, VectorClock

RACE_BASELINE_NAME = "bobrarace-baseline.json"

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_THIS_DIR))
_TEST_PART = f"{os.sep}tests{os.sep}"
_REPO_PARTS = (f"{os.sep}bobrapet_tpu{os.sep}", _TEST_PART)

#: the active detector, or None — product-code helpers (:func:`track`)
#: and the patched ``__init__``\\ s read this; a single global load when
#: the sanitizer is off.
_ACTIVE: Optional["RaceDetector"] = None

#: classes declared with :func:`guarded_state`: class -> field tuple.
#: Populated at import time (decoration), consumed at session arm time.
GUARDED_REGISTRY: dict[type, tuple[str, ...]] = {}


class RaceViolation(AssertionError):
    """An unsuppressed data race was observed (or a baseline went stale
    in strict mode)."""


def default_baseline_path() -> str:
    return os.path.join(_REPO_ROOT, RACE_BASELINE_NAME)


# ---------------------------------------------------------------------------
# declaration API (importable from product code, zero overhead when off)
# ---------------------------------------------------------------------------


def guarded_state(*fields: str):
    """Class decorator declaring which container attributes carry the
    class's cross-thread shared state. Purely declarative in
    production: it records ``(cls, fields)`` in :data:`GUARDED_REGISTRY`
    and returns the class unchanged. Inside a :func:`sanitize_races`
    session the declared fields are wrapped in tracked containers right
    after ``__init__`` returns.

    The field list is NOT free-form: the ``shared-state-discipline``
    checker recomputes the class's container attributes statically and
    flags any drift between that discovery and this declaration."""

    def deco(cls: type) -> type:
        GUARDED_REGISTRY[cls] = tuple(fields)
        cls.__guarded_fields__ = tuple(fields)
        return cls

    return deco


def track(label: str, container):
    """Opt a method-local / lazily-created container into tracking
    (e.g. the store's scheduling-gate reservation map, which is born
    outside ``__init__``). Returns the container unchanged when no
    sanitizer session is active."""
    det = _ACTIVE
    if det is None or not det.enabled:
        return container
    return det.wrap(label, container)


# ---------------------------------------------------------------------------
# access records + reports
# ---------------------------------------------------------------------------


def _capture_site(limit: int = 5) -> tuple:
    """Innermost repo frames (file, line, function) of the current
    access, skipping the sanitizer's own machinery."""
    frames = []
    f = sys._getframe(2)
    while f is not None and len(frames) < limit:
        fn = f.f_code.co_filename
        if any(p in fn for p in _REPO_PARTS) and not fn.startswith(_THIS_DIR):
            try:
                rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
            except ValueError:  # pragma: no cover - other-drive paths
                rel = fn
            frames.append((rel, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return tuple(frames)


@dataclasses.dataclass(frozen=True)
class AccessRecord:
    op: str  #: "read" | "write"
    thread: str
    site: tuple  #: ((rel_path, line, function), ...) innermost first
    lockset: frozenset

    @property
    def in_tests(self) -> bool:
        return bool(self.site) and self.site[0][0].startswith("tests/")

    def site_key(self) -> str:
        """Line-number-free identity of this access for fingerprints."""
        if not self.site:
            return f"{self.op}@?"
        path, _line, func = self.site[0]
        return f"{self.op}@{path}:{func}"

    def render(self) -> str:
        locks = ", ".join(sorted(self.lockset)) or "NO LOCKS"
        head = f"{self.op} by thread {self.thread!r} holding [{locks}]"
        body = "".join(
            f"\n      at {path}:{line} in {func}"
            for path, line, func in self.site
        ) or "\n      at <no repo frames>"
        return head + body


@dataclasses.dataclass
class RaceReport:
    """One unordered, unlocked conflicting access pair. Duck-compatible
    with bobralint's Finding where the Baseline machinery needs it
    (``fingerprint``/``checker``/``path``/``scope``/``message``)."""

    var: str
    a: AccessRecord  #: the earlier access
    b: AccessRecord  #: the access that exposed the race
    lockset_history: tuple
    count: int = 1

    checker: str = "bobrarace"

    @property
    def path(self) -> str:
        return self.b.site[0][0] if self.b.site else "?"

    @property
    def scope(self) -> str:
        return self.var

    @property
    def message(self) -> str:
        return (f"data race on {self.var}: {self.a.site_key()} vs "
                f"{self.b.site_key()}")

    @property
    def fingerprint(self) -> str:
        ka, kb = sorted((self.a.site_key(), self.b.site_key()))
        raw = f"bobrarace|{self.var}|{ka}|{kb}"
        return hashlib.sha256(raw.encode()).hexdigest()[:12]

    def render(self) -> str:
        lines = [
            f"DATA RACE on {self.var} ({self.fingerprint}, "
            f"seen {self.count}x):",
            f"  prior {self.a.render()}",
            f"  now   {self.b.render()}",
        ]
        if self.lockset_history:
            lines.append("  lockset history (most recent last):")
            lines.extend(f"    {h}" for h in self.lockset_history)
        return "\n".join(lines)


class _VarMeta:
    """Per-tracked-container detector state."""

    __slots__ = ("label", "state", "history", "prev_locks", "det")

    def __init__(self, label: str, det: "RaceDetector"):
        self.label = label
        self.state = VarState()
        self.history: deque = deque(maxlen=8)
        self.prev_locks: Optional[frozenset] = None
        self.det = det


# ---------------------------------------------------------------------------
# tracked containers
# ---------------------------------------------------------------------------


def _hooked(base: type, name: str, is_write: bool):
    orig = getattr(base, name)

    def method(self, *args, **kwargs):
        meta = self._rd_meta
        if meta is not None:
            meta.det.on_access(meta, is_write)
        return orig(self, *args, **kwargs)

    method.__name__ = name
    method.__qualname__ = name
    return method


def _make_tracked(base: type, name: str, writes: tuple, reads: tuple) -> type:
    ns: dict[str, Any] = {"_rd_meta": None}
    for m in writes:
        ns[m] = _hooked(base, m, True)
    for m in reads:
        ns[m] = _hooked(base, m, False)
    return type(name, (base,), ns)


TrackedDict = _make_tracked(
    dict, "TrackedDict",
    writes=("__setitem__", "__delitem__", "pop", "popitem", "clear",
            "update", "setdefault"),
    reads=("__getitem__", "__contains__", "__iter__", "__len__", "get",
           "keys", "values", "items", "copy"),
)

TrackedList = _make_tracked(
    list, "TrackedList",
    writes=("__setitem__", "__delitem__", "__iadd__", "append", "extend",
            "insert", "pop", "remove", "clear", "sort", "reverse"),
    reads=("__getitem__", "__contains__", "__iter__", "__len__", "index",
           "count", "copy"),
)

TrackedSet = _make_tracked(
    set, "TrackedSet",
    writes=("add", "discard", "remove", "pop", "clear", "update",
            "difference_update", "intersection_update",
            "symmetric_difference_update"),
    reads=("__contains__", "__iter__", "__len__", "copy", "issubset",
           "issuperset", "union", "intersection", "difference"),
)

TrackedDeque = _make_tracked(
    deque, "TrackedDeque",
    writes=("__setitem__", "__delitem__", "append", "appendleft", "extend",
            "extendleft", "insert", "pop", "popleft", "remove", "clear",
            "rotate"),
    reads=("__getitem__", "__contains__", "__iter__", "__len__", "count",
           "index", "copy"),
)

_TRACKED_TYPES = (TrackedDict, TrackedList, TrackedSet, TrackedDeque)


# ---------------------------------------------------------------------------
# the detector
# ---------------------------------------------------------------------------


class _ThreadState:
    __slots__ = ("tid", "vc")

    def __init__(self, tid: int):
        self.tid = tid
        self.vc = VectorClock()
        self.vc[tid] = 1


class RaceDetector:
    """One sanitizer session's state: per-thread clocks, per-variable
    FastTrack/Eraser states, the race report ledger, and the patch
    bookkeeping. Internal synchronization uses ``_thread.allocate_lock``
    directly so the detector's own lock is invisible to the lockorder
    patches and to itself."""

    def __init__(
        self,
        monitor: Optional[lockorder.LockMonitor] = None,
        mode: Optional[str] = None,
        schedule=None,
        include_tests: bool = False,
    ):
        if mode is None:
            mode = os.environ.get("BOBRA_RACE_MODE", "hybrid")
        if mode not in ("hybrid", "hb"):
            raise ValueError(f"unknown race mode {mode!r}")
        self.mode = mode
        self.enabled = True
        self.monitor = monitor
        self.schedule = schedule
        #: report races with a tests/-frame side? Default no: tests
        #: poll product state unlocked by design (wait_for loops); the
        #: clocks still advance through those accesses, but only
        #: product<->product unordered pairs gate. The known-bad corpus
        #: (whose racy bodies live in test files) flips this on.
        self.include_tests = include_tests
        self._lock = _thread.allocate_lock()
        self._tls = threading.local()
        self._next_tid = 0
        self.access_count = 0
        #: fingerprint -> RaceReport (deduped)
        self._reports: dict[str, RaceReport] = {}
        #: product-suppressed observations (a tests/-frame side), kept
        #: for debugging/triage visibility
        self.observer_races: list[RaceReport] = []
        #: id(lock) -> stable per-instance index for lockset identity
        self._lock_seq: dict[int, int] = {}
        #: id(lock) -> release-clock snapshot (mode="hb" only)
        self._release_clocks: dict[int, dict] = {}
        self._patches: list[tuple[Any, str, Any]] = []
        self._patched_inits: list[tuple[type, Any]] = []
        self.tracked_labels: list[str] = []

    # -- thread clocks -----------------------------------------------------

    def _thread_state(self) -> _ThreadState:
        st = getattr(self._tls, "st", None)
        if st is None:
            with self._lock:
                self._next_tid += 1
                tid = self._next_tid
            st = self._tls.st = _ThreadState(tid)
            birth = getattr(threading.current_thread(), "_rd_birth", None)
            if birth:
                st.vc.join(birth)
        return st

    def _publish(self) -> dict:
        """Snapshot the current thread's clock and advance it: the
        sender half of every HB edge."""
        st = self._thread_state()
        snap = st.vc.snapshot()
        st.vc.advance(st.tid)
        return snap

    def _join(self, snap: Optional[dict]) -> None:
        if snap:
            self._thread_state().vc.join(snap)

    def _merge_shared(self, obj, attr: str = "_rd_clock") -> None:
        """Publish into a clock slot on a shared object (condition,
        event, future), joining with whatever is already there."""
        snap = self._publish()
        with self._lock:
            cur = getattr(obj, attr, None)
            if cur is None:
                try:
                    setattr(obj, attr, dict(snap))
                except AttributeError:  # pragma: no cover - slotted obj
                    pass
            else:
                for t, c in snap.items():
                    if c > cur.get(t, 0):
                        cur[t] = c

    def _join_shared(self, obj, attr: str = "_rd_clock") -> None:
        with self._lock:
            cur = getattr(obj, attr, None)
            snap = dict(cur) if cur else None
        self._join(snap)

    # -- lockset -----------------------------------------------------------

    def _lockset(self) -> frozenset:
        mon = self.monitor
        if mon is None:
            return frozenset()
        out = []
        for lock, label in mon.held():
            key = id(lock)
            with self._lock:
                idx = self._lock_seq.get(key)
                if idx is None:
                    idx = self._lock_seq[key] = len(self._lock_seq) + 1
            out.append(f"{label}#{idx}")
        return frozenset(out)

    # -- lockorder listener hooks (mode="hb" lock HB edges) ----------------

    def lock_acquired(self, lock, label: str) -> None:
        if self.mode != "hb" or not self.enabled:
            return
        with self._lock:
            snap = self._release_clocks.get(id(lock))
            snap = dict(snap) if snap else None
        self._join(snap)

    def lock_released(self, lock, label: str) -> None:
        if self.mode != "hb" or not self.enabled:
            return
        snap = self._publish()
        with self._lock:
            cur = self._release_clocks.get(id(lock))
            if cur is None:
                self._release_clocks[id(lock)] = dict(snap)
            else:
                for t, c in snap.items():
                    if c > cur.get(t, 0):
                        cur[t] = c

    # -- instrumentation ---------------------------------------------------

    def wrap(self, label: str, container):
        if isinstance(container, _TRACKED_TYPES):
            return container
        t = type(container)
        if t is dict:
            wrapped = TrackedDict(container)
        elif t is list:
            wrapped = TrackedList(container)
        elif t is set:
            wrapped = TrackedSet(container)
        elif t is deque:
            wrapped = TrackedDeque(container, container.maxlen)
        else:
            return container
        wrapped._rd_meta = _VarMeta(label, self)
        with self._lock:
            self.tracked_labels.append(label)
        return wrapped

    def instrument(self, obj, cls: type, fields: tuple) -> None:
        if getattr(obj, "_rd_instrumented", False):
            return
        try:
            obj._rd_instrumented = True
        except AttributeError:  # pragma: no cover - __slots__ class
            return
        for field in fields:
            val = getattr(obj, field, None)
            wrapped = self.wrap(f"{cls.__name__}.{field}", val)
            if wrapped is not val:
                setattr(obj, field, wrapped)

    # -- the access check --------------------------------------------------

    def on_access(self, meta: _VarMeta, is_write: bool) -> None:
        if not self.enabled:
            return
        tls = self._tls
        if getattr(tls, "busy", False):
            return
        sched = self.schedule
        tls.busy = True
        try:
            st = self._thread_state()
            ls = self._lockset()
            rec = AccessRecord(
                op="write" if is_write else "read",
                thread=threading.current_thread().name,
                site=_capture_site(),
                lockset=ls,
            )
            with self._lock:
                self.access_count += 1
                if ls != meta.prev_locks:
                    meta.prev_locks = ls
                    meta.history.append(
                        f"{rec.op} by {rec.thread} holding "
                        f"[{', '.join(sorted(ls)) or 'nothing'}]"
                    )
                chk = meta.state.on_access(st.tid, st.vc, ls, is_write, rec)
                if chk.conflicts and chk.common_locks:
                    meta.history.append(
                        f"unordered {rec.op} by {rec.thread} excused by "
                        f"common [{', '.join(sorted(chk.common_locks))}]"
                    )
                elif chk.conflicts:
                    for prior in chk.conflicts:
                        if prior is not None:
                            self._record_race_locked(meta, prior, rec)
        finally:
            tls.busy = False
        if sched is not None:
            sched.on_access(meta.label)

    def _record_race_locked(self, meta: _VarMeta, prior: AccessRecord,
                            rec: AccessRecord) -> None:
        report = RaceReport(
            var=meta.label, a=prior, b=rec,
            lockset_history=tuple(meta.history),
        )
        if not self.include_tests and (prior.in_tests or rec.in_tests):
            if len(self.observer_races) < 100:
                self.observer_races.append(report)
            return
        existing = self._reports.get(report.fingerprint)
        if existing is not None:
            existing.count += 1
        else:
            self._reports[report.fingerprint] = report

    # -- results -----------------------------------------------------------

    @property
    def reports(self) -> list[RaceReport]:
        return sorted(self._reports.values(),
                      key=lambda r: (r.var, r.fingerprint))

    def report_text(self) -> str:
        parts = [r.render() for r in self.reports]
        parts.append(
            f"bobrarace: {len(self._reports)} distinct race(s) over "
            f"{self.access_count} tracked accesses, "
            f"{len(self.tracked_labels)} tracked containers"
        )
        return "\n".join(parts)

    def assert_clean(
        self,
        baseline_path: Optional[str] = None,
        strict_stale: Optional[bool] = None,
    ) -> None:
        """Gate against ``bobrarace-baseline.json``: raise on any race
        whose fingerprint is not suppressed there; report (or, strict,
        raise on) suppressions no longer observed this session is NOT
        stale — stale means the fingerprint never fires across the armed
        suites, which ``make race`` checks in aggregate via
        BOBRA_RACE_STRICT_STALE."""
        if strict_stale is None:
            strict_stale = os.environ.get(
                "BOBRA_RACE_STRICT_STALE", ""
            ) not in ("", "0", "false")
        baseline = Baseline.load(baseline_path or default_baseline_path())
        new, suppressed, stale = baseline.partition(self.reports)
        if stale and strict_stale:
            lines = [
                f"stale: {s.fingerprint} ({s.scope}): {s.message}"
                for s in stale
            ]
            raise RaceViolation(
                "bobrarace baseline has stale suppressions (fixed races "
                "whose entries must be deleted):\n" + "\n".join(lines)
            )
        if new:
            raise RaceViolation(
                "\n".join(r.render() for r in new)
                + f"\n{len(new)} unsuppressed data race(s); "
                f"{len(suppressed)} baseline-suppressed. Fix the race or "
                f"justify it in {RACE_BASELINE_NAME}."
            )

    @contextlib.contextmanager
    def scoped_schedule(self, sched) -> Iterator:
        """Arm a replay schedule for a code region (e.g. one churn
        soak): every tracked access becomes a seeded yield point."""
        prev = self.schedule
        self.schedule = sched
        try:
            yield sched
        finally:
            self.schedule = prev

    # -- patching ----------------------------------------------------------

    def _patch(self, obj, name: str, wrapper_factory: Callable) -> None:
        orig = getattr(obj, name)
        setattr(obj, name, wrapper_factory(orig))
        self._patches.append((obj, name, orig))

    def _arm_patches(self) -> None:
        det = self

        def wrap_start(orig):
            def start(thr):
                if det.enabled:
                    thr._rd_birth = det._publish()
                    det._wrap_run(thr)
                return orig(thr)
            return start

        def wrap_join(orig):
            def join(thr, timeout=None):
                r = orig(thr, timeout)
                if det.enabled and not thr.is_alive():
                    det._join(getattr(thr, "_rd_final", None))
                return r
            return join

        def wrap_is_alive(orig):
            def is_alive(thr):
                r = orig(thr)
                if det.enabled and not r:
                    det._join(getattr(thr, "_rd_final", None))
                return r
            return is_alive

        self._patch(threading.Thread, "start", wrap_start)
        self._patch(threading.Thread, "join", wrap_join)
        self._patch(threading.Thread, "is_alive", wrap_is_alive)

        def wrap_notify(orig):
            def notify(cond, n=1):
                if det.enabled:
                    det._merge_shared(cond)
                return orig(cond, n)
            return notify

        def wrap_notify_all(orig):
            def notify_all(cond):
                if det.enabled:
                    det._merge_shared(cond)
                return orig(cond)
            return notify_all

        def wrap_wait(orig):
            def wait(cond, timeout=None):
                r = orig(cond, timeout)
                if det.enabled:
                    det._join_shared(cond)
                return r
            return wait

        self._patch(threading.Condition, "notify", wrap_notify)
        self._patch(threading.Condition, "notify_all", wrap_notify_all)
        self._patch(threading.Condition, "wait", wrap_wait)

        def wrap_event_set(orig):
            def set_(ev):
                if det.enabled:
                    det._merge_shared(ev)
                return orig(ev)
            return set_

        def wrap_event_wait(orig):
            def wait(ev, timeout=None):
                r = orig(ev, timeout)
                if det.enabled and r:
                    det._join_shared(ev)
                return r
            return wait

        def wrap_event_is_set(orig):
            def is_set(ev):
                r = orig(ev)
                if det.enabled and r:
                    det._join_shared(ev)
                return r
            return is_set

        self._patch(threading.Event, "set", wrap_event_set)
        self._patch(threading.Event, "wait", wrap_event_wait)
        self._patch(threading.Event, "is_set", wrap_event_is_set)

        def wrap_put(orig):
            def put(q, item, block=True, timeout=None):
                r = orig(q, item, block, timeout)
                if det.enabled:
                    snap = det._publish()
                    with det._lock:
                        clocks = getattr(q, "_rd_clock_q", None)
                        if clocks is None:
                            try:
                                q._rd_clock_q = clocks = deque()
                            except AttributeError:  # pragma: no cover
                                return r
                        clocks.append(snap)
                return r
            return put

        def wrap_get(orig):
            def get(q, block=True, timeout=None):
                item = orig(q, block, timeout)
                if det.enabled:
                    with det._lock:
                        clocks = getattr(q, "_rd_clock_q", None)
                        snap = clocks.popleft() if clocks else None
                    det._join(snap)
                return item
            return get

        self._patch(queue_mod.Queue, "put", wrap_put)
        self._patch(queue_mod.Queue, "get", wrap_get)

        def wrap_set_result(orig):
            def set_result(fut, result):
                if det.enabled:
                    det._merge_shared(fut)
                return orig(fut, result)
            return set_result

        def wrap_set_exception(orig):
            def set_exception(fut, exception):
                if det.enabled:
                    det._merge_shared(fut)
                return orig(fut, exception)
            return set_exception

        def wrap_result(orig):
            def result(fut, timeout=None):
                try:
                    return orig(fut, timeout)
                finally:
                    if det.enabled and fut.done():
                        det._join_shared(fut)
            return result

        def wrap_exception(orig):
            def exception(fut, timeout=None):
                try:
                    return orig(fut, timeout)
                finally:
                    if det.enabled and fut.done():
                        det._join_shared(fut)
            return exception

        self._patch(Future, "set_result", wrap_set_result)
        self._patch(Future, "set_exception", wrap_set_exception)
        self._patch(Future, "result", wrap_result)
        self._patch(Future, "exception", wrap_exception)

        def wrap_submit(orig):
            def submit(ex, fn, *args, **kwargs):
                if not det.enabled:
                    return orig(ex, fn, *args, **kwargs)
                birth = det._publish()

                @functools.wraps(fn)
                def handoff(*a, **kw):
                    det._join(birth)
                    return fn(*a, **kw)

                return orig(ex, handoff, *args, **kwargs)
            return submit

        self._patch(ThreadPoolExecutor, "submit", wrap_submit)

    def _wrap_run(self, thr: threading.Thread) -> None:
        det = self
        orig_run = thr.run

        def run():
            det._join(getattr(thr, "_rd_birth", None))
            try:
                orig_run()
            finally:
                if det.enabled:
                    st = det._thread_state()
                    thr._rd_final = st.vc.snapshot()

        thr.run = run

    def _arm_guarded_classes(self) -> None:
        for cls, fields in list(GUARDED_REGISTRY.items()):
            orig = cls.__dict__.get("__init__")
            if orig is None or getattr(orig, "_rd_wrapped", False):
                continue
            cls.__init__ = _make_guarded_init(orig, cls, fields)
            self._patched_inits.append((cls, orig))

    def _disarm(self) -> None:
        self.enabled = False
        for obj, name, orig in reversed(self._patches):
            setattr(obj, name, orig)
        self._patches.clear()
        for cls, orig in reversed(self._patched_inits):
            cls.__init__ = orig
        self._patched_inits.clear()


def _make_guarded_init(orig, cls: type, fields: tuple):
    @functools.wraps(orig)
    def __init__(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        det = _ACTIVE
        if det is not None and det.enabled:
            det.instrument(self, cls, fields)

    __init__._rd_wrapped = True
    return __init__


# ---------------------------------------------------------------------------
# session entry point
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def sanitize_races(
    monitor: Optional[lockorder.LockMonitor] = None,
    mode: Optional[str] = None,
    schedule=None,
    include_tests: bool = False,
) -> Iterator[RaceDetector]:
    """Arm the data-race sanitizer for a region. Composes with an
    already-armed lockorder session (pass its monitor, or let it find
    :func:`lockorder.current_monitor`); opens a private one otherwise —
    the lockset clause needs instrumented locks to see anything.

    Typical suite wiring (module-scoped autouse, after the lockorder
    fixture so lock patching is already live)::

        with sanitize_races(monitor=lock_monitor) as det:
            ... threaded workload ...
        det.assert_clean()
    """
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("sanitize_races sessions do not nest")
    own_locks = None
    if monitor is None:
        monitor = lockorder.current_monitor()
    if monitor is None:
        own_locks = lockorder.sanitize_locks()
        monitor = own_locks.__enter__()
    det = RaceDetector(monitor=monitor, mode=mode, schedule=schedule,
                       include_tests=include_tests)
    monitor.add_listener(det)
    det._arm_patches()
    det._arm_guarded_classes()
    _ACTIVE = det
    try:
        yield det
    finally:
        _ACTIVE = None
        det._disarm()
        monitor.remove_listener(det)
        if own_locks is not None:
            own_locks.__exit__(None, None, None)


def render_race_baseline(reports, justification: str = "todo") -> str:
    """Serialize observed races as a ``bobrarace-baseline.json``
    document (the loader rejects the placeholder justification — each
    entry must be hand-audited, same contract as bobralint)."""
    return Baseline.render(reports, justification)
