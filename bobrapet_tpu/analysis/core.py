"""Analyzer framework: findings, file loading, checker driver.

A checker is an object with a ``name``, a one-line ``description`` and a
``run(files, ctx)`` returning :class:`Finding`s. Checkers get the WHOLE
parsed project at once (not one file at a time) because most of the
repo-native checks are cross-file by nature: a metric emitted in
``controllers/`` is validated against the registry in
``observability/metrics.py``, a config literal in ``tests/`` against
the dotted-key table in ``config/operator.py``.

Finding fingerprints deliberately exclude line numbers: a baseline entry
must survive unrelated edits above it. The identity is
``(checker, path, enclosing scope, message kernel)`` — lockdep-style
class suppression, so two identical violations in one function share a
fingerprint and one justification covers both.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
from typing import Iterable, Optional, Protocol, Sequence

#: directories never analyzed (generated output, caches, VCS, and the
#: checker test corpus — its *_bad.py files violate invariants on
#: purpose; test_analysis.py feeds them to the checkers directly)
_SKIP_DIRS = {
    "__pycache__", ".git", ".jax_cache", "node_modules", ".venv",
    "analysis_corpus",
}

#: default analysis roots, relative to the repo root. Tests are
#: included: the invariants (no bare enum literals, registered config
#: keys) bind test code too — tests are where drift usually starts.
DEFAULT_ROOTS = ("bobrapet_tpu", "tests", "bench.py")


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str  #: checker name, e.g. "lock-blocking-io"
    path: str  #: repo-relative posix path
    line: int
    col: int
    scope: str  #: dotted enclosing class/function chain ("" at module level)
    message: str  #: full human-readable description
    kernel: str  #: stable short core of the message (fingerprint input)

    @property
    def fingerprint(self) -> str:
        raw = f"{self.checker}|{self.path}|{self.scope}|{self.kernel}"
        return hashlib.sha256(raw.encode()).hexdigest()[:12]

    def render(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.checker}: {self.message}{scope} ({self.fingerprint})"


@dataclasses.dataclass
class ProjectFile:
    path: str  #: absolute
    rel: str  #: repo-relative posix
    source: str
    tree: ast.Module


class Checker(Protocol):  # pragma: no cover - typing only
    name: str
    description: str

    def run(self, files: Sequence[ProjectFile], ctx: "AnalysisContext") -> Iterable[Finding]: ...


@dataclasses.dataclass
class AnalysisContext:
    """Shared project facts, computed once per run (see context.py)."""

    root: str
    files: list[ProjectFile] = dataclasses.field(default_factory=list)
    _cache: dict = dataclasses.field(default_factory=dict)

    def file(self, rel: str) -> Optional[ProjectFile]:
        for f in self.files:
            if f.rel == rel:
                return f
        return None

    def memo(self, key: str, build):
        if key not in self._cache:
            self._cache[key] = build()
        return self._cache[key]


def _iter_py_files(root: str, roots: Sequence[str]) -> Iterable[str]:
    for entry in roots:
        top = os.path.join(root, entry)
        if os.path.isfile(top):
            if top.endswith(".py"):
                yield top
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def load_project(
    root: str, roots: Sequence[str] = DEFAULT_ROOTS
) -> tuple[AnalysisContext, list[str]]:
    """Parse every analyzable file once; syntax errors are reported,
    not fatal (one broken file must not hide findings elsewhere)."""
    ctx = AnalysisContext(root=os.path.abspath(root))
    errors: list[str] = []
    for path in _iter_py_files(ctx.root, roots):
        rel = os.path.relpath(path, ctx.root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel)
        except (OSError, SyntaxError) as e:
            errors.append(f"{rel}: {e}")
            continue
        ctx.files.append(ProjectFile(path=path, rel=rel, source=source, tree=tree))
    return ctx, errors


def run_checkers(
    ctx: AnalysisContext, checkers: Sequence[Checker]
) -> list[Finding]:
    findings: list[Finding] = []
    for checker in checkers:
        findings.extend(checker.run(ctx.files, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.checker))
    return findings


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def attr_chain(node: ast.AST) -> Optional[list[str]]:
    """``a.b.c`` -> ["a","b","c"]; ``a["k"].b`` -> ["a","b"] (subscripts
    are transparent). Returns None if the chain passes through a call or
    any non-name root — a call result is a NEW object, which breaks
    taint/receiver reasoning."""
    parts: list[str] = []
    cur = node
    while True:
        if isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Name):
            parts.append(cur.id)
            parts.reverse()
            return parts
        else:
            return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last identifier of a Name/Attribute expression, else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def hint_text(node: ast.AST) -> str:
    """Lowercased bag of identifiers + string constants under a node —
    used to decide whether e.g. a comparison is 'about' a phase."""
    out: list[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
    return " ".join(out).lower()
