"""CLI: ``python -m bobrapet_tpu.analysis`` (Makefile: ``make analyze``).

Exit codes: 0 = clean modulo baseline, 1 = new findings (or baseline
errors), 2 = usage/internal error. ``--write-baseline`` regenerates the
baseline from the current findings with placeholder justifications the
loader deliberately REJECTS — each entry must be hand-audited (replace
the placeholder with a real why) before CI goes green again.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .baseline import BASELINE_NAME, Baseline, BaselineError
from .checkers import ALL_CHECKERS
from .core import DEFAULT_ROOTS, load_project, run_checkers


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bobrapet_tpu.analysis",
        description="bobralint: repo-native invariant analyzer",
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help=f"analysis roots relative to --root (default: {', '.join(DEFAULT_ROOTS)})",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root (default: auto-detect from this package's location)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <root>/{BASELINE_NAME})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file with placeholder "
             "justifications (hand-audit required before it loads)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    parser.add_argument(
        "--checker", action="append", default=None,
        help="run only the named checker(s)",
    )
    parser.add_argument(
        "--strict-stale", action="store_true",
        help="fail when baseline entries no longer match any finding",
    )
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)

    checkers = ALL_CHECKERS
    if args.checker:
        wanted = set(args.checker)
        known = {c.name for c in ALL_CHECKERS}
        unknown = wanted - known
        if unknown:
            print(f"unknown checker(s): {', '.join(sorted(unknown))}; "
                  f"available: {', '.join(sorted(known))}", file=sys.stderr)
            return 2
        checkers = tuple(c for c in ALL_CHECKERS if c.name in wanted)

    roots = tuple(args.paths) if args.paths else DEFAULT_ROOTS
    ctx, parse_errors = load_project(root, roots)
    findings = run_checkers(ctx, checkers)

    if args.write_baseline:
        doc = Baseline.render(
            findings,
            justification="PLACEHOLDER — audit this finding and explain why "
                          "it is intentional, or fix it",
        )
        with open(baseline_path, "w", encoding="utf-8") as f:
            f.write(doc)
        print(f"wrote {len(findings)} suppression(s) to {baseline_path}; "
              f"hand-audit every justification before CI will pass")
        return 0

    if args.no_baseline:
        new, suppressed, stale = list(findings), [], []
    else:
        try:
            baseline = Baseline.load(baseline_path)
        except BaselineError as e:
            print(f"baseline error: {e}", file=sys.stderr)
            return 1
        new, suppressed, stale = baseline.partition(findings)

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "suppressed": [f.fingerprint for f in suppressed],
            "stale": [s.fingerprint for s in stale],
            "parse_errors": parse_errors,
        }, indent=2))
    else:
        for err in parse_errors:
            print(f"PARSE ERROR: {err}")
        for f in new:
            print(f.render())
        if stale:
            print(f"-- {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed or moved; "
                  f"prune from {os.path.basename(baseline_path)}):")
            for s in stale:
                print(f"   {s.fingerprint} {s.checker} {s.path} [{s.scope}]")
        print(
            f"bobralint: {len(new)} new finding(s), "
            f"{len(suppressed)} suppressed, {len(stale)} stale, "
            f"{len(ctx.files)} file(s) analyzed"
        )

    if parse_errors or new:
        return 1
    if stale and args.strict_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
