"""Runtime lock-order sanitizer: lockdep for the concurrency suites.

The static checkers see lock-held *regions*; this module watches lock
*interleavings* while the threaded suites actually run. Inside a
:func:`sanitize_locks` session, every ``threading.Lock()`` /
``threading.RLock()`` created by repo code is wrapped so acquisitions
record, per thread, the stack of locks currently held. Two facts are
collected:

- the **acquisition-order graph**: an edge A→B whenever a thread
  acquires a lock of class B while holding one of class A. A cycle in
  that graph (including a self-edge over two *distinct instances* of
  one class) is a potential deadlock — two threads can interleave the
  two orders and wait on each other forever. This is ThreadSanitizer's
  lock-order inversion detection / the kernel's lockdep, scoped to this
  process model.
- **hold times**: wall-clock per acquisition, with Condition waits
  excluded (``wait()`` releases the lock; the hold naturally splits).
  Holds beyond the budget (``BOBRA_LOCK_HOLD_BUDGET``, default 0.5 s)
  are reported as warnings — wall-clock under CI contention is too
  noisy to gate on by default; set ``BOBRA_LOCK_HOLD_STRICT=1`` to
  fail on them.

Lock *classes* are keyed by allocation site (``module:lineno``), like
lockdep: all instances born on one line share a class, so an ordering
inversion between two ``SlicePool``\\ s is caught even though the
specific instances differ, while a class's two different lock
attributes (born on different lines) stay distinct.

Locks created by stdlib code (logging, queue, thread startup) are left
untouched — zero overhead, zero duck-typing risk; edges through them
are invisible, which is fine: the invariants under test are about repo
locks.

Usage (the three threaded suites wire this as an autouse fixture)::

    with sanitize_locks() as monitor:
        ... run threaded workload ...
    monitor.assert_clean()   # raises LockOrderViolation on cycles
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Iterator, Optional

_THIS_FILE = os.path.abspath(__file__)
#: repo source prefixes whose lock allocations are tracked
_TRACKED_PARTS = (f"{os.sep}bobrapet_tpu{os.sep}", f"{os.sep}tests{os.sep}")


class LockOrderViolation(AssertionError):
    """The acquisition-order graph has a cycle (potential deadlock)."""


class LockMonitor:
    """Collects acquisition edges + hold times for one session."""

    def __init__(self, hold_budget: Optional[float] = None):
        if hold_budget is None:
            hold_budget = float(os.environ.get("BOBRA_LOCK_HOLD_BUDGET", "0.5"))
        self.hold_budget = hold_budget
        self.enabled = True
        self._tls = threading.local()
        #: (from_label, to_label) -> acquisition count. Plain dict ops
        #: under the GIL; per-edge counts may undercount under heavy
        #: races but edge EXISTENCE (what cycles are built from) cannot
        #: be lost.
        self.edges: dict[tuple[str, str], int] = {}
        #: label -> max observed hold seconds
        self.max_hold: dict[str, float] = {}
        #: (label, seconds) for holds beyond budget
        self.hold_violations: list[tuple[str, float]] = []
        #: downstream consumers of lock events (racedetect attaches
        #: here to build happens-before edges + per-thread locksets
        #: without double-patching threading.Lock). A listener sees
        #: ``lock_acquired(lock, label)`` after a real (non-reentrant)
        #: acquisition and ``lock_released(lock, label)`` just BEFORE
        #: the real release — so a release-clock snapshot is taken
        #: while the lock is still held (correct release->acquire HB
        #: ordering).
        self.listeners: list = []

    def add_listener(self, listener) -> None:
        if listener not in self.listeners:
            self.listeners.append(listener)

    def remove_listener(self, listener) -> None:
        if listener in self.listeners:
            self.listeners.remove(listener)

    # -- per-thread stack --------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> list[tuple[object, str]]:
        """(lock, label) pairs the CURRENT thread holds, bottom-up.
        Reads only this thread's stack — safe without a lock."""
        return [(entry[0], entry[1]) for entry in self._stack()]

    def on_acquired(self, lock: "_SanitizedLockBase", count: int = 1) -> None:
        if not self.enabled:
            return
        stack = self._stack()
        for entry in stack:
            if entry[0] is lock:  # re-entrant RLock acquire
                entry[3] += count
                return
        if stack:
            top = stack[-1]
            if top[0] is not lock:
                key = (top[1], lock.label)
                self.edges[key] = self.edges.get(key, 0) + 1
        stack.append([lock, lock.label, time.monotonic(), count])
        for listener in self.listeners:
            listener.lock_acquired(lock, lock.label)

    def on_released(self, lock: "_SanitizedLockBase") -> None:
        if not self.enabled:
            return
        stack = self._stack()
        # search from the top: locks may legally release out of order
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                if stack[i][3] > 1:
                    stack[i][3] -= 1
                    return
                held = time.monotonic() - stack[i][2]
                del stack[i]
                self._note_hold(lock.label, held)
                for listener in self.listeners:
                    listener.lock_released(lock, lock.label)
                return

    def on_wait_release(self, lock: "_SanitizedLockBase") -> None:
        """Condition.wait released the lock entirely (_release_save)."""
        if not self.enabled:
            return
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                held = time.monotonic() - stack[i][2]
                del stack[i]
                self._note_hold(lock.label, held)
                for listener in self.listeners:
                    listener.lock_released(lock, lock.label)
                return

    def _note_hold(self, label: str, held: float) -> None:
        if held > self.max_hold.get(label, 0.0):
            self.max_hold[label] = held
        if held > self.hold_budget > 0:
            self.hold_violations.append((label, held))

    # -- analysis ----------------------------------------------------------

    def cycles(self) -> list[list[str]]:
        """Strongly connected components of the edge graph with more
        than one node, plus self-edges — each is a potential deadlock."""
        graph: dict[str, set[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        out: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            # iterative Tarjan (suites can build deep graphs)
            work = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1 or (node, node) in self.edges:
                        out.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return out

    def report(self) -> str:
        lines = [
            f"lock-order sanitizer: {len(self.edges)} edge(s), "
            f"{len(self.max_hold)} lock class(es)"
        ]
        for cyc in self.cycles():
            involved = [
                f"{a} -> {b} ({n}x)"
                for (a, b), n in sorted(self.edges.items())
                if a in cyc and b in cyc
            ]
            lines.append("CYCLE: " + " | ".join(involved))
        for label, held in self.hold_violations:
            lines.append(
                f"HOLD: {label} held {held:.3f}s "
                f"(budget {self.hold_budget:.3f}s)"
            )
        return "\n".join(lines)

    def assert_clean(self, strict_hold: Optional[bool] = None) -> None:
        """Raise on acquisition-order cycles; hold-budget violations
        raise only in strict mode (default: BOBRA_LOCK_HOLD_STRICT)."""
        if strict_hold is None:
            strict_hold = os.environ.get("BOBRA_LOCK_HOLD_STRICT", "") not in (
                "", "0", "false",
            )
        cycles = self.cycles()
        if cycles or (strict_hold and self.hold_violations):
            raise LockOrderViolation(self.report())
        if self.hold_violations:
            print(f"[lockorder warning]\n{self.report()}", file=sys.stderr)


# ---------------------------------------------------------------------------
# instrumented lock wrappers
# ---------------------------------------------------------------------------


class _SanitizedLockBase:
    __slots__ = ("_inner", "label", "_mon")

    def __init__(self, inner, label: str, mon: LockMonitor):
        self._inner = inner
        self.label = label
        self._mon = mon

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._mon.on_acquired(self)
        return got

    def release(self) -> None:
        self._mon.on_released(self)
        self._inner.release()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<sanitized {self.label} wrapping {self._inner!r}>"


class _SanitizedLock(_SanitizedLockBase):
    __slots__ = ()

    # Condition duck-typing for plain Locks uses acquire/release only —
    # already instrumented above.


class _SanitizedRLock(_SanitizedLockBase):
    __slots__ = ()

    # Condition(RLock) protocol: wait() saves/releases the whole
    # recursion and restores it on wakeup; mirror that in the stack so
    # the wait time never counts as hold time.
    def _release_save(self):
        self._mon.on_wait_release(self)
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        # CPython's RLock state is (count, owner): restore the SAME
        # recursion depth in the monitor, or the first post-wait
        # release() of a recursively-held lock would drop the entry
        # while the lock is still held (missed ordering edges)
        count = state[0] if isinstance(state, tuple) and state else 1
        self._mon.on_acquired(self, count=max(1, int(count)))

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def _creation_label() -> Optional[str]:
    """Allocation site of the lock being created, as ``module:lineno``;
    None -> do not track. Only the IMMEDIATE caller frame counts: a
    ``threading.Lock()`` written in repo source is a repo lock, but a
    lock born inside a stdlib constructor the repo merely invoked
    (ThreadPoolExecutor, Thread, Event, Condition) is stdlib machinery —
    attributing those to the repo call site would fuse many unrelated
    stdlib locks into one fake lock class and manufacture cycles."""
    frame = sys._getframe(2)
    fn = frame.f_code.co_filename
    if fn != _THIS_FILE and any(p in fn for p in _TRACKED_PARTS):
        mod = frame.f_globals.get("__name__", "?")
        return f"{mod}:{frame.f_lineno}"
    return None


#: the monitor of the innermost active :func:`sanitize_locks` session,
#: so cooperating instrumentation (racedetect) can attach listeners to
#: an already-armed session instead of re-patching threading.Lock.
_CURRENT_MONITOR: Optional[LockMonitor] = None


def current_monitor() -> Optional[LockMonitor]:
    return _CURRENT_MONITOR


@contextlib.contextmanager
def sanitize_locks(
    hold_budget: Optional[float] = None,
) -> Iterator[LockMonitor]:
    """Patch ``threading.Lock``/``RLock`` for the duration; locks repo
    code creates inside the session are instrumented and keep working
    (recording stops) after the session ends."""
    global _CURRENT_MONITOR
    mon = LockMonitor(hold_budget=hold_budget)
    real_lock = threading.Lock
    real_rlock = threading.RLock

    def make_lock():
        label = _creation_label()
        inner = real_lock()
        return inner if label is None else _SanitizedLock(inner, label, mon)

    def make_rlock():
        label = _creation_label()
        inner = real_rlock()
        return inner if label is None else _SanitizedRLock(inner, label, mon)

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.RLock = make_rlock  # type: ignore[assignment]
    prev_monitor = _CURRENT_MONITOR
    _CURRENT_MONITOR = mon
    try:
        yield mon
    finally:
        _CURRENT_MONITOR = prev_monitor
        threading.Lock = real_lock  # type: ignore[assignment]
        threading.RLock = real_rlock  # type: ignore[assignment]
        mon.enabled = False
