"""Project-fact extraction for the repo-native checkers.

Everything here reads the AUTHORITATIVE in-repo registries by AST — the
dotted-key table in ``config/operator.py``, the metric families in
``observability/metrics.py``, the enum vocabulary in ``api/enums.py`` /
``api/conditions.py`` — so the checkers compare code against what the
code actually registers, never against a second hand-maintained list
that could itself drift.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Optional

from .core import AnalysisContext, attr_chain

CONFIG_MODULE = "bobrapet_tpu/config/operator.py"
METRICS_MODULE = "bobrapet_tpu/observability/metrics.py"
ENUMS_MODULE = "bobrapet_tpu/api/enums.py"
CONDITIONS_MODULE = "bobrapet_tpu/api/conditions.py"

#: dynamic dotted-key families parsed structurally (not via the table)
#: in config/operator.py:_apply_dotted — kept in sync by
#: test_analysis.py::test_dynamic_config_families_still_parsed
DYNAMIC_CONFIG_FAMILIES = (
    re.compile(r"^controllers\.[a-z0-9-]+\.max-concurrent-reconciles$"),
    re.compile(
        r"^scheduling\.queue\.[a-z0-9-]+\."
        r"(max-concurrent|priority-aging|accelerator|chip-budget)$"
    ),
)


@dataclasses.dataclass
class ConfigKey:
    key: str  #: dotted key, e.g. "fleet.preemption-retry-cap"
    group: str  #: "fleet" for grouped keys, "" for top-level OperatorConfig
    attr: str  #: dataclass attribute the setter writes
    line: int


@dataclasses.dataclass
class ConfigRegistry:
    keys: dict[str, ConfigKey]
    #: dataclass name -> set of field names (from operator.py)
    dataclass_fields: dict[str, set[str]]
    #: OperatorConfig group field name -> dataclass name
    group_classes: dict[str, str]

    def known_groups(self) -> set[str]:
        return {k.split(".")[0] for k in self.keys if "." in k}

    def is_registered(self, key: str) -> bool:
        if key in self.keys:
            return True
        return any(f.match(key) for f in DYNAMIC_CONFIG_FAMILIES)


def _lambda_fset_target(lam: ast.Lambda) -> Optional[tuple[str, str]]:
    """A table entry ``lambda: fset(cfg.fleet, "attr", conv)`` ->
    ("fleet", "attr"); ``lambda: fset(cfg, "attr", conv)`` -> ("", attr)."""
    body = lam.body
    if not (isinstance(body, ast.Call) and isinstance(body.func, ast.Name)):
        return None
    if body.func.id != "fset" or len(body.args) < 2:
        return None
    obj, attr_node = body.args[0], body.args[1]
    if not (isinstance(attr_node, ast.Constant) and isinstance(attr_node.value, str)):
        return None
    chain = attr_chain(obj)
    if chain == ["cfg"]:
        return "", attr_node.value
    if chain and len(chain) == 2 and chain[0] == "cfg":
        return chain[1], attr_node.value
    return None


def config_registry(ctx: AnalysisContext) -> Optional[ConfigRegistry]:
    def build() -> Optional[ConfigRegistry]:
        pf = ctx.file(CONFIG_MODULE)
        if pf is None:
            return None
        keys: dict[str, ConfigKey] = {}
        dataclass_fields: dict[str, set[str]] = {}
        group_classes: dict[str, str] = {}
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                is_dc = any(
                    (isinstance(d, ast.Attribute) and d.attr == "dataclass")
                    or (isinstance(d, ast.Name) and d.id == "dataclass")
                    or (
                        isinstance(d, ast.Call)
                        and (attr_chain(d.func) or [""])[-1] == "dataclass"
                    )
                    for d in node.decorator_list
                )
                if not is_dc:
                    continue
                fields = {
                    s.target.id
                    for s in node.body
                    if isinstance(s, ast.AnnAssign) and isinstance(s.target, ast.Name)
                }
                dataclass_fields[node.name] = fields
                if node.name == "OperatorConfig":
                    for s in node.body:
                        if isinstance(s, ast.AnnAssign) and isinstance(s.target, ast.Name):
                            ann = s.annotation
                            if isinstance(ann, ast.Name):
                                group_classes[s.target.id] = ann.id
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            if (
                target is not None
                and isinstance(target, ast.Name)
                and target.id == "table"
                and isinstance(node.value, ast.Dict)
            ):
                for k, v in zip(node.value.keys, node.value.values):
                    if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                        continue
                    target = (
                        _lambda_fset_target(v) if isinstance(v, ast.Lambda) else None
                    )
                    group, attr = target if target else ("?", "?")
                    keys[k.value] = ConfigKey(
                        key=k.value, group=group, attr=attr, line=k.lineno
                    )
        if not keys:
            return None
        return ConfigRegistry(
            keys=keys,
            dataclass_fields=dataclass_fields,
            group_classes=group_classes,
        )

    return ctx.memo("config_registry", build)


@dataclasses.dataclass
class MetricsRegistryFacts:
    #: _ControlPlaneMetrics attribute -> registered family name
    attr_names: dict[str, str]
    #: family name -> registration line in metrics.py
    name_lines: dict[str, int]
    #: duplicate registrations: (name, line)
    duplicates: list[tuple[str, int]]


def metrics_registry(ctx: AnalysisContext) -> Optional[MetricsRegistryFacts]:
    def build() -> Optional[MetricsRegistryFacts]:
        pf = ctx.file(METRICS_MODULE)
        if pf is None:
            return None
        attr_names: dict[str, str] = {}
        name_lines: dict[str, int] = {}
        duplicates: list[tuple[str, int]] = []
        cpm = next(
            (
                n
                for n in ast.walk(pf.tree)
                if isinstance(n, ast.ClassDef) and n.name == "_ControlPlaneMetrics"
            ),
            None,
        )
        if cpm is None:
            return None
        for node in ast.walk(cpm):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            chain = attr_chain(tgt)
            if not (chain and chain[0] == "self" and len(chain) == 2):
                continue
            call = node.value
            if not (isinstance(call, ast.Call) and call.args):
                continue
            first = call.args[0]
            if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                continue
            name = first.value
            attr_names[chain[1]] = name
            if name in name_lines:
                duplicates.append((name, node.lineno))
            else:
                name_lines[name] = node.lineno
        return MetricsRegistryFacts(
            attr_names=attr_names, name_lines=name_lines, duplicates=duplicates
        )

    return ctx.memo("metrics_registry", build)


@dataclasses.dataclass
class EnumVocabulary:
    #: enum class name -> {string value -> member name}
    families: dict[str, dict[str, str]]
    #: condition type constants (READY = "Ready" ...)
    condition_types: dict[str, str]  # value -> constant name
    #: Reason codes (Reason.X values)
    reasons: dict[str, str]


def enum_vocabulary(ctx: AnalysisContext) -> Optional[EnumVocabulary]:
    def build() -> Optional[EnumVocabulary]:
        pf = ctx.file(ENUMS_MODULE)
        if pf is None:
            return None
        families: dict[str, dict[str, str]] = {}
        for node in pf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            bases = {b.id for b in node.bases if isinstance(b, ast.Name)}
            if "StrEnum" not in bases:
                continue
            values: dict[str, str] = {}
            for s in node.body:
                if (
                    isinstance(s, ast.Assign)
                    and len(s.targets) == 1
                    and isinstance(s.targets[0], ast.Name)
                    and isinstance(s.value, ast.Constant)
                    and isinstance(s.value.value, str)
                ):
                    values[s.value.value] = s.targets[0].id
            if values:
                families[node.name] = values
        condition_types: dict[str, str] = {}
        reasons: dict[str, str] = {}
        pc = ctx.file(CONDITIONS_MODULE)
        if pc is not None:
            for node in pc.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.isupper()
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    condition_types[node.value.value] = node.targets[0].id
                if isinstance(node, ast.ClassDef) and node.name == "Reason":
                    for s in node.body:
                        if (
                            isinstance(s, ast.Assign)
                            and len(s.targets) == 1
                            and isinstance(s.targets[0], ast.Name)
                            and isinstance(s.value, ast.Constant)
                            and isinstance(s.value.value, str)
                        ):
                            reasons[s.value.value] = s.targets[0].id
        if not families:
            return None
        return EnumVocabulary(
            families=families, condition_types=condition_types, reasons=reasons
        )

    return ctx.memo("enum_vocabulary", build)
