"""Happens-before machinery for the data-race sanitizer (racedetect).

Pure data structures, no threads and no patching — so the race logic is
unit-testable without spawning a single thread (tests/test_racedetect.py
drives it with hand-built clocks):

- :class:`VectorClock` — tid -> counter maps with ``join`` / ``advance``
  (Lamport/Mattern vector time over the sanitizer's synthetic thread
  ids, NOT OS idents — idents are recycled by the OS, synthetic tids
  never are, so a dead thread's epochs cannot be confused with a new
  thread's).
- epochs — FastTrack's ``(tid, clock)`` pairs (Flanagan & Freund,
  "FastTrack: Efficient and Precise Dynamic Race Detection"): the last
  write to a variable is one epoch, not a whole vector, because a
  race-free history needs only the MOST RECENT write ordered before the
  current access.
- :class:`VarState` — the per-variable detector state machine: a write
  epoch, a read vector (FastTrack's promoted read state, kept simple as
  a per-tid dict), and an Eraser-style candidate lockset (Savage et
  al., "Eraser: A Dynamic Data Race Detector for Multithreaded
  Programs"). An access pair is a race iff it is conflicting (at least
  one write), unordered by the pure-sync happens-before clocks, AND the
  two accesses share no common lock.

The hybrid detection rule (lockset AND clocks, like ThreadSanitizer
v1's hybrid mode) is deliberate: building HB edges out of every mutex
release->acquire (pure FastTrack) makes detection timing-dependent —
ambient lock traffic between two racy accesses accidentally orders
them and the race is only caught 1-run-in-N. With the lockset clause
carrying mutex reasoning, a consistently-locked variable never reports
regardless of timing, and an unlocked access pair reports whenever the
two threads both touch it, ordered or not — unless a real fork/join /
Future / Condition / queue handoff ordered them.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

#: a FastTrack epoch: (synthetic tid, that thread's clock at the access)
Epoch = tuple[int, int]


class VectorClock(dict):
    """tid -> counter. A plain dict subclass: missing tids read as 0."""

    __slots__ = ()

    def time_of(self, tid: int) -> int:
        return self.get(tid, 0)

    def advance(self, tid: int) -> None:
        self[tid] = self.get(tid, 0) + 1

    def join(self, other: Optional[dict]) -> None:
        """Pointwise max, in place. ``None`` joins as the zero clock."""
        if not other:
            return
        for tid, c in other.items():
            if c > self.get(tid, 0):
                self[tid] = c

    def snapshot(self) -> dict:
        """Immutable-by-convention copy for publishing into shared maps
        (lock-release clocks, condition clocks, queue clocks). Publishers
        never mutate a snapshot after handing it out."""
        return dict(self)

    def leq(self, other: dict) -> bool:
        return all(c <= other.get(tid, 0) for tid, c in self.items())


def epoch_leq(epoch: Optional[Epoch], vc: dict) -> bool:
    """``e ⊑ VC`` — the access the epoch stamps happened-before a thread
    whose clock is ``vc``. A missing epoch (no prior access) is ⊑ all."""
    if epoch is None:
        return True
    tid, c = epoch
    return c <= vc.get(tid, 0)


@dataclasses.dataclass
class AccessCheck:
    """Outcome of one :meth:`VarState.on_access`.

    ``conflicts`` holds the caller-supplied tokens (access records) of
    every prior conflicting access NOT ordered before the current one by
    the sync-only happens-before relation. ``common_locks`` is the
    non-empty lock intersection that excused those conflicts, if any —
    so ``conflicts and not common_locks`` is the race condition, and a
    suppressed pair still surfaces in the variable's lockset history.
    """

    conflicts: list
    common_locks: frozenset

    @property
    def is_race(self) -> bool:
        return bool(self.conflicts) and not self.common_locks


class VarState:
    """FastTrack-style last-access state + Eraser candidate lockset for
    ONE shared variable. Callers pass an opaque ``token`` per access
    (racedetect passes a stack/thread/lockset record) that comes back in
    :class:`AccessCheck.conflicts` for reporting."""

    __slots__ = ("write_epoch", "write_token", "read_epochs", "read_tokens",
                 "lockset")

    def __init__(self) -> None:
        self.write_epoch: Optional[Epoch] = None
        self.write_token: Any = None
        #: tid -> clock of that thread's last read since the last write
        self.read_epochs: dict[int, int] = {}
        self.read_tokens: dict[int, Any] = {}
        #: Eraser candidate lockset: locks held on EVERY access of the
        #: current concurrent phase; None = virgin (no access yet)
        self.lockset: Optional[frozenset] = None

    def on_access(
        self,
        tid: int,
        vc: dict,
        lockset: frozenset,
        is_write: bool,
        token: Any = None,
    ) -> AccessCheck:
        conflicts: list = []
        if not epoch_leq(self.write_epoch, vc):
            conflicts.append(self.write_token)
        if is_write:
            for rt, rc in self.read_epochs.items():
                if rt != tid and rc > vc.get(rt, 0):
                    conflicts.append(self.read_tokens[rt])

        common: frozenset = frozenset()
        if conflicts:
            # unordered conflicting accesses: Eraser refinement decides
            refined = (self.lockset if self.lockset is not None
                       else lockset) & lockset
            self.lockset = refined
            common = refined
        else:
            # every prior conflicting access happens-before this one (or
            # there was none): a new exclusive phase begins — re-arm the
            # candidate lockset so a clean handoff chain (fork/join,
            # future, queue) doesn't inherit a drained lockset from the
            # previous owner's unlocked accesses.
            self.lockset = frozenset(lockset)

        # FastTrack state update
        if is_write:
            self.write_epoch = (tid, vc.get(tid, 0))
            self.write_token = token
            self.read_epochs.clear()
            self.read_tokens.clear()
        else:
            self.read_epochs[tid] = vc.get(tid, 0)
            self.read_tokens[tid] = token
        return AccessCheck(conflicts=conflicts, common_locks=common)
