"""Checker registry: one module per repo-native invariant."""

from .config_key_drift import ConfigKeyDriftChecker
from .cow_discipline import CowDisciplineChecker
from .enum_literal_drift import EnumLiteralDriftChecker
from .lock_blocking_io import LockBlockingIOChecker
from .metrics_drift import MetricsDriftChecker
from .serving_sync_points import ServingSyncPointsChecker
from .shared_state_discipline import SharedStateDisciplineChecker

ALL_CHECKERS = (
    LockBlockingIOChecker(),
    CowDisciplineChecker(),
    ConfigKeyDriftChecker(),
    MetricsDriftChecker(),
    EnumLiteralDriftChecker(),
    ServingSyncPointsChecker(),
    SharedStateDisciplineChecker(),
)

__all__ = ["ALL_CHECKERS"]
