"""config-key-drift: dotted config keys must exist, work, and be used.

The operator config travels as flat dotted keys through a ConfigMap
(``config/operator.py:_apply_dotted``); nothing but convention ties a
literal like ``"fleet.preemption-retry-cap"`` in a test, a doc table or
a chart default to the setter table. Four drift modes, all mechanical:

1. **unknown literal** — a dotted-key string literal used as a dict
   key or as the first argument of a ``.get(...)`` call, whose first
   segment is a known config group but which is neither in the table
   nor a dynamic family (``controllers.<name>.max-concurrent-
   reconciles``, ``scheduling.queue.<name>.*``): it would be silently
   ignored at parse time. Only those two positions are scanned — a
   dotted string elsewhere (a span name, an id) is not a config key;
2. **broken setter** — a table entry whose ``fset`` writes an attribute
   that does not exist on the target dataclass (a field rename that
   missed the table: the key parses, sets a ghost attribute, and the
   consumer keeps reading the stale default);
3. **dead key** — a registered key whose dataclass attribute is never
   read anywhere outside ``config/``: registered but not consumed, so a
   reload can never take effect;
4. **doc drift** — a backticked dotted key in ``docs/*.md`` / README
   with a known group prefix that is not registered.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Sequence

from ..context import config_registry
from ..core import AnalysisContext, Finding, ProjectFile

_KEY_RE = re.compile(r"^[a-z][a-z0-9-]*(\.[a-z0-9-]+)+$")
_DOC_KEY_RE = re.compile(r"`([a-z][a-z0-9-]*(?:\.[a-z0-9-]+)+)`")
_DOC_FILES = ("README.md", "docs/SCALING.md", "docs/FLEET.md", "docs/TRAINING.md",
              "docs/STREAMING.md", "docs/SERVING.md", "docs/KUBECTL.md",
              "docs/ANALYSIS.md", "docs/OBSERVABILITY.md", "docs/STORAGE.md",
              "docs/TRAFFIC.md")


class ConfigKeyDriftChecker:
    name = "config-key-drift"
    description = "dotted config-key literals vs the registered setter table"

    def run(
        self, files: Sequence[ProjectFile], ctx: AnalysisContext
    ) -> Iterable[Finding]:
        reg = config_registry(ctx)
        if reg is None:
            return []
        out: list[Finding] = []
        groups = reg.known_groups()

        # (1) unknown dotted literals in code
        for pf in files:
            if pf.rel == "bobrapet_tpu/config/operator.py":
                continue
            scope_stack: list[str] = []
            self._scan_literals(pf, pf.tree, scope_stack, groups, reg, out)

        # (2) broken setters + collect attr reads for (3)
        attr_reads: set[str] = set()
        for pf in files:
            # the registry itself doesn't count as a consumer, but the
            # resolver chain (config/resolver.py) does
            if pf.rel == "bobrapet_tpu/config/operator.py":
                continue
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.Attribute):
                    attr_reads.add(node.attr)
        for key in sorted(reg.keys):
            ck = reg.keys[key]
            if ck.group == "?":
                continue
            cls = (
                "OperatorConfig"
                if ck.group == ""
                else reg.group_classes.get(ck.group, "")
            )
            fields = reg.dataclass_fields.get(cls)
            if fields is not None and ck.attr not in fields:
                out.append(
                    Finding(
                        checker=self.name,
                        path="bobrapet_tpu/config/operator.py",
                        line=ck.line,
                        col=0,
                        scope="_apply_dotted",
                        message=(
                            f"config key {key!r} sets attribute "
                            f"{ck.attr!r} which does not exist on {cls} — "
                            f"the key parses but writes a ghost attribute"
                        ),
                        kernel=f"ghost attribute {cls}.{ck.attr} for {key}",
                    )
                )
            elif ck.attr not in attr_reads:
                # (3) dead key: attribute never read outside config/
                out.append(
                    Finding(
                        checker=self.name,
                        path="bobrapet_tpu/config/operator.py",
                        line=ck.line,
                        col=0,
                        scope="_apply_dotted",
                        message=(
                            f"config key {key!r} is registered but its "
                            f"attribute {ck.attr!r} is never read outside "
                            f"the registry — a reload can never take effect"
                        ),
                        kernel=f"dead config key {key}",
                    )
                )

        # (4) documented keys must be registered
        for rel in _DOC_FILES:
            path = os.path.join(ctx.root, rel)
            if not os.path.exists(path):
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            for lineno, line in enumerate(text.splitlines(), 1):
                for m in _DOC_KEY_RE.finditer(line):
                    key = m.group(1)
                    if key.split(".")[0] not in groups:
                        continue
                    if not reg.is_registered(key):
                        out.append(
                            Finding(
                                checker=self.name,
                                path=rel,
                                line=lineno,
                                col=m.start(1),
                                scope="",
                                message=(
                                    f"documented config key {key!r} is not "
                                    f"registered in config/operator.py"
                                ),
                                kernel=f"documented-but-unregistered {key}",
                            )
                        )
        return out

    def _scan_literals(
        self,
        pf: ProjectFile,
        node: ast.AST,
        scope_stack: list[str],
        groups: set[str],
        reg,
        out: list[Finding],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                scope_stack.append(child.name)
                self._scan_literals(pf, child, scope_stack, groups, reg, out)
                scope_stack.pop()
                continue
            candidates: list[ast.Constant] = []
            if isinstance(child, ast.Dict):
                candidates = [
                    k for k in child.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                ]
            elif (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr == "get"
                and child.args
                and isinstance(child.args[0], ast.Constant)
                and isinstance(child.args[0].value, str)
            ):
                candidates = [child.args[0]]
            for lit in candidates:
                if (
                    _KEY_RE.match(lit.value)
                    and lit.value.split(".")[0] in groups
                    and not reg.is_registered(lit.value)
                ):
                    out.append(
                        Finding(
                            checker=self.name,
                            path=pf.rel,
                            line=lit.lineno,
                            col=lit.col_offset,
                            scope=".".join(scope_stack),
                            message=(
                                f"config key literal {lit.value!r} is not "
                                f"registered in config/operator.py — it "
                                f"would be silently ignored at parse time"
                            ),
                            kernel=f"unregistered key literal {lit.value}",
                        )
                    )
            self._scan_literals(pf, child, scope_stack, groups, reg, out)
