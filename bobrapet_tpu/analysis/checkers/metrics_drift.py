"""metrics-drift: emitted metric families vs the registered inventory.

``observability/metrics.py:_ControlPlaneMetrics`` is the single
inventory of Prometheus families (the reference keeps the same shape in
pkg/metrics). Drift modes:

1. **unknown attribute** — ``metrics.<attr>...`` emission for an attr
   not defined in ``_ControlPlaneMetrics`` (raises ``AttributeError``
   only when that code path actually runs — typically in production,
   not in tests);
2. **bad prefix** — a registered family whose name does not carry the
   ``bobrapet_`` / ``bobravoz_`` namespace;
3. **duplicate family** — two registrations with the same name (the
   registry silently returns the first, so the second's help/labels
   are dead);
4. **rogue registration** — a ``REGISTRY.counter/gauge/histogram`` call
   outside ``observability/metrics.py`` with an unprefixed name
   literal (ad-hoc families bypass the inventory; they may, but must
   stay in the namespace).
"""

from __future__ import annotations

import ast
from typing import Iterable, Sequence

from ..context import METRICS_MODULE, metrics_registry
from ..core import AnalysisContext, Finding, ProjectFile, attr_chain

_PREFIXES = ("bobrapet_", "bobravoz_")
_FACTORY_METHODS = {"counter", "gauge", "histogram"}


class MetricsDriftChecker:
    name = "metrics-drift"
    description = "emitted metric families vs observability/metrics.py registry"

    def run(
        self, files: Sequence[ProjectFile], ctx: AnalysisContext
    ) -> Iterable[Finding]:
        facts = metrics_registry(ctx)
        if facts is None:
            return []
        out: list[Finding] = []

        # (2) + (3): registry hygiene
        for attr, mname in sorted(facts.attr_names.items()):
            if not mname.startswith(_PREFIXES):
                out.append(
                    Finding(
                        checker=self.name,
                        path=METRICS_MODULE,
                        line=facts.name_lines.get(mname, 0),
                        col=0,
                        scope="_ControlPlaneMetrics",
                        message=(
                            f"metric family {mname!r} (attr {attr!r}) lacks "
                            f"the bobrapet_/bobravoz_ namespace prefix"
                        ),
                        kernel=f"unprefixed family {mname}",
                    )
                )
        for mname, line in facts.duplicates:
            out.append(
                Finding(
                    checker=self.name,
                    path=METRICS_MODULE,
                    line=line,
                    col=0,
                    scope="_ControlPlaneMetrics",
                    message=(
                        f"metric family {mname!r} registered twice — the "
                        f"registry keeps the first, the second is dead"
                    ),
                    kernel=f"duplicate family {mname}",
                )
            )

        known_attrs = set(facts.attr_names)
        for pf in files:
            if pf.rel == METRICS_MODULE:
                continue
            scope: list[str] = []
            self._scan(pf, pf.tree, scope, known_attrs, out)
        return out

    def _scan(
        self,
        pf: ProjectFile,
        node: ast.AST,
        scope: list[str],
        known_attrs: set[str],
        out: list[Finding],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                scope.append(child.name)
                self._scan(pf, child, scope, known_attrs, out)
                scope.pop()
                continue
            # (1) metrics.<attr>.<method>(...) emissions
            if isinstance(child, ast.Attribute):
                chain = attr_chain(child)
                if (
                    chain
                    and len(chain) >= 2
                    and chain[0] == "metrics"
                    and chain[1] not in known_attrs
                    # plain module access like metrics.REGISTRY is fine
                    and not chain[1].isupper()
                    and chain[1] != "metrics"  # observability.metrics.metrics
                ):
                    out.append(
                        Finding(
                            checker=self.name,
                            path=pf.rel,
                            line=child.lineno,
                            col=child.col_offset,
                            scope=".".join(scope),
                            message=(
                                f"metrics.{chain[1]} is not a family "
                                f"registered in _ControlPlaneMetrics — "
                                f"emission would raise AttributeError at "
                                f"runtime"
                            ),
                            kernel=f"unregistered emission {chain[1]}",
                        )
                    )
                    continue
            # (4) rogue REGISTRY.counter("name"...) outside metrics.py
            if isinstance(child, ast.Call):
                chain = attr_chain(child.func)
                if (
                    chain
                    and len(chain) >= 2
                    and chain[-2] == "REGISTRY"
                    and chain[-1] in _FACTORY_METHODS
                    and child.args
                    and isinstance(child.args[0], ast.Constant)
                    and isinstance(child.args[0].value, str)
                    and not child.args[0].value.startswith(_PREFIXES)
                ):
                    out.append(
                        Finding(
                            checker=self.name,
                            path=pf.rel,
                            line=child.lineno,
                            col=child.col_offset,
                            scope=".".join(scope),
                            message=(
                                f"ad-hoc metric {child.args[0].value!r} "
                                f"registered outside the inventory without "
                                f"the bobrapet_/bobravoz_ prefix"
                            ),
                            kernel=f"rogue unprefixed {child.args[0].value}",
                        )
                    )
            self._scan(pf, child, scope, known_attrs, out)
        return
