"""shared-state-discipline: lock-owning classes mutate shared
containers only under their lock; @guarded_state declarations match.

The static half of the bobrarace data-race sanitizer
(:mod:`..racedetect`). Two coupled invariants:

1. **lock discipline** — in any class that stores a
   ``threading.Lock``/``RLock``/``Condition`` on ``self``, every
   mutation of a container attribute initialized in ``__init__``
   (``self.x[...] = / del / .append / .add / .update / += ...``) must
   be lexically inside a ``with self.<lock_attr>:`` block. ``__init__``
   itself is exempt (pre-publication, no concurrent reader exists yet).
   PR-4-style same-file interprocedural reasoning applies, as a fixed
   point over the class (the lock-blocking-io precedent): an unlocked
   mutating helper is fine if EVERY ``self.helper(...)`` call site in
   the class is lock-held or inside a method already proven
   locked-only (the ``_index_add_locked`` convention, transitively —
   ``_acquire_gang_locked`` -> ``_acquire_block_locked`` ->
   ``_commit_block_locked`` chains resolve). A helper's recursive call
   to itself inherits its own precondition, and a call site inside
   ``__init__`` counts as protected (pre-publication). A helper with
   no in-class call sites stays flagged, because nothing proves its
   callers lock.
2. **instrumentation drift** — a class decorated ``@guarded_state``
   must declare exactly the container attributes this checker
   discovers: a missing field means the runtime sanitizer silently
   skips shared state; an unknown field means the declaration rotted.
   ``discover_guarded`` is exported so tests/test_racedetect.py can
   assert the runtime registry equals this discovery on the real tree
   — the static view and the instrumentation cannot drift apart.

Known static limits (the RUNTIME sanitizer covers these): cross-object
mutations (``self.router.parked.add(...)`` from another class), calls
that mutate through an argument (``heapq.heappush(self._timers, ...)``),
and aliasing through locals. Subscripts are transparent in receiver
chains, so ``self._buckets[k].discard(...)`` counts as a mutation of
``_buckets`` — inner containers inherit the outer discipline.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional, Sequence

from ..core import AnalysisContext, Finding, ProjectFile, attr_chain, terminal_name

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_CONTAINER_FACTORIES = {
    "dict", "list", "set", "frozenset", "deque", "defaultdict",
    "OrderedDict", "Counter",
}
_CONTAINER_NODES = (
    ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp, ast.SetComp,
)
_MUTATORS = {
    # dict
    "pop", "popitem", "clear", "update", "setdefault",
    # list / deque
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "sort", "reverse", "rotate", "popleft",
    # set
    "add", "discard", "difference_update", "intersection_update",
    "symmetric_difference_update",
}


@dataclasses.dataclass
class ClassInfo:
    name: str
    line: int
    lock_attrs: set  #: attr names holding Lock/RLock/Condition
    containers: dict  #: attr name -> __init__ assignment line
    declared: Optional[tuple]  #: @guarded_state fields, None if undecorated


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` (subscripts transparent) -> "x", else None."""
    chain = attr_chain(node)
    if chain and len(chain) == 2 and chain[0] == "self":
        return chain[1]
    return None


def _guarded_decorator_fields(cls: ast.ClassDef) -> Optional[tuple]:
    for deco in cls.decorator_list:
        if isinstance(deco, ast.Call) and \
                terminal_name(deco.func) == "guarded_state":
            fields = []
            for arg in deco.args:
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    fields.append(arg.value)
            return tuple(fields)
    return None


def class_info(cls: ast.ClassDef) -> ClassInfo:
    info = ClassInfo(name=cls.name, line=cls.lineno, lock_attrs=set(),
                     containers={}, declared=_guarded_decorator_fields(cls))
    init = next(
        (n for n in cls.body
         if isinstance(n, ast.FunctionDef) and n.name == "__init__"),
        None,
    )
    if init is None:
        return info
    for node in ast.walk(init):
        if isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        elif isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        else:
            continue
        if value is None:
            continue
        for target in targets:
            if not isinstance(target, ast.Attribute):
                continue
            attr = _self_attr(target)
            if attr is None:
                continue
            if isinstance(value, ast.Call) and \
                    terminal_name(value.func) in _LOCK_FACTORIES:
                info.lock_attrs.add(attr)
            elif isinstance(value, _CONTAINER_NODES) or (
                isinstance(value, ast.Call)
                and terminal_name(value.func) in _CONTAINER_FACTORIES
            ):
                info.containers[attr] = node.lineno
    return info


def discover_guarded(files: Sequence[ProjectFile]) -> dict:
    """(rel_path, class name) -> ClassInfo for every @guarded_state
    class — the registry the runtime sanitizer must match."""
    out = {}
    for pf in files:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                info = class_info(node)
                if info.declared is not None:
                    out[(pf.rel, node.name)] = info
    return out


@dataclasses.dataclass
class _Mutation:
    attr: str
    line: int
    col: int
    method: str
    locked: bool


class _MethodScan(ast.NodeVisitor):
    """Collect container mutations + self-method call sites within one
    method body, tracking lexical ``with self.<lock>`` nesting. Nested
    function definitions reset the locked flag: a closure built under a
    lock may run long after the lock is gone."""

    def __init__(self, info: ClassInfo, method: str):
        self.info = info
        self.method = method
        self.locked = 0
        self.mutations: list[_Mutation] = []
        #: called method name -> [locked?] per call site
        self.calls: dict[str, list[bool]] = {}

    def _note(self, node: ast.AST, attr: Optional[str]) -> None:
        if attr is not None and attr in self.info.containers:
            self.mutations.append(_Mutation(
                attr=attr, line=node.lineno, col=node.col_offset,
                method=self.method, locked=self.locked > 0,
            ))

    def visit_With(self, node: ast.With) -> None:
        guards = 0
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in self.info.lock_attrs:
                guards += 1
        self.locked += guards
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        self.locked -= guards

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                self._note(node, _self_attr(target.value))
            elif isinstance(target, ast.Attribute):
                self._note(node, _self_attr(target))
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            if isinstance(node.target, ast.Subscript):
                self._note(node, _self_attr(node.target.value))
            elif isinstance(node.target, ast.Attribute):
                self._note(node, _self_attr(node.target))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Subscript):
            self._note(node, _self_attr(node.target.value))
        elif isinstance(node.target, ast.Attribute):
            self._note(node, _self_attr(node.target))
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                self._note(node, _self_attr(target.value))
            elif isinstance(target, ast.Attribute):
                self._note(node, _self_attr(target))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            chain = attr_chain(node.func)
            if chain and chain[0] == "self":
                if len(chain) == 2:
                    # self.helper(...) — interprocedural call site
                    self.calls.setdefault(chain[1], []).append(
                        self.locked > 0
                    )
                if len(chain) >= 3 and node.func.attr in _MUTATORS:
                    # self.x.append(...) / self.x[k].discard(...)
                    self._note(node, chain[1] if chain[1] in
                               self.info.containers else None)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved, self.locked = self.locked, 0
        self.generic_visit(node)
        self.locked = saved

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved, self.locked = self.locked, 0
        self.generic_visit(node)
        self.locked = saved


class SharedStateDisciplineChecker:
    name = "shared-state-discipline"
    description = (
        "lock-owning classes must mutate shared containers under their "
        "lock; @guarded_state declarations must match discovered state"
    )

    def run(
        self, files: Sequence[ProjectFile], ctx: AnalysisContext
    ) -> Iterable[Finding]:
        for pf in files:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(pf, node)

    def _check_class(
        self, pf: ProjectFile, cls: ast.ClassDef
    ) -> Iterable[Finding]:
        info = class_info(cls)
        if info.declared is not None:
            yield from self._check_drift(pf, cls, info)
        if not info.lock_attrs or not info.containers:
            return

        scans: list[_MethodScan] = []
        #: callee -> [(caller method, call site lexically locked?)]
        calls: dict[str, list[tuple[str, bool]]] = {}
        for item in cls.body:
            if not isinstance(item, ast.FunctionDef):
                continue
            scan = _MethodScan(info, item.name)
            for stmt in item.body:
                scan.visit(stmt)
            if item.name != "__init__":
                # __init__ mutations are exempt (pre-publication), but its
                # call sites still feed the proof below — also as
                # protected, for the same reason.
                scans.append(scan)
            for callee, sites in scan.calls.items():
                for locked in sites:
                    calls.setdefault(callee, []).append(
                        (item.name, locked or item.name == "__init__")
                    )

        # Least fixed point of "locked-only" methods: M qualifies iff it
        # has in-class call sites and every one is lexically lock-held,
        # inside an already locked-only method, or a self-recursive call
        # (which inherits M's own precondition). Starting empty and only
        # adding is what makes a mutually-recursive cycle with no locked
        # entry point stay flagged.
        locked_only: set[str] = set()
        changed = True
        while changed:
            changed = False
            for scan in scans:
                m = scan.method
                if m in locked_only:
                    continue
                sites = calls.get(m)
                if sites and all(
                    locked or caller in locked_only or caller == m
                    for caller, locked in sites
                ):
                    locked_only.add(m)
                    changed = True

        locks = ", ".join(sorted(info.lock_attrs))
        for scan in scans:
            unprotected = [m for m in scan.mutations if not m.locked]
            if not unprotected:
                continue
            if scan.method in locked_only:
                # every in-class call chain reaching this helper holds the
                # lock: a *_locked-style extraction, not an escape
                continue
            for m in unprotected:
                yield Finding(
                    checker=self.name,
                    path=pf.rel,
                    line=m.line,
                    col=m.col,
                    scope=f"{cls.name}.{m.method}",
                    message=(
                        f"mutation of shared container self.{m.attr} "
                        f"outside any 'with self.<lock>' block (class "
                        f"owns {locks}); runtime-verify with bobrarace "
                        f"or move under the lock"
                    ),
                    kernel=f"{m.attr} mutated unlocked",
                )

    def _check_drift(
        self, pf: ProjectFile, cls: ast.ClassDef, info: ClassInfo
    ) -> Iterable[Finding]:
        declared = set(info.declared or ())
        discovered = set(info.containers)
        for attr in sorted(discovered - declared):
            yield Finding(
                checker=self.name,
                path=pf.rel,
                line=info.containers[attr],
                col=0,
                scope=cls.name,
                message=(
                    f"@guarded_state on {cls.name} omits container "
                    f"attribute self.{attr} — the race sanitizer will "
                    f"not track it; declare it or it drifts"
                ),
                kernel=f"{attr} undeclared in guarded_state",
            )
        for attr in sorted(declared - discovered):
            yield Finding(
                checker=self.name,
                path=pf.rel,
                line=cls.lineno,
                col=0,
                scope=cls.name,
                message=(
                    f"@guarded_state on {cls.name} declares {attr!r} "
                    f"but __init__ assigns no such container attribute"
                ),
                kernel=f"{attr} unknown in guarded_state",
            )
