"""cow-discipline: never mutate a shared copy-on-write view in place.

PR 1's copy-on-write reads hand the COMMITTED object out directly:
``store.get_view`` / ``try_get_view`` / ``list_views`` results, watch
event ``.resource`` payloads, and ``cached_parse`` returns are all
shared process-wide. One in-place mutation poisons every other holder —
the exact bug class the ``BOBRA_PARSE_CACHE_DEBUG`` trap catches at
runtime; this checker catches it at review time.

Intraprocedural taint per function:

- ``x = store.get_view(...)`` / ``try_get_view`` / ``cached_parse``
  taints ``x``;
- ``for v in store.list_views(...)`` (or iterating a name assigned from
  it) taints ``v``;
- ``sr = ev.resource`` in a watch handler taints ``sr`` (drain shares
  the committed object with every handler);

then any store into an attribute/subscript chain rooted at a tainted
name (``x.spec["k"] = ...``, ``x.status.update(...)``, ``del x.meta...``)
or a mutating method call on such a chain is flagged. Rebinding the
name clears the taint; chains broken by an intermediate call (e.g.
``x.deepcopy().spec[...] = ...``) are NOT flagged — a call result is a
fresh object.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from ..core import AnalysisContext, Finding, ProjectFile, attr_chain

#: callables whose result is a shared view
_VIEW_SOURCES = {"get_view", "try_get_view", "cached_parse"}
_LIST_VIEW_SOURCES = {"list_views"}

#: methods that mutate their receiver in place
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "sort", "reverse", "add", "discard",
}

def _call_terminal(call: ast.Call) -> Optional[str]:
    chain = attr_chain(call.func)
    return chain[-1] if chain else None


class _FunctionScanner(ast.NodeVisitor):
    """One instance per function body; nested defs get their own."""

    def __init__(self, pf: ProjectFile, scope: str):
        self.pf = pf
        self.scope = scope
        self.findings: list[Finding] = []
        self.tainted: dict[str, str] = {}  # name -> origin description
        self.list_names: dict[str, str] = {}  # names holding list_views results

    # -- taint sources -----------------------------------------------------

    def _origin_of_call(self, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        term = _call_terminal(value)
        if term in _VIEW_SOURCES:
            return term
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            self._check_store(node.targets[0], node)
            return
        name = node.targets[0].id
        origin = self._origin_of_call(node.value)
        if origin is not None:
            self.tainted[name] = origin
            self.list_names.pop(name, None)
            return
        if (
            isinstance(node.value, ast.Call)
            and _call_terminal(node.value) in _LIST_VIEW_SOURCES
        ):
            self.list_names[name] = "list_views"
            self.tainted.pop(name, None)
            return
        # ``sr = ev.resource``: watch handlers share the committed object
        chain = attr_chain(node.value)
        if chain and len(chain) == 2 and chain[1] == "resource" and chain[0] in ("ev", "event"):
            self.tainted[name] = "watch event .resource"
            return
        # ``alias = tainted`` propagates; anything else clears
        if isinstance(node.value, ast.Name) and node.value.id in self.tainted:
            self.tainted[name] = self.tainted[node.value.id]
        else:
            self.tainted.pop(name, None)
            self.list_names.pop(name, None)

    def visit_For(self, node: ast.For) -> None:
        if isinstance(node.target, ast.Name):
            it = node.iter
            if (
                isinstance(it, ast.Call)
                and _call_terminal(it) in _LIST_VIEW_SOURCES
            ) or (isinstance(it, ast.Name) and it.id in self.list_names):
                self.tainted[node.target.id] = "list_views"
        self.generic_visit(node)

    # -- nested scopes -----------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        sub = _FunctionScanner(self.pf, f"{self.scope}.{node.name}" if self.scope else node.name)
        for stmt in node.body:
            sub.visit(stmt)
        self.findings.extend(sub.findings)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        sub = _FunctionScanner(self.pf, f"{self.scope}.{node.name}" if self.scope else node.name)
        for stmt in node.body:
            sub.visit(stmt)
        self.findings.extend(sub.findings)

    # -- mutation sinks ----------------------------------------------------

    def _tainted_root(self, node: ast.AST) -> Optional[tuple[str, str]]:
        """If node is an Attribute/Subscript chain rooted at a tainted
        name (and deeper than the bare name), -> (name, origin)."""
        if not isinstance(node, (ast.Attribute, ast.Subscript)):
            return None
        chain = attr_chain(node)
        if chain is None or len(chain) < 1:
            return None
        root = chain[0]
        if root in self.tainted:
            return root, self.tainted[root]
        return None

    def _check_store(self, target: ast.AST, node: ast.AST) -> None:
        for t in ast.walk(target) if isinstance(target, (ast.Tuple, ast.List)) else [target]:
            hit = self._tainted_root(t)
            if hit is not None:
                name, origin = hit
                self._flag(node, name, origin, "assignment into")

    def _flag(self, node: ast.AST, name: str, origin: str, what: str) -> None:
        self.findings.append(
            Finding(
                checker="cow-discipline",
                path=self.pf.rel,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                scope=self.scope,
                message=(
                    f"{what} {name!r}, a shared view from {origin} — views "
                    f"are committed objects shared process-wide; deepcopy "
                    f"first or write through store.mutate()/update()"
                ),
                kernel=f"{what} view {name} from {origin}",
            )
        )

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        hit = self._tainted_root(node.target)
        if hit is not None:
            self._flag(node, hit[0], hit[1], "augmented assignment into")

    def visit_Delete(self, node: ast.Delete) -> None:
        self.generic_visit(node)
        for t in node.targets:
            hit = self._tainted_root(t)
            if hit is not None:
                self._flag(node, hit[0], hit[1], "del on")

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        func = node.func
        if not isinstance(func, ast.Attribute) or func.attr not in _MUTATORS:
            return
        # receiver chain must be Attribute/Subscript only down to a
        # tainted name: x.spec.update(...) flags, x.to_dict().update(...)
        # does not (attr_chain returns None through a Call)
        receiver = func.value
        chain = attr_chain(receiver)
        if chain is None:
            return
        root = chain[0]
        if root in self.tainted:
            # bare ``x.update(...)`` counts too: a parsed spec object
            # mutated directly is still a shared-parse mutation
            self._flag(node, root, self.tainted[root], f".{func.attr}() on")


class CowDisciplineChecker:
    name = "cow-discipline"
    description = "in-place mutation of shared copy-on-write views / cached parses"

    def run(
        self, files: Sequence[ProjectFile], ctx: AnalysisContext
    ) -> Iterable[Finding]:
        out: list[Finding] = []
        for pf in files:
            scanner = _FunctionScanner(pf, "")
            for stmt in pf.tree.body:
                scanner.visit(stmt)
            out.extend(scanner.findings)
        return out
