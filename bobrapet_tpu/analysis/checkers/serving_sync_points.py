"""serving-sync-points: no unannotated host syncs in serving hot paths.

The pipelined serving engine (PR 16) keeps the device fed by
dispatching horizons ahead of the host; its entire win evaporates if
any code on the dispatch/commit path forces an early device
round-trip. The three spellings that do:

- ``jax.device_get(...)`` — blocks until the value is resident on
  host;
- ``.block_until_ready()`` / ``jax.block_until_ready(...)`` — blocks
  until the computation completes;
- ``np.asarray(x)`` (any numpy alias) — silently performs a
  device->host transfer when ``x`` is a jax array, indistinguishable
  at the call site from a free host-side view.

Inside ``bobrapet_tpu/serving/`` every such call must either carry a
trailing ``# sync-point: <why>`` annotation on the call line (the
reviewed allowlist — the justification is part of the source, next to
the sync it excuses) or be suppressed in ``bobralint-baseline.json``
(the per-horizon commit syncs, which are the engine's ONE intended
round-trip per horizon). An annotation with an empty justification is
still flagged: "# sync-point:" with no reason is a TODO, not a
review.

``jnp.asarray`` is deliberately NOT matched — it produces a device
array (an upload, not a sync) and is the engine's standard patch
idiom. The checker is lexical about numpy aliases (``np``, ``_np``,
``numpy``): serving code imports numpy under those names only.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from ..core import AnalysisContext, Finding, ProjectFile, attr_chain

#: rel-path prefixes the invariant binds to: the serving package, plus
#: the pseudo-path test_analysis.py feeds corpus fixtures under
_DOMAIN_PREFIXES = (
    "bobrapet_tpu/serving/",
    "bobrapet_tpu/_corpus/serving_sync_points",
)

#: numpy module aliases (lowercased, underscores stripped)
_NUMPY_ALIASES = {"np", "numpy"}

_ANNOTATION = "# sync-point:"


def _classify(call: ast.Call) -> Optional[str]:
    """-> stable kernel for a host-sync call, or None."""
    chain = attr_chain(call.func)
    if chain is None:
        return None
    last = chain[-1]
    if last == "device_get":
        return "host sync jax.device_get"
    if last == "block_until_ready":
        return "host sync block_until_ready"
    if (
        last == "asarray"
        and len(chain) >= 2
        and chain[-2].lower().strip("_") in _NUMPY_ALIASES
    ):
        return "device->host copy np.asarray"
    return None


def _annotation_state(source_lines: list[str], lineno: int) -> Optional[bool]:
    """None = no annotation; True = justified; False = empty reason."""
    if not 1 <= lineno <= len(source_lines):
        return None
    line = source_lines[lineno - 1]
    idx = line.find(_ANNOTATION)
    if idx < 0:
        return None
    # the reason runs to the next comment marker (tooling tags like
    # the corpus' "# BAD" may trail the annotation) or end of line
    reason = line[idx + len(_ANNOTATION):]
    reason = reason.split("#", 1)[0]
    return bool(reason.strip())


class _Visitor(ast.NodeVisitor):
    def __init__(self, pf: ProjectFile):
        self.pf = pf
        self.lines = pf.source.splitlines()
        self.findings: list[Finding] = []
        self._scope: list[str] = []

    def _in_scope(self, name: str, node: ast.AST) -> None:
        self._scope.append(name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._in_scope(node.name, node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._in_scope(node.name, node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._in_scope(node.name, node)

    def visit_Call(self, node: ast.Call) -> None:
        kernel = _classify(node)
        if kernel is not None:
            ann = _annotation_state(self.lines, node.lineno)
            if ann is True:
                pass  # reviewed allowlist entry
            elif ann is False:
                self._flag(node, f"{kernel} (empty sync-point reason)",
                           "empty '# sync-point:' annotation — state WHY "
                           "this sync is acceptable on the hot path")
            else:
                self._flag(node, kernel,
                           "forces a device round-trip on a serving hot "
                           "path — move it to the commit boundary, or "
                           "annotate the line with '# sync-point: <why>' "
                           "if the sync is intended")
        self.generic_visit(node)

    def _flag(self, node: ast.Call, kernel: str, advice: str) -> None:
        self.findings.append(
            Finding(
                checker="serving-sync-points",
                path=self.pf.rel,
                line=node.lineno,
                col=node.col_offset,
                scope=".".join(self._scope),
                message=f"{kernel}: {advice}",
                kernel=kernel,
            )
        )


class ServingSyncPointsChecker:
    name = "serving-sync-points"
    description = (
        "unannotated host sync (device_get/block_until_ready/np.asarray) "
        "in the serving package"
    )

    def run(
        self, files: Sequence[ProjectFile], ctx: AnalysisContext
    ) -> Iterable[Finding]:
        out: list[Finding] = []
        for pf in files:
            if not pf.rel.startswith(_DOMAIN_PREFIXES):
                continue
            v = _Visitor(pf)
            v.visit(pf.tree)
            out.extend(v.findings)
        return out
