"""enum-literal-drift: bare literals shadowing the typed vocabulary.

``api/enums.py`` / ``api/conditions.py`` are the single vocabulary for
phases, exit classes, trigger decisions and condition reasons — the
reference's pkg/enums. A bare ``"Running"`` compared against a phase
field keeps working until someone renames/retires the member, then
fails open (comparison silently False). Flagged contexts, chosen for
precision over recall:

- comparisons (``==``, ``!=``, ``in``/``not in`` over a literal tuple)
  where one side is a string matching an enum family's value and the
  OTHER side's identifiers mention that family's hint token
  (``phase``, ``exit``, ``decision``, …);
- subscript stores / dict literals pairing a vocabulary KEY
  (``"phase"``, ``"exitClass"``, ``"decision"``, …) with a bare value
  literal from the matching family.

The fix is ``Phase.RUNNING`` / ``Phase.RUNNING.value`` — admission and
the store serialize enums transparently (SpecBase dumps ``.value``).

Scope: package code only (``bobrapet_tpu/``). Tests and the bench
harness deliberately assert on RAW wire strings — a test pinning
``status["phase"] == "Succeeded"`` verifies the serialized contract
independently of the enum, which is exactly what you want when the
enum itself is what might drift.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from ..context import CONDITIONS_MODULE, ENUMS_MODULE, enum_vocabulary
from ..core import AnalysisContext, Finding, ProjectFile, hint_text

#: enum family -> identifier tokens that mark a context as being
#: "about" that family. Tokens are matched against the non-literal
#: side of a comparison (or the subscript key), lowercased.
_FAMILY_HINTS = {
    "Phase": ("phase",),
    "ExitClass": ("exitclass", "exit_class", "exit"),
    "TriggerDecision": ("decision",),
    "EffectClaimPhase": ("phase",),
    "StopMode": ("stopmode", "stop_mode",),
    "StoryPattern": ("pattern",),
    "WorkloadMode": ("workloadmode", "workload_mode",),
}

#: dict/subscript keys -> families whose values they carry
_KEY_FAMILIES = {
    "phase": ("Phase", "EffectClaimPhase"),
    "exitClass": ("ExitClass",),
    "exit_class": ("ExitClass",),
    "decision": ("TriggerDecision",),
    "pattern": ("StoryPattern",),
}

#: modules that DEFINE the vocabulary (never flagged)
_DEFINITION_MODULES = {ENUMS_MODULE, CONDITIONS_MODULE}


class EnumLiteralDriftChecker:
    name = "enum-literal-drift"
    description = "bare string literals shadowing Phase/ExitClass/... enum values"

    def run(
        self, files: Sequence[ProjectFile], ctx: AnalysisContext
    ) -> Iterable[Finding]:
        vocab = enum_vocabulary(ctx)
        if vocab is None:
            return []
        #: value -> [(family, member)], for families we police
        value_map: dict[str, list[tuple[str, str]]] = {}
        for family, hints in _FAMILY_HINTS.items():
            for value, member in vocab.families.get(family, {}).items():
                value_map.setdefault(value, []).append((family, member))
        out: list[Finding] = []
        for pf in files:
            if pf.rel in _DEFINITION_MODULES:
                continue
            if not pf.rel.startswith("bobrapet_tpu/"):
                continue  # tests/bench pin raw wire strings on purpose
            scope: list[str] = []
            self._scan(pf, pf.tree, scope, value_map, out)
        return out

    # ------------------------------------------------------------------
    def _families_for(
        self, literal: str, hint: str, value_map
    ) -> Optional[list[tuple[str, str]]]:
        matches = value_map.get(literal)
        if not matches:
            return None
        picked = [
            (family, member)
            for family, member in matches
            if any(tok in hint for tok in _FAMILY_HINTS[family])
        ]
        return picked or None

    def _flag(
        self,
        pf: ProjectFile,
        node: ast.AST,
        scope: list[str],
        literal: str,
        picked: list[tuple[str, str]],
        context: str,
        out: list[Finding],
    ) -> None:
        suggestions = ", ".join(f"{fam}.{mem}" for fam, mem in picked)
        out.append(
            Finding(
                checker=self.name,
                path=pf.rel,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                scope=".".join(scope),
                message=(
                    f"bare literal {literal!r} in {context} shadows "
                    f"{suggestions} — use the enum member (renames/retires "
                    f"fail open on raw strings)"
                ),
                kernel=f"bare {literal} in {context} ({suggestions})",
            )
        )

    def _scan(
        self,
        pf: ProjectFile,
        node: ast.AST,
        scope: list[str],
        value_map,
        out: list[Finding],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                scope.append(child.name)
                self._scan(pf, child, scope, value_map, out)
                scope.pop()
                continue
            if isinstance(child, ast.Compare):
                self._check_compare(pf, child, scope, value_map, out)
            elif isinstance(child, ast.Assign):
                for tgt in child.targets:
                    if isinstance(tgt, ast.Subscript):
                        self._check_keyed(
                            pf, tgt.slice, child.value, child, scope, value_map, out
                        )
            elif isinstance(child, ast.Dict):
                for k, v in zip(child.keys, child.values):
                    if k is not None:
                        self._check_keyed(pf, k, v, v, scope, value_map, out)
            self._scan(pf, child, scope, value_map, out)

    def _check_compare(
        self, pf: ProjectFile, node: ast.Compare, scope, value_map, out
    ) -> None:
        operands = [node.left, *node.comparators]
        ops = node.ops
        for i, op in enumerate(ops):
            if not isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn)):
                continue
            left, right = operands[i], operands[i + 1]
            literals: list[tuple[ast.Constant, ast.AST]] = []
            if isinstance(left, ast.Constant) and isinstance(left.value, str):
                literals.append((left, right))
            if isinstance(right, ast.Constant) and isinstance(right.value, str):
                literals.append((right, left))
            # ``phase in ("Failed", "Timeout")``
            if isinstance(op, (ast.In, ast.NotIn)) and isinstance(
                right, (ast.Tuple, ast.List, ast.Set)
            ):
                for elt in right.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        literals.append((elt, left))
            for lit_node, other in literals:
                hint = hint_text(other)
                picked = self._families_for(lit_node.value, hint, value_map)
                if picked:
                    self._flag(
                        pf, lit_node, scope, lit_node.value, picked,
                        "comparison", out,
                    )

    def _check_keyed(
        self, pf: ProjectFile, key: ast.AST, value: ast.AST, at: ast.AST,
        scope, value_map, out,
    ) -> None:
        if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
            return
        families = _KEY_FAMILIES.get(key.value)
        if not families:
            return
        if not (isinstance(value, ast.Constant) and isinstance(value.value, str)):
            return
        picked = [
            (fam, value_map[value.value][j][1])
            for fam in families
            for j, (f2, _) in enumerate(value_map.get(value.value, []))
            if f2 == fam
        ]
        if picked:
            self._flag(
                pf, at, scope, value.value, picked,
                f"{key.value!r}-keyed store", out,
            )
