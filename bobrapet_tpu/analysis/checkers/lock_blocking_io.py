"""lock-blocking-io: no blocking calls inside ``with <lock>:`` blocks.

The control plane's scalability story depends on every lock being a
short critical section around in-memory state — PR 1's advisor round
found the recorder holding its lock across store listings, and with
~120 lock-held regions in the tree that bug class WILL recur. This
checker flags, lexically inside any ``with <something named *lock*>:``
body:

- sleeps (``time.sleep`` / bare ``sleep``);
- store traffic — any method call on a receiver named ``store`` (the
  coordination bus takes its own global lock and fans out to watchers:
  calling it under a private lock couples unrelated subsystems'
  latencies and invites lock-order cycles);
- filesystem calls (``open``, ``os.replace/remove/listdir/...``,
  ``shutil.*``);
- socket traffic (``recv/sendall/sendmsg/accept/connect/...``);
- subprocess / urllib calls;
- ``.wait(...)`` on anything that does not look like a Condition
  (``Condition.wait`` atomically releases the lock — ``Event.wait``
  under someone else's lock just blocks it).

Nested ``def``/``lambda`` bodies are skipped (defining a function under
a lock does not run it); comprehensions are scanned (they do run).

One level of interprocedural reasoning, same file only: a helper that
itself performs blocking calls (directly or via other same-file
helpers) marks every call site of that helper inside a lock-held
region — ``self._persist(obj)`` under the store lock is flagged
because ``_persist`` opens and replaces files, even though the
``open()`` is lexically elsewhere.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from ..core import (
    AnalysisContext,
    Finding,
    ProjectFile,
    attr_chain,
    terminal_name,
)

#: method names that block on sockets/pipes regardless of receiver
_SOCKET_METHODS = {
    "recv", "recv_into", "recvfrom", "sendall", "sendmsg", "accept",
    "connect", "makefile", "do_handshake", "unwrap",
}

#: os/shutil functions that hit the filesystem
_OS_BLOCKING = {
    "replace", "remove", "rename", "listdir", "makedirs", "mkdir",
    "rmdir", "unlink", "fsync", "stat", "scandir", "walk",
}

#: store methods — the full bus API; even the "cheap" view reads take
#: the store's global lock, so calling them under a private lock
#: creates a cross-subsystem lock edge
_STORE_METHODS = {
    "get", "try_get", "get_view", "try_get_view", "list", "list_views",
    "list_keys", "count", "create", "update", "update_status", "delete",
    "mutate", "patch_status", "watch",
}

#: receiver names treated as condition variables (``.wait`` releases)
_CONDVAR_HINTS = ("cond", "cv", "not_empty", "not_full", "_wakeup", "waiter")


def _lock_like(expr: ast.AST) -> Optional[str]:
    """Name the lock if this with-item looks like one (terminal
    identifier contains 'lock' and is not a condition variable)."""
    name = terminal_name(expr)
    if name is None:
        return None
    low = name.lower()
    if "lock" in low and not any(h in low for h in _CONDVAR_HINTS):
        chain = attr_chain(expr)
        return ".".join(chain) if chain else name
    return None


def _classify_call(call: ast.Call) -> Optional[str]:
    """-> stable kernel string describing the blocking call, or None."""
    func = call.func
    chain = attr_chain(func)
    if chain is None:
        return None
    dotted = ".".join(chain)
    last = chain[-1]
    if dotted in ("time.sleep",) or (len(chain) == 1 and last == "sleep"):
        return f"sleep call {dotted}"
    if len(chain) == 1 and last == "open":
        return "filesystem call open()"
    if len(chain) >= 2 and chain[-2] == "os" and last in _OS_BLOCKING:
        return f"filesystem call {dotted}"
    if len(chain) >= 2 and chain[-2] == "shutil":
        return f"filesystem call {dotted}"
    if len(chain) >= 2 and chain[-2] == "subprocess":
        return f"subprocess call {dotted}"
    if "urlopen" in last or (len(chain) >= 2 and "urllib" in chain[0]):
        return f"network call {dotted}"
    if len(chain) >= 2 and last in _SOCKET_METHODS:
        return f"socket call .{last}()"
    if len(chain) >= 2 and last in _STORE_METHODS and chain[-2] == "store":
        return f"store call {dotted}"
    if (
        len(chain) >= 2
        and last == "wait"
        and not any(h in chain[-2].lower() for h in _CONDVAR_HINTS)
    ):
        return f"blocking wait {dotted}"
    return None


def _same_file_callee(func: ast.AST) -> Optional[str]:
    """Name a call target that can plausibly resolve to a function
    defined in this file: a bare name (``helper()``) or a self/cls
    method (``self._persist()``). Attribute calls through any OTHER
    receiver are rejected — ``self._defaulters.get(...)`` is a dict
    read, and bare-name matching used to make it inherit whatever a
    same-file method named ``get`` does."""
    if isinstance(func, ast.Name):
        return func.id
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id in ("self", "cls")
    ):
        return func.attr
    return None


def _blocking_functions(tree: ast.Module) -> dict[str, str]:
    """Map bare function/method name -> kernel of a blocking call it
    performs, propagated through same-file call edges to a fixed point
    (names collide across classes in one file; the union is a cheap,
    sound-enough over-approximation for a lint)."""
    direct: dict[str, str] = {}
    edges: dict[str, set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        callees: set[str] = set()
        for call in _walk_skipping_defs_multi(node.body):
            if not isinstance(call, ast.Call):
                continue
            kernel = _classify_call(call)
            if kernel is not None:
                direct.setdefault(node.name, kernel)
            else:
                t = _same_file_callee(call.func)
                if t is not None:
                    callees.add(t)
        edges[node.name] = callees
    # propagate: fn with no kernel inherits from a blocking callee —
    # callees in sorted order so the chosen kernel (and therefore the
    # finding fingerprint) is stable across runs
    blocking = dict(direct)
    changed = True
    while changed:
        changed = False
        for fn in sorted(edges):
            if fn in blocking:
                continue
            for c in sorted(edges[fn]):
                if c in blocking and c != fn:
                    blocking[fn] = f"{blocking[c]} (via {c}())"
                    changed = True
                    break
    return blocking


def _walk_skipping_defs_multi(stmts):
    for stmt in stmts:
        yield from _walk_skipping_defs(stmt)


class _Visitor(ast.NodeVisitor):
    def __init__(self, pf: ProjectFile, blocking_fns: dict[str, str]):
        self.pf = pf
        self.blocking_fns = blocking_fns
        self.findings: list[Finding] = []
        self._scope: list[str] = []

    def _in_scope(self, name: str, node: ast.AST) -> None:
        self._scope.append(name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._in_scope(node.name, node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._in_scope(node.name, node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._in_scope(node.name, node)

    def visit_With(self, node: ast.With) -> None:
        locks = [_lock_like(item.context_expr) for item in node.items]
        lock_name = next((name for name in locks if name), None)
        if lock_name is not None:
            for stmt in node.body:
                self._scan_locked(stmt, lock_name)
        self.generic_visit(node)

    def _scan_locked(self, node: ast.AST, lock_name: str) -> None:
        for child in _walk_skipping_defs(node):
            if isinstance(child, ast.Call):
                kernel = _classify_call(child)
                if kernel is None:
                    t = _same_file_callee(child.func)
                    if t in self.blocking_fns:
                        kernel = f"{t}(): {self.blocking_fns[t]}"
                if kernel is not None:
                    self.findings.append(
                        Finding(
                            checker="lock-blocking-io",
                            path=self.pf.rel,
                            line=child.lineno,
                            col=child.col_offset,
                            scope=".".join(self._scope),
                            message=(
                                f"{kernel} while holding {lock_name} — move "
                                f"the blocking work outside the critical "
                                f"section (snapshot under the lock, act after "
                                f"release)"
                            ),
                            kernel=f"{kernel} under {lock_name}",
                        )
                    )


def _walk_skipping_defs(root: ast.AST):
    """ast.walk, but do not descend into nested function/lambda bodies
    (code defined under a lock is not code RUN under it). Applies to
    the root too: a bare ``def`` statement inside a with-block
    contributes nothing."""
    if isinstance(root, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


class LockBlockingIOChecker:
    name = "lock-blocking-io"
    description = "blocking I/O, sleeps or store traffic inside a lock-held region"

    def run(
        self, files: Sequence[ProjectFile], ctx: AnalysisContext
    ) -> Iterable[Finding]:
        out: list[Finding] = []
        for pf in files:
            v = _Visitor(pf, _blocking_functions(pf.tree))
            v.visit(pf.tree)
            out.extend(v.findings)
        return out
