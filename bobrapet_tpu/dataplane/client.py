"""SDK-side stream clients: producer (send with credit respect) and
consumer (iterate + ack).

These are what an engram uses under the hood via
``EngramContext.open_output_stream`` / ``open_input_stream`` — the
endpoint and settings come from the operator-negotiated BindingInfo and
downstream targets (reference: SDKs stream outputs P2P via
controller-computed gRPC endpoints, steprun_controller.go:1405-1651).

The producer BLOCKS in :meth:`StreamProducer.send` when credit flow
control is active and the hub has stopped granting — that is the
backpressure contract: a full downstream buffer slows the producer
instead of dropping data (unless the negotiated drop policy says
otherwise, which the hub enforces).
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Any, Iterator, Optional

from .frames import FrameError, read_frame, send_frame


class StreamClosed(Exception):
    """The peer closed the stream."""


class StreamProtocolError(Exception):
    """The peer rejected our traffic (e.g. sending without credit)."""


def _connect(endpoint: str, timeout: float, tls=None) -> socket.socket:
    host, _, port = endpoint.rpartition(":")
    sock = socket.create_connection((host or "127.0.0.1", int(port)), timeout=timeout)
    sock.settimeout(timeout)
    if tls is not None:
        # shared-CA mutual TLS (dataplane/tls.py): the server must
        # present a CA-chained cert; we present ours. The wrapper
        # serializes SSL_read/SSL_write — these sockets are shared by a
        # reader thread and a sending thread
        from .tls import client_context, wrap_tls

        sock = wrap_tls(sock, client_context(tls),
                        server_hostname=host or "127.0.0.1")
        sock.settimeout(timeout)
    return sock


class StreamProducer:
    """Connects to a hub (or a P2P consumer's embedded hub) and sends."""

    def __init__(
        self,
        endpoint: str,
        stream: str,
        settings: Optional[dict[str, Any]] = None,
        lane: str = "data",
        connect_timeout: float = 10.0,
        tls=None,
    ):
        self.stream = stream
        # observability.watermark.timestampSource: a dotted path into
        # JSON payloads (e.g. "metadata.event_time_ms"); when set, send
        # extracts the event time and stamps the header "et" the hubs'
        # watermark tracking consumes — extraction lives CLIENT-side so
        # both hub engines stay payload-agnostic
        wm = ((settings or {}).get("observability") or {}).get("watermark") or {}
        self._et_source = (
            (wm.get("timestampSource") or "").split(".")
            if wm.get("enabled") and wm.get("timestampSource") else None
        )
        self._sock = _connect(endpoint, connect_timeout, tls=tls)
        self._credits = 0
        self._unlimited = False
        self._credit_cv = threading.Condition()
        self._closed = False
        self._error: Optional[str] = None
        send_frame(self._sock, {
            "t": "hello", "role": "producer", "stream": stream,
            "lane": lane, "settings": settings,
        })
        fr = read_frame(self._sock)
        if fr is None or fr[0].get("t") != "ok":
            raise StreamProtocolError(f"handshake failed: {fr and fr[0]}")
        # the timeout guarded connect+handshake only: an idle stream is
        # healthy, so reads must block indefinitely afterwards
        self._sock.settimeout(None)
        credits = int(fr[0].get("credits", -1))
        if credits < 0:
            self._unlimited = True
        else:
            self._credits = credits
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name=f"producer-{stream}"
        )
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                fr = read_frame(self._sock)
                if fr is None:
                    break
                header, _ = fr
                t = header.get("t")
                if t == "credit":
                    with self._credit_cv:
                        self._credits += int(header.get("n", 0))
                        self._credit_cv.notify_all()
                elif t == "err":
                    with self._credit_cv:
                        self._error = header.get("message", "stream error")
                        self._credit_cv.notify_all()
                    return
        except (OSError, FrameError):
            pass
        with self._credit_cv:
            self._closed = True
            self._credit_cv.notify_all()

    def send(
        self,
        payload: Any,
        key: Optional[str] = None,
        timeout: Optional[float] = None,
        event_time_ms: Optional[int] = None,
    ) -> None:
        """Send one message; blocks while the hub withholds credits
        (backpressure). Raises TimeoutError when `timeout` elapses
        blocked, StreamClosed/StreamProtocolError on a dead stream.
        ``event_time_ms`` stamps the event-time header for watermark
        tracking (auto-extracted from JSON payloads when the settings
        declare a timestampSource)."""
        if event_time_ms is None and self._et_source and not isinstance(payload, bytes):
            node: Any = payload
            for part in self._et_source:
                node = node.get(part) if isinstance(node, dict) else None
            if isinstance(node, (int, float)):
                event_time_ms = int(node)
        data = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        if not self._unlimited:
            with self._credit_cv:
                ok = self._credit_cv.wait_for(
                    lambda: self._credits > 0 or self._closed or self._error,
                    timeout=timeout,
                )
                if self._error:
                    raise StreamProtocolError(self._error)
                if self._closed:
                    raise StreamClosed(self.stream)
                if not ok:
                    raise TimeoutError(
                        f"backpressured: no credit on {self.stream!r} "
                        f"after {timeout}s"
                    )
                self._credits -= 1
        header: dict[str, Any] = {"t": "data"}
        if key is not None:
            header["key"] = key
        if event_time_ms is not None:
            header["et"] = int(event_time_ms)
        send_frame(self._sock, header, data)

    @property
    def credits(self) -> int:
        with self._credit_cv:
            return -1 if self._unlimited else self._credits

    def close(self, eos: bool = True) -> None:
        # half-close, then wait for the hub to finish reading: closing
        # outright while a credit frame sits unread in OUR receive
        # buffer turns the close into a TCP RST, which discards the
        # EOS frame still queued toward the hub
        try:
            if eos:
                send_frame(self._sock, {"t": "eos"})
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        self._reader.join(timeout=5.0)
        try:
            self._sock.close()
        except OSError:
            pass


class StreamConsumer:
    """Connects to a hub and iterates messages, acking per the
    negotiated ``ackEvery`` cadence (cumulative acks)."""

    def __init__(
        self,
        endpoint: str,
        stream: str,
        settings: Optional[dict[str, Any]] = None,
        lane: str = "data",
        connect_timeout: float = 10.0,
        decode_json: bool = False,
        from_seq: Optional[int] = None,
        tls=None,
        consumer_id: Optional[str] = None,
    ):
        self.stream = stream
        self.decode_json = decode_json
        #: latest event-time watermark (ms) pushed by the hub; None
        #: until the first watermark frame arrives
        self.watermark_ms: Optional[int] = None
        fc = (settings or {}).get("flowControl") or {}
        self._ack_every = int(((fc.get("ackEvery") or {}).get("messages")) or 1)
        self._sock = _connect(endpoint, connect_timeout, tls=tls)
        self._since_ack = 0
        self._last_seq = -1
        hello: dict[str, Any] = {
            "t": "hello", "role": "consumer", "stream": stream,
            "lane": lane, "settings": settings,
        }
        if from_seq is not None:
            # replay.mode=full: rejoin the stream at a seq in retained
            # history (re-delivers already-acked entries)
            hello["fromSeq"] = int(from_seq)
        if consumer_id is not None:
            # replay.mode=fromCheckpoint: the durable checkpoint
            # identity — the hub resumes this consumer after its last
            # persisted cumulative ack automatically
            hello["consumerId"] = str(consumer_id)
        send_frame(self._sock, hello)
        fr = read_frame(self._sock)
        if fr is None or fr[0].get("t") != "ok":
            raise StreamProtocolError(f"handshake failed: {fr and fr[0]}")
        self._sock.settimeout(None)  # idle != dead; block between messages

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                fr = read_frame(self._sock)
            except FrameError as e:
                raise StreamProtocolError(str(e)) from e
            except OSError as e:
                raise StreamClosed(f"{self.stream}: {e}") from e
            if fr is None:
                # EOF without an eos frame = the hub died mid-stream; a
                # truncated stream must NOT read as a clean end
                raise StreamClosed(f"{self.stream}: connection closed before eos")
            header, payload = fr
            t = header.get("t")
            if t == "data":
                self._last_seq = int(header.get("seq", self._last_seq))
                # yield BEFORE acking: the cumulative ack covering this
                # message goes out only after the application consumed
                # it (atLeastOnce survives a crash mid-processing)
                yield json.loads(payload) if self.decode_json else payload
                self._since_ack += 1
                if self._since_ack >= self._ack_every:
                    self.ack()
            elif t == "watermark":
                # event-time frontier update; not part of the data
                # iteration. max-guarded: reconnects/races must never
                # rewind the locally observed frontier
                ms = header.get("ms")
                if ms is not None and (self.watermark_ms is None
                                       or int(ms) > self.watermark_ms):
                    self.watermark_ms = int(ms)
            elif t == "eos":
                self.ack()
                return
            elif t == "err":
                raise StreamProtocolError(header.get("message", "stream error"))

    def ack(self) -> None:
        if self._last_seq >= 0:
            try:
                send_frame(self._sock, {"t": "ack", "seq": self._last_seq})
            except OSError:
                pass
        self._since_ack = 0

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def open_producer(endpoint: str, stream: str,
                  settings: Optional[dict[str, Any]] = None,
                  **kw: Any):
    """Settings-aware producer factory: partitioned settings return a
    router over N hub streams (dataplane/partition.py), plain settings
    a direct :class:`StreamProducer` — call sites stay agnostic."""
    from .partition import PartitionedProducer, partitioning_of

    part = partitioning_of(settings)
    if part is not None:
        return PartitionedProducer(endpoint, stream, settings, part, **kw)
    return StreamProducer(endpoint, stream, settings=settings, **kw)


def open_consumer(endpoint: str, stream: str,
                  settings: Optional[dict[str, Any]] = None,
                  **kw: Any):
    """Settings-aware consumer factory: the partitioned variant fan-in
    merges every partition into one iterator."""
    from .partition import PartitionedConsumer, partitioning_of

    part = partitioning_of(settings)
    if part is not None:
        return PartitionedConsumer(endpoint, stream, settings, part, **kw)
    return StreamConsumer(endpoint, stream, settings=settings, **kw)
