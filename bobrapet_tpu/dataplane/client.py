"""SDK-side stream clients: producer (send with credit respect) and
consumer (iterate + ack).

These are what an engram uses under the hood via
``EngramContext.open_output_stream`` / ``open_input_stream`` — the
endpoint and settings come from the operator-negotiated BindingInfo and
downstream targets (reference: SDKs stream outputs P2P via
controller-computed gRPC endpoints, steprun_controller.go:1405-1651).

The producer BLOCKS in :meth:`StreamProducer.send` when credit flow
control is active and the hub has stopped granting — that is the
backpressure contract: a full downstream buffer slows the producer
instead of dropping data (unless the negotiated drop policy says
otherwise, which the hub enforces).
"""

from __future__ import annotations

import collections
import json
import socket
import threading
from typing import Any, Iterator, Optional

from .frames import FrameError, FrameReader, encode_frame, send_frame, send_frames


class StreamClosed(Exception):
    """The peer closed the stream."""


class StreamProtocolError(Exception):
    """The peer rejected our traffic (e.g. sending without credit)."""


def _connect(endpoint: str, timeout: float, tls=None,
             nodelay: bool = False) -> socket.socket:
    host, _, port = endpoint.rpartition(":")
    sock = socket.create_connection((host or "127.0.0.1", int(port)), timeout=timeout)
    sock.settimeout(timeout)
    if nodelay:
        # consumers ack on this socket and producers wait on the credit
        # replenish those acks trigger — Nagle would hold each tiny ack
        # for a delayed-ACK window. Producer data sockets keep Nagle:
        # back-to-back sends coalesce into fewer segments, and the
        # producer never waits on its own socket's round trip.
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover - non-TCP transports
            pass
    if tls is not None:
        # shared-CA mutual TLS (dataplane/tls.py): the server must
        # present a CA-chained cert; we present ours. The wrapper
        # serializes SSL_read/SSL_write — these sockets are shared by a
        # reader thread and a sending thread
        from .tls import client_context, wrap_tls

        sock = wrap_tls(sock, client_context(tls),
                        server_hostname=host or "127.0.0.1")
        sock.settimeout(timeout)
    return sock


class StreamProducer:
    """Connects to a hub (or a P2P consumer's embedded hub) and sends.

    Sends go through a per-producer write queue drained by one writer
    thread: a burst of :meth:`send` calls coalesces into one large
    write (one TCP segment train) instead of one small segment per
    frame — small-frame streams are otherwise throttled by the
    Nagle/delayed-ACK round trip, not by bandwidth. An idle writer
    flushes a lone frame immediately (one thread wakeup of latency);
    :meth:`close` drains the queue before the eos leaves."""

    def __init__(
        self,
        endpoint: str,
        stream: str,
        settings: Optional[dict[str, Any]] = None,
        lane: str = "data",
        connect_timeout: float = 10.0,
        tls=None,
        trace_context: Optional[dict[str, Any]] = None,
    ):
        self.stream = stream
        #: run trace context advertised in the hello — the hub stamps it
        #: onto the stream record, so a stream is queryable by traceId
        #: (observability plane; ignored by hubs that predate it)
        self.trace_context = trace_context
        # observability.watermark.timestampSource: a dotted path into
        # JSON payloads (e.g. "metadata.event_time_ms"); when set, send
        # extracts the event time and stamps the header "et" the hubs'
        # watermark tracking consumes — extraction lives CLIENT-side so
        # both hub engines stay payload-agnostic
        wm = ((settings or {}).get("observability") or {}).get("watermark") or {}
        self._et_source = (
            (wm.get("timestampSource") or "").split(".")
            if wm.get("enabled") and wm.get("timestampSource") else None
        )
        self._sock = _connect(endpoint, connect_timeout, tls=tls)
        self._reader = FrameReader(self._sock)
        self._credits = 0
        self._unlimited = False
        self._credit_cv = threading.Condition()
        self._closed = False
        self._error: Optional[str] = None
        hello: dict[str, Any] = {
            "t": "hello", "role": "producer", "stream": stream,
            "lane": lane, "settings": settings,
        }
        if trace_context and trace_context.get("traceId"):
            hello["trace"] = {
                "traceId": trace_context.get("traceId"),
                "spanId": trace_context.get("spanId"),
            }
        send_frame(self._sock, hello)
        fr = self._reader.read()
        if fr is None or fr[0].get("t") != "ok":
            raise StreamProtocolError(f"handshake failed: {fr and fr[0]}")
        # the timeout guarded connect+handshake only: an idle stream is
        # healthy, so reads must block indefinitely afterwards
        self._sock.settimeout(None)
        credits = int(fr[0].get("credits", -1))
        if credits < 0:
            self._unlimited = True
        else:
            self._credits = credits
        self._reader_thread = threading.Thread(
            target=self._read_loop, daemon=True, name=f"producer-{stream}"
        )
        self._reader_thread.start()
        # batched writer: send() only enqueues encoded frames. The
        # queue is BYTE-bounded: a producer outrunning a backpressured
        # peer blocks in send() (the same TCP backpressure contract as
        # the old synchronous sendall, one buffer earlier).
        self._wq: collections.deque = collections.deque()
        self._wq_bytes = 0
        self._wq_max_bytes = 8 * 1024 * 1024
        self._wcv = threading.Condition()
        self._wclosed = False
        self._winflight = False
        self._writer_thread = threading.Thread(
            target=self._write_loop, daemon=True,
            name=f"producer-writer-{stream}",
        )
        self._writer_thread.start()

    def _write_loop(self) -> None:
        while True:
            with self._wcv:
                self._wcv.wait_for(lambda: self._wq or self._wclosed)
                if not self._wq:
                    if self._wclosed:
                        return  # drained: everything enqueued was sent
                    continue
                bufs = []
                while self._wq and len(bufs) < 256:
                    w = self._wq.popleft()
                    self._wq_bytes -= len(w)
                    bufs.append(w)
                self._winflight = True
                self._wcv.notify_all()  # wake senders blocked on the bound
            try:
                send_frames(self._sock, bufs)
            except OSError as e:
                with self._credit_cv:
                    if self._error is None:
                        self._error = f"send failed: {e}"
                    self._credit_cv.notify_all()
                with self._wcv:
                    self._wclosed = True
                    self._wq.clear()
                    self._wq_bytes = 0
                    self._winflight = False
                    self._wcv.notify_all()
                return
            with self._wcv:
                self._winflight = False
                self._wcv.notify_all()  # wake flush()/close() waiters

    def _enqueue_wire(self, wire: bytes) -> None:
        with self._wcv:
            # backpressure: block while the queue is at its byte bound
            # (the writer drains it; a dead writer raises below)
            self._wcv.wait_for(
                lambda: self._wclosed
                or self._wq_bytes + len(wire) <= self._wq_max_bytes
                or not self._wq
            )
            if self._wclosed:
                raise StreamClosed(self.stream)
            self._wq.append(wire)
            self._wq_bytes += len(wire)
            self._wcv.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every enqueued frame reached the socket."""
        with self._wcv:
            self._wcv.wait_for(
                lambda: (not self._wq and not self._winflight)
                or self._wclosed,
                timeout=timeout,
            )

    def _read_loop(self) -> None:
        try:
            while True:
                fr = self._reader.read()
                if fr is None:
                    break
                header, _ = fr
                t = header.get("t")
                if t == "credit":
                    with self._credit_cv:
                        self._credits += int(header.get("n", 0))
                        self._credit_cv.notify_all()
                elif t == "err":
                    with self._credit_cv:
                        self._error = header.get("message", "stream error")
                        self._credit_cv.notify_all()
                    return
        except (OSError, FrameError):
            pass
        with self._credit_cv:
            self._closed = True
            self._credit_cv.notify_all()

    def send(
        self,
        payload: Any,
        key: Optional[str] = None,
        timeout: Optional[float] = None,
        event_time_ms: Optional[int] = None,
    ) -> None:
        """Send one message; blocks while the hub withholds credits
        (backpressure). Raises TimeoutError when `timeout` elapses
        blocked, StreamClosed/StreamProtocolError on a dead stream.
        ``event_time_ms`` stamps the event-time header for watermark
        tracking (auto-extracted from JSON payloads when the settings
        declare a timestampSource)."""
        if event_time_ms is None and self._et_source and not isinstance(payload, bytes):
            node: Any = payload
            for part in self._et_source:
                node = node.get(part) if isinstance(node, dict) else None
            if isinstance(node, (int, float)):
                event_time_ms = int(node)
        data = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        if not self._unlimited:
            with self._credit_cv:
                ok = self._credit_cv.wait_for(
                    lambda: self._credits > 0 or self._closed or self._error,
                    timeout=timeout,
                )
                if self._error:
                    raise StreamProtocolError(self._error)
                if self._closed:
                    raise StreamClosed(self.stream)
                if not ok:
                    raise TimeoutError(
                        f"backpressured: no credit on {self.stream!r} "
                        f"after {timeout}s"
                    )
                self._credits -= 1
        header: dict[str, Any] = {"t": "data"}
        if key is not None:
            header["key"] = key
        if event_time_ms is not None:
            header["et"] = int(event_time_ms)
        self._enqueue_wire(encode_frame(header, data))

    @property
    def credits(self) -> int:
        with self._credit_cv:
            return -1 if self._unlimited else self._credits

    def close(self, eos: bool = True) -> None:
        # half-close, then wait for the hub to finish reading: closing
        # outright while a credit frame sits unread in OUR receive
        # buffer turns the close into a TCP RST, which discards the
        # EOS frame still queued toward the hub
        if eos:
            try:
                self._enqueue_wire(encode_frame({"t": "eos"}, b""))
            except StreamClosed:
                pass  # writer already dead; nothing more can be sent
            # drain-then-exit: the writer flushes everything queued
            # (the eos included) before the half-close below. No join
            # timeout — the old synchronous send blocked exactly the
            # same way on a stalled peer, and a DEAD peer breaks the
            # writer's sendall with an error that ends the drain.
            with self._wcv:
                self._wclosed = True
                self._wcv.notify_all()
            self._writer_thread.join()
            try:
                self._sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
        else:
            # abort-close (crash semantics): drop what was queued,
            # break any in-flight sendall with the shutdown, and give
            # the writer a BOUNDED exit window — never hang an abort
            with self._wcv:
                self._wq.clear()
                self._wq_bytes = 0
                self._wclosed = True
                self._wcv.notify_all()
            try:
                self._sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            self._writer_thread.join(timeout=5.0)
        self._reader_thread.join(timeout=5.0)
        try:
            self._sock.close()
        except OSError:
            pass


class StreamConsumer:
    """Connects to a hub and iterates messages, acking per the
    negotiated ``ackEvery`` cadence (cumulative acks)."""

    def __init__(
        self,
        endpoint: str,
        stream: str,
        settings: Optional[dict[str, Any]] = None,
        lane: str = "data",
        connect_timeout: float = 10.0,
        decode_json: bool = False,
        from_seq: Optional[int] = None,
        tls=None,
        consumer_id: Optional[str] = None,
    ):
        self.stream = stream
        self.decode_json = decode_json
        #: latest event-time watermark (ms) pushed by the hub; None
        #: until the first watermark frame arrives
        self.watermark_ms: Optional[int] = None
        fc = (settings or {}).get("flowControl") or {}
        self._ack_every = int(((fc.get("ackEvery") or {}).get("messages")) or 1)
        #: deferral bound: at most this many consumed-but-unacked
        #: messages before an ack is forced mid-burst
        self._ack_defer_cap = max(64, 8 * self._ack_every)
        self._sock = _connect(endpoint, connect_timeout, tls=tls, nodelay=True)
        self._since_ack = 0
        self._last_seq = -1
        hello: dict[str, Any] = {
            "t": "hello", "role": "consumer", "stream": stream,
            "lane": lane, "settings": settings,
        }
        if from_seq is not None:
            # replay.mode=full: rejoin the stream at a seq in retained
            # history (re-delivers already-acked entries)
            hello["fromSeq"] = int(from_seq)
        if consumer_id is not None:
            # replay.mode=fromCheckpoint: the durable checkpoint
            # identity — the hub resumes this consumer after its last
            # persisted cumulative ack automatically
            hello["consumerId"] = str(consumer_id)
        send_frame(self._sock, hello)
        self._reader = FrameReader(self._sock)
        fr = self._reader.read()
        if fr is None or fr[0].get("t") != "ok":
            raise StreamProtocolError(f"handshake failed: {fr and fr[0]}")
        self._sock.settimeout(None)  # idle != dead; block between messages

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                fr = self._reader.read()
            except FrameError as e:
                raise StreamProtocolError(str(e)) from e
            except OSError as e:
                raise StreamClosed(f"{self.stream}: {e}") from e
            if fr is None:
                # EOF without an eos frame = the hub died mid-stream; a
                # truncated stream must NOT read as a clean end
                raise StreamClosed(f"{self.stream}: connection closed before eos")
            header, payload = fr
            t = header.get("t")
            if t == "data":
                self._last_seq = int(header.get("seq", self._last_seq))
                # yield BEFORE acking: the cumulative ack covering this
                # message goes out only after the application consumed
                # it (atLeastOnce survives a crash mid-processing)
                yield json.loads(payload) if self.decode_json else payload
                self._since_ack += 1
            elif t == "watermark":
                # event-time frontier update; not part of the data
                # iteration. max-guarded: reconnects/races must never
                # rewind the locally observed frontier
                ms = header.get("ms")
                if ms is not None and (self.watermark_ms is None
                                       or int(ms) > self.watermark_ms):
                    self.watermark_ms = int(ms)
            elif t == "eos":
                self.ack()
                return
            elif t == "err":
                raise StreamProtocolError(header.get("message", "stream error"))
            # deferred cumulative-ack flush, checked after EVERY frame
            # type: acks are cumulative, so while a drain burst is
            # still buffered locally one later ack covers the whole
            # run. Capped so a long burst can't starve the producer's
            # credit replenish (which rides on acks) — and flushed when
            # the buffer runs dry even if the LAST buffered frame was a
            # control frame (a watermark behind the final data frame
            # must not leave the ack deferred forever: the producer
            # would wait on credits that only an ack can release).
            if self._since_ack >= self._ack_every:
                if (self._since_ack >= self._ack_defer_cap
                        or not self._reader.has_buffered_frame()):
                    self.ack()

    def ack(self) -> None:
        if self._last_seq >= 0:
            try:
                send_frame(self._sock, {"t": "ack", "seq": self._last_seq})
            except OSError:
                pass
        self._since_ack = 0

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def open_producer(endpoint: str, stream: str,
                  settings: Optional[dict[str, Any]] = None,
                  **kw: Any):
    """Settings-aware producer factory: partitioned settings return a
    router over N hub streams (dataplane/partition.py), plain settings
    a direct :class:`StreamProducer` — call sites stay agnostic."""
    from .partition import PartitionedProducer, partitioning_of

    part = partitioning_of(settings)
    if part is not None:
        return PartitionedProducer(endpoint, stream, settings, part, **kw)
    return StreamProducer(endpoint, stream, settings=settings, **kw)


def open_consumer(endpoint: str, stream: str,
                  settings: Optional[dict[str, Any]] = None,
                  **kw: Any):
    """Settings-aware consumer factory: the partitioned variant fan-in
    merges every partition into one iterator."""
    from .partition import PartitionedConsumer, partitioning_of

    part = partitioning_of(settings)
    if part is not None:
        return PartitionedConsumer(endpoint, stream, settings, part, **kw)
    return StreamConsumer(endpoint, stream, settings=settings, **kw)
