"""Run a standalone stream hub: ``python -m bobrapet_tpu.dataplane``.

The deployment shape of the reference's realtime add-on (its hub is a
separate deployable installed next to the operator); on GKE this runs
as a Service on the TPU-VM host network.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main() -> None:
    parser = argparse.ArgumentParser(description="bobrapet stream hub")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=7447)
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument(
        "--engine", choices=["auto", "native", "python"], default="auto",
        help="native = C++ poll loop (native/streamhub.cc); auto prefers "
             "native and falls back to the Python broker",
    )
    parser.add_argument(
        "--tls-dir", default=None,
        help="shared-CA mTLS material (ca.crt/tls.crt/tls.key); the "
             "native engine runs behind a TLS-terminating frontend",
    )
    parser.add_argument(
        "--record-dir", default=None,
        help="record streams whose settings enable recording into this "
             "directory (FileStore); forces the Python engine",
    )
    args = parser.parse_args()
    logging.basicConfig(level=args.log_level)

    from .native import build_hub

    native = {"auto": None, "native": True, "python": False}[args.engine]
    hub = build_hub(host=args.host, port=args.port, native=native,
                    tls_dir=args.tls_dir, record_dir=args.record_dir)
    port = hub.start()
    logging.getLogger(__name__).info(
        "stream hub (%s) listening on %s:%s",
        type(hub).__name__, args.host, port,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    hub.stop()


if __name__ == "__main__":
    main()
