"""Stream recording: tee data frames into the blob store.

The streaming policy language's ``recording`` block (reference:
transport_settings_types.go:498-528). Two vocabularies are accepted:
the reference's ``off | metadata | payload`` — ``metadata`` records
seq/key/size with no payload bytes, and ``sampleRate`` samples a
deterministic percentage orthogonally — and the in-tree shorthand
``none | sample | full`` (``full`` always records 100%; ``sample``
needs a rate). ``redactFields`` scrubs named top-level JSON payload
fields before anything touches storage; ``retentionSeconds`` bounds
how long segments live (the storage retention sweep pattern).

Segments are JSONL blobs under ``{prefix}/{stream}/{first_seq}.jsonl``
in any :class:`~bobrapet_tpu.storage.store.Store` (Memory/File/S3/SSD),
so a recorded stream replays from durable storage long after the hub
forgot it — unlike ``replay.mode=full``, which is hub-memory-bounded.

Flush model: the hub records under its stream lock so per-stream entry
order is exactly seq order; appends are cheap, and the occasional
segment write at a boundary is one ``store.put`` (Memory/File stores —
wrap a slow remote store in an async adapter before handing it to a
hot hub). A final flush lands the tail at eos, and ``replay`` merges
flushed segments with the unflushed tail, so readers never wait for a
boundary.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from typing import Any, Iterator, Optional

from ..storage.store import Store

DEFAULT_SEGMENT_ENTRIES = 256

#: deterministic per-seq sampling hash (Knuth multiplicative); NOT
#: random so a replayed producer records the same subset
_SAMPLE_MIX = 2654435761


def _sampled(seq: int, rate: float) -> bool:
    return (seq * _SAMPLE_MIX) % 10_000 < rate * 100


def recording_knobs(settings: Optional[dict[str, Any]]) -> Optional[dict[str, Any]]:
    """Normalized recording knobs, accepting BOTH vocabularies:

    - the reference's (transport_settings_types.go:498-505):
      ``off | metadata | payload`` with an orthogonal ``sampleRate``
      (metadata records seq/key/size without the payload bytes);
    - the in-tree shorthand: ``none | sample | full`` (full==payload;
      sample==payload at sampleRate%).
    """
    rec = (settings or {}).get("recording") or {}
    mode = rec.get("mode")
    if mode in (None, "none", "off"):
        return None
    if mode not in ("full", "sample", "payload", "metadata"):
        return None  # admission already rejected unknown modes
    return {
        "payload": mode != "metadata",
        # legacy "full" means 100% by definition (admission also
        # rejects a stray sampleRate there); reference modes take the
        # orthogonal rate
        "sample_rate": (100.0 if mode == "full"
                        else float(rec.get("sampleRate") or 100.0)),
        "retention": float(rec.get("retentionSeconds") or 0) or None,
        "redact": list(rec.get("redactFields") or []),
    }


def _redact(payload: bytes, fields: list[str]) -> bytes:
    if not fields:
        return payload
    try:
        obj = json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        return payload  # opaque payloads cannot be field-redacted
    if isinstance(obj, dict):
        for f in fields:
            if f in obj:
                obj[f] = "[REDACTED]"
    return json.dumps(obj).encode()


class StreamRecorder:
    """Records streams into a Store (see module doc)."""

    def __init__(self, store: Store, prefix: str = "recordings",
                 segment_entries: int = DEFAULT_SEGMENT_ENTRIES):
        self.store = store
        self.prefix = prefix
        self.segment_entries = segment_entries
        self._lock = threading.Lock()
        #: stream -> pending (seq, key, payload-or-None, size) entries
        #: (payload None = metadata-mode entry)
        self._pending: dict[
            str, list[tuple[int, Optional[str], Optional[bytes], int]]
        ] = {}
        #: stream -> retention seconds (for the sweep)
        self._retention: dict[str, Optional[float]] = {}
        #: stream -> count of segments ever written (under the lock);
        #: lets the sweep prove "no segment landed since my listing"
        #: without holding the lock across a store round trip
        self._segment_epoch: dict[str, int] = {}

    # -- write path --------------------------------------------------------

    def record(self, stream: str, seq: int, key: Optional[str],
               payload: bytes, knobs: Optional[dict[str, Any]]) -> None:
        """Tee one data frame; cheap unless a segment boundary is
        crossed (then the full segment is written to the store)."""
        if knobs is None:
            return
        if knobs["sample_rate"] < 100.0 and not _sampled(seq, knobs["sample_rate"]):
            return
        size = len(payload)
        if knobs["payload"]:
            payload = _redact(payload, knobs["redact"])
        else:
            # metadata mode: seq/key/size only — the bytes never touch
            # storage (the reference's TransportRecordingMetadata)
            payload = None
        with self._lock:
            pend = self._pending.setdefault(stream, [])
            pend.append((seq, key, payload, size))
            self._retention[stream] = knobs["retention"]
            if len(pend) >= self.segment_entries:
                # write INSIDE the lock: popping first and writing
                # outside would open a window where a concurrent
                # replay() sees the entries in neither the store nor
                # the tail (a silent mid-stream gap)
                self._write_segment(stream, pend)
                self._pending[stream] = []

    def flush(self, stream: str) -> None:
        """Persist the unflushed tail (the hub calls this at eos)."""
        with self._lock:
            pend = self._pending.pop(stream, None)
            if pend:
                self._write_segment(stream, pend)

    def _write_segment(self, stream: str, entries: list) -> None:
        self._segment_epoch[stream] = self._segment_epoch.get(stream, 0) + 1
        first = entries[0][0]
        lines = [
            json.dumps({
                "seq": seq,
                "key": key,
                # null payload = metadata-mode entry (size retained)
                "payload": (base64.b64encode(payload).decode()
                            if payload is not None else None),
                "bytes": size,
            })
            for seq, key, payload, size in entries
        ]
        self.store.put(
            f"{self.prefix}/{stream}/{first:012d}.jsonl",
            ("\n".join(lines) + "\n").encode(),
        )

    # -- read / retention --------------------------------------------------

    def replay(self, stream: str, from_seq: int = 0) -> Iterator[dict[str, Any]]:
        """Entries of a recorded stream in seq order: flushed segments
        from the store plus the unflushed tail.

        The tail is snapshotted BEFORE the segment listing: a segment
        flush racing the other way (list first, then snapshot) would
        hide entries that moved from pending into a segment between the
        two reads — a silent mid-stream gap. Snapshotting first means
        an entry can instead appear in BOTH the snapshot and a freshly
        flushed segment, so tail entries at or below the highest
        segment seq are deduped away.
        """
        with self._lock:
            tail = list(self._pending.get(stream, []))
        last_segment_seq = -1
        keys = sorted(self.store.list(f"{self.prefix}/{stream}/"))
        for blob_key in keys:
            for line in self.store.get(blob_key).splitlines():
                if not line.strip():
                    continue
                entry = json.loads(line)
                last_segment_seq = max(last_segment_seq, entry["seq"])
                if entry["seq"] >= from_seq:
                    entry["payload"] = (
                        base64.b64decode(entry["payload"])
                        if entry.get("payload") is not None else None
                    )
                    # segments written before the metadata-mode change
                    # carry no "bytes" field — derive it so every
                    # replayed entry has one shape
                    entry.setdefault(
                        "bytes",
                        len(entry["payload"]) if entry["payload"] else 0,
                    )
                    yield entry
        for seq, key, payload, size in tail:
            if seq >= from_seq and seq > last_segment_seq:
                yield {"seq": seq, "key": key, "payload": payload,
                       "bytes": size}

    def sweep(self, now: Optional[float] = None) -> int:
        """Delete segments past their stream's retention; returns the
        number removed (the storage-retention sweep pattern)."""
        now = now if now is not None else time.time()
        removed = 0
        with self._lock:
            retentions = dict(self._retention)
            epochs = dict(self._segment_epoch)
        for stream, retention in retentions.items():
            remaining = 0
            if retention:
                for blob_key in self.store.list(f"{self.prefix}/{stream}/"):
                    try:
                        if now - self.store.stat_mtime(blob_key) > retention:
                            self.store.delete(blob_key)
                            removed += 1
                        else:
                            remaining += 1
                    except Exception:  # noqa: BLE001 - raced deletion
                        remaining += 1
            if remaining == 0:
                # fully swept (or never-segmented) stream: drop its
                # bookkeeping so run-scoped stream names don't grow the
                # maps — and sweep() cost — monotonically across runs.
                # The re-check holds the lock only for in-memory state
                # (the old under-lock store.list() blocked record()/
                # flush() for a full S3 round trip): pending must be
                # empty AND the segment epoch unchanged since before
                # this stream's listing — segments are only written
                # under the lock, so an unchanged epoch proves the
                # (lock-free) listing is still authoritative and no
                # fresh segment can be orphaned from retention.
                with self._lock:
                    if (not self._pending.get(stream)
                            and self._segment_epoch.get(stream, 0)
                            == epochs.get(stream, 0)):
                        self._pending.pop(stream, None)
                        self._retention.pop(stream, None)
                        self._segment_epoch.pop(stream, None)
        return removed
